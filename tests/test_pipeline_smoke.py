"""Pipeline smoke: encode(i+1) must actually hide under solve(i).

The double-buffered host pipeline's one load-bearing property is that the
scheduler's solve lock covers only the host prepare stages (sort / inject /
encode) and the non-blocking dispatch — the in-flight device/wire wait and
the decode run OFF the lock. This test pins that with the chaos harness
(testing/chaos.py): the sidecar's ``solve_bytes`` is slowed by a
deterministic ``latency_floor``, two batches are driven through ONE
TpuScheduler concurrently, and the wall clock proves the second batch's
host work ran while the first solve was in flight.

Serialized (the v2 shape: fetch under the solve lock), the two solves cost
at least 2× the floor back-to-back. Overlapped, both floors tick
concurrently and the wall stays well under 2×.
"""

import random
import threading
import time

import pytest

from tests.test_solver_service import free_port

# long enough to dwarf warm host stages (a 32-pod encode is ~ms) yet keep
# the test comfortably inside tier-1 time
FLOOR_S = 0.5


@pytest.fixture()
def sidecar_env(monkeypatch):
    """A chaos-slowed sidecar + a scheduler forced onto it.

    KARPENTER_PACKER=fused pins the device path deterministically (with a
    configured sidecar the fused route yields to it), so the router can't
    send a timed solve to the native packer mid-test."""
    monkeypatch.setenv("KARPENTER_PACKER", "fused")
    from karpenter_tpu.solver.service import SolverService, serve
    from karpenter_tpu.testing.chaos import ChaosPolicy, chaos_wrap

    policy = ChaosPolicy(
        latency_floor=FLOOR_S, methods=frozenset({"solve_bytes"})
    )
    service = chaos_wrap(SolverService(), policy)
    address = f"127.0.0.1:{free_port()}"
    server = serve(address, service=service)
    yield address, service
    server.stop(grace=1)


def test_encode_overlaps_inflight_solve(sidecar_env):
    address, service = sidecar_env
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.solver.backend import TpuScheduler
    from karpenter_tpu.testing import make_pod, make_provisioner

    catalog = instance_types(8)
    constraints = make_provisioner(solver="tpu").spec.constraints
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    sched = TpuScheduler(Cluster(), rng=random.Random(0), service_address=address)

    def batch(tag):
        return [
            make_pod(name=f"{tag}-{i}", requests={"cpu": "0.25"})
            for i in range(32)
        ]

    # warm serially: XLA compile, session open, statics — the timed round
    # must measure the pipeline, not cold starts
    warm_a = sched.solve(constraints, catalog, batch("warm-a"))
    assert sum(len(v.pods) for v in warm_a) == 32
    assert sched.last_profile.get("packer_backend") == "device"
    sched.solve(constraints, catalog, batch("warm-b"))
    # the catalog crossed the wire exactly once across both warm solves
    assert sched._remote is not None and sched._remote.session_uploads == 1
    assert service.delayed.get("solve_bytes", 0) >= 2  # chaos actually fired

    results = {}

    def run(tag):
        results[tag] = sched.solve(constraints, catalog, batch(tag))

    threads = [
        threading.Thread(target=run, args=(t,), daemon=True) for t in ("i", "i+1")
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    wall = time.perf_counter() - t0

    for tag in ("i", "i+1"):
        assert tag in results, f"solve {tag} never finished"
        assert sum(len(v.pods) for v in results[tag]) == 32
    # overlap bar: serialized execution pays >= 2 floors (1.0s); the
    # double-buffered pipeline pays ~1 floor + host work. 1.75x leaves slack
    # for a loaded CI host while still failing any re-serialization.
    assert wall < 1.75 * FLOOR_S, (
        f"two concurrent solves took {wall:.3f}s — encode(i+1) did not "
        f"overlap the in-flight solve(i) ({FLOOR_S}s floor each)"
    )
    # steady state held: no further catalog upload during the timed round
    assert sched._remote.session_uploads == 1


def test_stage_timings_split_wire_from_fetch(sidecar_env):
    """The profile attributes wire serialization separately from the
    in-flight wait, and the in-flight wait dominates under the chaos floor."""
    address, _service = sidecar_env
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.solver.backend import TpuScheduler
    from karpenter_tpu.testing import make_pod, make_provisioner

    catalog = instance_types(8)
    constraints = make_provisioner(solver="tpu").spec.constraints
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    sched = TpuScheduler(Cluster(), rng=random.Random(0), service_address=address)
    pods = [make_pod(requests={"cpu": "0.25"}) for _ in range(16)]
    sched.solve(constraints, catalog, list(pods))  # warm
    sched.solve(constraints, catalog, list(pods))
    prof = sched.last_profile
    assert prof.get("packer_backend") == "device"
    assert "wire_ser_s" in prof and "wire_deser_s" in prof
    # pack_fetch_s excludes the wire codec stages by construction
    assert prof["pack_fetch_s"] >= FLOOR_S * 0.9
    assert prof["wire_ser_s"] < FLOOR_S / 2
    assert prof["wire_deser_s"] < FLOOR_S / 2
