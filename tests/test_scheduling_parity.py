"""Scheduling parity suites: host ports (reference: suite_test.go:1756-1810),
price optimality over the 1,344-type assorted catalog (reference:
instance_selection_test.go), and binpacking behavior (reference:
suite_test.go:1813+)."""

import random

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import ContainerPort, NodeSelectorRequirement
from karpenter_tpu.cloudprovider.fake import (
    default_catalog,
    instance_types,
    instance_types_assorted,
)
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.scheduler import Scheduler
from tests.factories import make_pod, make_provisioner


def solve(pods, catalog, solver="ffd", rng=None):
    provisioner = make_provisioner(solver=solver)
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    return Scheduler(Cluster(), rng=rng or random.Random(0)).solve(
        provisioner, catalog, pods
    )


def with_port(pod, host_port=0, protocol="TCP", host_ip=""):
    pod.spec.containers[0].ports.append(
        ContainerPort(host_port=host_port, protocol=protocol, host_ip=host_ip)
    )
    return pod


class TestHostPorts:
    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_same_host_port_and_protocol_not_colocated(self, solver):
        pods = [
            with_port(make_pod(requests={"cpu": "0.5"}), host_port=80)
            for _ in range(2)
        ]
        vnodes = solve(pods, instance_types(5), solver=solver)
        assert sum(len(v.pods) for v in vnodes) == 2
        assert len(vnodes) == 2  # split across nodes

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_same_port_different_protocol_colocated(self, solver):
        pods = [
            with_port(make_pod(requests={"cpu": "0.5"}), host_port=80, protocol="TCP"),
            with_port(make_pod(requests={"cpu": "0.5"}), host_port=80, protocol="UDP"),
        ]
        vnodes = solve(pods, instance_types(5), solver=solver)
        assert sum(len(v.pods) for v in vnodes) == 2
        assert len(vnodes) == 1

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_wildcard_ip_conflicts_with_specific_ip(self, solver):
        """0.0.0.0 binds every interface: same port/protocol on a specific
        IP must not co-locate with it (kubelet semantics)."""
        pods = [
            with_port(make_pod(requests={"cpu": "0.5"}), host_port=80),  # wildcard
            with_port(make_pod(requests={"cpu": "0.5"}), host_port=80, host_ip="10.0.0.1"),
        ]
        vnodes = solve(pods, instance_types(5), solver=solver)
        assert sum(len(v.pods) for v in vnodes) == 2
        assert len(vnodes) == 2

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_different_specific_ips_colocated(self, solver):
        pods = [
            with_port(make_pod(requests={"cpu": "0.5"}), host_port=80, host_ip="10.0.0.1"),
            with_port(make_pod(requests={"cpu": "0.5"}), host_port=80, host_ip="10.0.0.2"),
        ]
        vnodes = solve(pods, instance_types(5), solver=solver)
        assert sum(len(v.pods) for v in vnodes) == 2
        assert len(vnodes) == 1

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_no_host_port_colocated(self, solver):
        pods = [
            with_port(make_pod(requests={"cpu": "0.5"}))  # containerPort only
            for _ in range(2)
        ]
        vnodes = solve(pods, instance_types(5), solver=solver)
        assert sum(len(v.pods) for v in vnodes) == 2
        assert len(vnodes) == 1


class TestPriceOptimality:
    """Always lands on the cheapest feasible type under every
    arch/os/zone/capacity-type combination (reference:
    instance_selection_test.go:37-70, shuffled assorted catalog)."""

    @pytest.fixture(scope="class")
    def catalog(self):
        catalog = instance_types_assorted()
        random.Random(5).shuffle(catalog)
        return catalog

    def cheapest_feasible(self, catalog, predicate):
        return min(
            (it for it in catalog if predicate(it)), key=lambda it: it.effective_price()
        ).effective_price()

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_unconstrained_pod_gets_cheapest_type(self, catalog, solver):
        vnodes = solve([make_pod(requests={"cpu": "0.9"})], catalog, solver=solver)
        assert len(vnodes) == 1
        chosen = vnodes[0].instance_type_options[0]
        best = self.cheapest_feasible(catalog, lambda it: it.resources.get("cpu", 0) >= 1)
        assert chosen.effective_price() == pytest.approx(best)

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    @pytest.mark.parametrize(
        "key,value",
        [
            (lbl.ARCH, lbl.ARCH_ARM64),
            (lbl.OS, "windows"),
            (lbl.TOPOLOGY_ZONE, "test-zone-2"),
            (lbl.CAPACITY_TYPE, lbl.CAPACITY_TYPE_SPOT),
        ],
    )
    def test_constrained_pod_gets_cheapest_matching_type(self, catalog, solver, key, value):
        pod = make_pod(
            requests={"cpu": "0.9"},
            node_requirements=[NodeSelectorRequirement(key=key, operator="In", values=[value])],
        )
        vnodes = solve([pod], catalog, solver=solver)
        assert len(vnodes) == 1
        chosen = vnodes[0].instance_type_options[0]

        def feasible(it):
            if it.resources.get("cpu", 0) < 1:
                return False
            if key == lbl.ARCH:
                return it.architecture == value
            if key == lbl.OS:
                return value in it.operating_systems
            if key == lbl.TOPOLOGY_ZONE:
                return value in it.zones()
            return value in it.capacity_types()

        assert chosen.effective_price() == pytest.approx(
            self.cheapest_feasible(catalog, feasible)
        )


class TestWellKnownLabels:
    """nodeSelector on every well-known label lands on a matching node
    (reference: suite_test.go well-known-labels context)."""

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    @pytest.mark.parametrize(
        "key,value,check",
        [
            (lbl.INSTANCE_TYPE, "fake-it-3", lambda it: it.name == "fake-it-3"),
            (lbl.ARCH, lbl.ARCH_AMD64, lambda it: it.architecture == "amd64"),
            (lbl.OS, "linux", lambda it: "linux" in it.operating_systems),
            (lbl.CAPACITY_TYPE, "spot", lambda it: "spot" in it.capacity_types()),
            (lbl.TOPOLOGY_ZONE, "test-zone-2", lambda it: "test-zone-2" in it.zones()),
        ],
    )
    def test_selector_lands_on_matching_type(self, solver, key, value, check):
        pod = make_pod(requests={"cpu": "0.5"}, node_selector={key: value})
        vnodes = solve([pod], instance_types(10), solver=solver)
        assert len(vnodes) == 1
        chosen = vnodes[0].instance_type_options[0]
        assert check(chosen)
        assert vnodes[0].constraints.requirements.get(key).has(value)

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_beta_label_normalized(self, solver):
        pod = make_pod(
            requests={"cpu": "0.5"},
            node_selector={"failure-domain.beta.kubernetes.io/zone": "test-zone-2"},
        )
        vnodes = solve([pod], instance_types(10), solver=solver)
        assert len(vnodes) == 1
        assert vnodes[0].constraints.requirements.zones() == {"test-zone-2"}


class TestCombinedTopology:
    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_zone_and_hostname_spread_together(self, solver):
        """Pods with BOTH constraints satisfy both: ≤ maxSkew per zone and
        one pod per hostname (reference: combined topology context)."""
        from karpenter_tpu.testing.factories import hostname_spread, zone_spread

        sel = {"app": "both"}
        pods = [
            make_pod(
                labels=sel, requests={"cpu": "0.5"},
                topology=[zone_spread(max_skew=1, labels=sel),
                          hostname_spread(max_skew=1, labels=sel)],
            )
            for _ in range(6)
        ]
        vnodes = solve(pods, instance_types(10), solver=solver)
        assert sum(len(v.pods) for v in vnodes) == 6
        # hostname skew 1 → one pod per node
        assert all(len(v.pods) == 1 for v in vnodes)
        # zone skew ≤ 1 across the three zones
        zone_counts = {}
        for v in vnodes:
            zones = v.constraints.requirements.zones()
            assert len(zones) == 1
            z = next(iter(zones))
            zone_counts[z] = zone_counts.get(z, 0) + len(v.pods)
        assert max(zone_counts.values()) - min(zone_counts.values()) <= 1


class TestPreferredNodeAffinity:
    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_heaviest_preferred_term_folded_in(self, solver):
        """The heaviest preferred term acts as a requirement at solve time
        (reference: requirements.go:55-75; relaxation removes it on retry)."""
        from karpenter_tpu.api.objects import NodeSelectorTerm, PreferredSchedulingTerm

        pod = make_pod(
            requests={"cpu": "0.5"},
            node_preferences=[
                PreferredSchedulingTerm(
                    weight=1,
                    preference=NodeSelectorTerm(match_expressions=[
                        NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In",
                                                values=["test-zone-1"])
                    ]),
                ),
                PreferredSchedulingTerm(
                    weight=50,
                    preference=NodeSelectorTerm(match_expressions=[
                        NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In",
                                                values=["test-zone-3"])
                    ]),
                ),
            ],
        )
        vnodes = solve([pod], instance_types(10), solver=solver)
        assert len(vnodes) == 1
        assert vnodes[0].constraints.requirements.zones() == {"test-zone-3"}


class TestBinpacking:
    """reference: suite_test.go:1813+ against the default fake catalog."""

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_small_pod_lands_on_smallest_instance(self, solver):
        vnodes = solve([make_pod(requests={"memory": "100M"})], default_catalog(), solver=solver)
        assert len(vnodes) == 1
        assert vnodes[0].instance_type_options[0].name == "small-instance-type"

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_multiple_small_pods_share_smallest_instance(self, solver):
        pods = [make_pod(requests={"memory": "10M"}) for _ in range(5)]
        vnodes = solve(pods, default_catalog(), solver=solver)
        assert len(vnodes) == 1
        assert len(vnodes[0].pods) == 5
        assert vnodes[0].instance_type_options[0].name == "small-instance-type"

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_new_node_opened_at_capacity(self, solver):
        # default-instance-type allots 5 pods; 12 tiny pods need 3 nodes
        pods = [make_pod(requests={"cpu": "0.01"}) for _ in range(12)]
        vnodes = solve(pods, [default_catalog()[0]], solver=solver)
        assert sum(len(v.pods) for v in vnodes) == 12
        assert len(vnodes) == 3

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_gpu_pod_gets_gpu_instance(self, solver):
        from karpenter_tpu.utils import resources as res

        pod = make_pod(requests={"cpu": "0.5", res.NVIDIA_GPU: 1})
        vnodes = solve([pod], default_catalog(), solver=solver)
        assert len(vnodes) == 1
        assert vnodes[0].instance_type_options[0].name == "nvidia-gpu-instance-type"
