"""Requirements algebra tests (mirrors requirements.go semantics and parts of
apis/provisioning/v1alpha5/suite_test.go)."""

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import (
    Affinity,
    NodeAffinity,
    NodeSelectorRequirement as R,
    NodeSelectorTerm,
    PreferredSchedulingTerm,
)
from karpenter_tpu.api.requirements import Requirements
from tests.factories import make_pod


class TestAdd:
    def test_intersects_per_key(self):
        r = Requirements.new(
            R(key="k", operator="In", values=["a", "b"]),
            R(key="k", operator="In", values=["b", "c"]),
        )
        assert r.get("k").finite_values() == frozenset({"b"})

    def test_not_in_narrows(self):
        r = Requirements.new(
            R(key="k", operator="In", values=["a", "b"]),
            R(key="k", operator="NotIn", values=["b"]),
        )
        assert r.get("k").finite_values() == frozenset({"a"})

    def test_normalizes_beta_labels(self):
        r = Requirements.new(
            R(key="beta.kubernetes.io/arch", operator="In", values=["amd64"]),
        )
        assert r.has(lbl.ARCH)
        assert not r.has("beta.kubernetes.io/arch")

    def test_ignores_region(self):
        r = Requirements.new(
            R(key=lbl.TOPOLOGY_REGION, operator="In", values=["us-east-1"]),
        )
        assert not r.has(lbl.TOPOLOGY_REGION)
        assert len(r.requirements) == 0

    def test_immutable(self):
        a = Requirements.new(R(key="k", operator="In", values=["a"]))
        b = a.add(R(key="k", operator="In", values=["b"]))
        assert a.get("k").finite_values() == frozenset({"a"})
        assert b.get("k").is_empty


class TestCompatible:
    def test_overlap_ok(self):
        prov = Requirements.new(R(key="k", operator="In", values=["a", "b"]))
        pod = Requirements.new(R(key="k", operator="In", values=["b", "c"]))
        assert prov.compatible(pod) == []

    def test_disjoint_fails(self):
        prov = Requirements.new(R(key="k", operator="In", values=["a"]))
        pod = Requirements.new(R(key="k", operator="In", values=["c"]))
        assert prov.compatible(pod)

    def test_missing_key_fails_for_in(self):
        # Pod requires k In [a]; provisioner says nothing about k → zero-value
        # set is empty → incompatible (matches reference zero-value Set).
        prov = Requirements.new()
        pod = Requirements.new(R(key="k", operator="In", values=["a"]))
        assert prov.compatible(pod)

    def test_not_in_escape_hatch(self):
        prov = Requirements.new(R(key="k", operator="DoesNotExist"))
        pod = Requirements.new(R(key="k", operator="NotIn", values=["a"]))
        # NotIn ∩ DoesNotExist = empty, but both ops are in the escape set
        assert prov.compatible(pod) == []

    def test_exists_compatible_with_in(self):
        prov = Requirements.new(R(key="k", operator="Exists"))
        pod = Requirements.new(R(key="k", operator="In", values=["a"]))
        assert prov.compatible(pod) == []


class TestFromPod:
    def test_node_selector(self):
        pod = make_pod(node_selector={lbl.TOPOLOGY_ZONE: "z-1"})
        r = Requirements.from_pod(pod)
        assert r.get(lbl.TOPOLOGY_ZONE).finite_values() == frozenset({"z-1"})

    def test_heaviest_preferred_term(self):
        pod = make_pod(
            node_preferences=[
                PreferredSchedulingTerm(
                    weight=1,
                    preference=NodeSelectorTerm(
                        match_expressions=[R(key="w", operator="In", values=["light"])]
                    ),
                ),
                PreferredSchedulingTerm(
                    weight=10,
                    preference=NodeSelectorTerm(
                        match_expressions=[R(key="w", operator="In", values=["heavy"])]
                    ),
                ),
            ]
        )
        r = Requirements.from_pod(pod)
        assert r.get("w").finite_values() == frozenset({"heavy"})

    def test_first_required_term(self):
        pod = make_pod()
        pod.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=[
                    NodeSelectorTerm(match_expressions=[R(key="t", operator="In", values=["one"])]),
                    NodeSelectorTerm(match_expressions=[R(key="t", operator="In", values=["two"])]),
                ]
            )
        )
        r = Requirements.from_pod(pod)
        assert r.get("t").finite_values() == frozenset({"one"})


class TestValidate:
    def test_infeasible(self):
        r = Requirements.new(
            R(key="k", operator="In", values=["a"]),
            R(key="k", operator="In", values=["b"]),
        )
        assert any("no feasible value" in e for e in r.validate())

    def test_feasible(self):
        r = Requirements.new(R(key="k", operator="In", values=["a"]))
        assert r.validate() == []

    def test_unsupported_operator(self):
        r = Requirements.new(R(key="k", operator="Gt", values=["1"]))
        assert any("operator" in e for e in r.validate())
