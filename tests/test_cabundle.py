"""Webhook caBundle self-reconciliation (kube/cabundle.py): rotate the CA →
the registration's clientConfig.caBundle is patched → the apiserver's TLS
verification of the webhook still succeeds (reference: knative certificates
controller, cmd/webhook/main.go:46-63)."""

import base64
import json
import os
import ssl
import urllib.request

import pytest

# The self-managed TLS stack (kube/certs.py) needs the `cryptography`
# package, which the hermetic CPU test image does not bake in. Skip (not
# fail) the whole module there so tier-1 runs green; CI's envtest/image
# jobs install cryptography and run these for real. Tracked in ROADMAP.md
# ("webhook TLS suite needs cryptography").
pytest.importorskip(
    "cryptography",
    reason="cryptography not installed: webhook TLS suite skipped "
    "(tracked in ROADMAP.md; CI envtest installs it)",
)

from karpenter_tpu.api.objects import ObjectMeta, ValidatingWebhookConfiguration
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.kube.cabundle import CABundleReconciler
from karpenter_tpu.kube.certs import ensure_serving_cert
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.webhook import Webhook, serve


def _registration(name: str, kind_suffix: str, bundle: str) -> ValidatingWebhookConfiguration:
    return ValidatingWebhookConfiguration(
        metadata=ObjectMeta(name=name, namespace=""),
        webhooks=[
            {
                "name": name,
                "admissionReviewVersions": ["v1"],
                "sideEffects": "None",
                "failurePolicy": "Fail",
                "clientConfig": {
                    "service": {
                        "name": "karpenter-tpu-webhook",
                        "namespace": "karpenter",
                        "path": f"/{kind_suffix}",
                        "port": 443,
                    },
                    "caBundle": bundle,
                },
                "rules": [
                    {
                        "apiGroups": ["karpenter.sh"],
                        "apiVersions": ["v1alpha5"],
                        "operations": ["CREATE", "UPDATE"],
                        "resources": ["provisioners"],
                    }
                ],
            }
        ],
    )


class TestCABundleReconciler:
    def test_stale_bundles_patched_fields_preserved(self, tmp_path):
        cert_dir = str(tmp_path / "certs")
        _, _, ca_path = ensure_serving_cert(cert_dir, ["svc", "svc.ns"])
        cluster = Cluster()
        cluster.create(
            "validatingwebhookconfigurations",
            _registration("validation.webhook.karpenter.sh", "validate-resource", "c3RhbGU="),
        )
        cluster.create(
            "mutatingwebhookconfigurations",
            _registration("defaulting.webhook.karpenter.sh", "default-resource", "c3RhbGU="),
        )
        rec = CABundleReconciler(
            cluster,
            [
                ("validatingwebhookconfigurations", "validation.webhook.karpenter.sh"),
                ("mutatingwebhookconfigurations", "defaulting.webhook.karpenter.sh"),
            ],
            ca_path,
        )
        assert rec.reconcile_once() == 2
        want = base64.b64encode(open(ca_path, "rb").read()).decode()
        for kind, name in rec.configs:
            cfg = cluster.get(kind, name, namespace="")
            w = cfg.webhooks[0]
            assert w["clientConfig"]["caBundle"] == want
            # every other field survived the list-replacing merge patch
            assert w["rules"][0]["apiGroups"] == ["karpenter.sh"]
            assert w["admissionReviewVersions"] == ["v1"]
            assert w["clientConfig"]["service"]["port"] == 443
        # steady state: nothing to do
        assert rec.reconcile_once() == 0

    def test_rotation_updates_registration_and_admission_verifies(self, tmp_path):
        cert_dir = str(tmp_path / "certs")
        cert, key, ca_path = ensure_serving_cert(cert_dir, ["localhost"])
        cluster = Cluster()
        name = "validation.webhook.karpenter.sh"
        cluster.create(
            "validatingwebhookconfigurations",
            _registration(name, "validate-resource",
                          base64.b64encode(open(ca_path, "rb").read()).decode()),
        )
        rec = CABundleReconciler(
            cluster, [("validatingwebhookconfigurations", name)], ca_path
        )
        assert rec.reconcile_once() == 0  # bundle current

        # force a CA rotation: remove the CA pair so ensure regenerates it
        os.remove(os.path.join(cert_dir, "ca.key"))
        os.remove(os.path.join(cert_dir, "ca.crt"))
        os.remove(os.path.join(cert_dir, "tls.crt"))  # leaf must be re-signed
        cert, key, ca_path2 = ensure_serving_cert(cert_dir, ["localhost"])
        new_ca = open(ca_path2, "rb").read()
        stale = cluster.get("validatingwebhookconfigurations", name, namespace="")
        assert stale.webhooks[0]["clientConfig"]["caBundle"] != base64.b64encode(new_ca).decode()

        assert rec.reconcile_once() == 1
        cfg = cluster.get("validatingwebhookconfigurations", name, namespace="")
        patched = base64.b64decode(cfg.webhooks[0]["clientConfig"]["caBundle"])
        assert patched == new_ca

        # the apiserver's view: TLS-verify the webhook using EXACTLY the
        # patched bundle, then POST an AdmissionReview
        server = serve(
            Webhook(FakeCloudProvider(instance_types(4))),
            "127.0.0.1:0", tls_cert=cert, tls_key=key,
        )
        try:
            port = server.server_address[1]
            ctx = ssl.create_default_context(cadata=patched.decode())
            review = {
                "kind": "AdmissionReview",
                "apiVersion": "admission.k8s.io/v1",
                "request": {
                    "uid": "u1",
                    "object": {
                        "apiVersion": "karpenter.sh/v1alpha5",
                        "kind": "Provisioner",
                        "metadata": {"name": "default"},
                        "spec": {"solver": "ffd"},
                    },
                },
            }
            req = urllib.request.Request(
                f"https://localhost:{port}/validate-resource",
                data=json.dumps(review).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, context=ctx, timeout=10) as resp:
                body = json.loads(resp.read())
            assert body["response"]["uid"] == "u1"
            assert body["response"]["allowed"] is True
        finally:
            server.shutdown()

    def test_reconciles_over_real_apiserver_boundary(self, tmp_path):
        from karpenter_tpu.kube.apiserver import ApiCluster
        from karpenter_tpu.kube.testserver import TestApiServer

        cert_dir = str(tmp_path / "certs")
        _, _, ca_path = ensure_serving_cert(cert_dir, ["svc"])
        server = TestApiServer()
        server.start()
        try:
            name = "validation.webhook.karpenter.sh"
            server.cluster.create(
                "validatingwebhookconfigurations",
                _registration(name, "validate-resource", "c3RhbGU="),
            )
            # no informer start: the reconciler reads live + merge-patches,
            # matching the webhook RBAC (get/update/patch only)
            client = ApiCluster(server.url)
            rec = CABundleReconciler(
                client, [("validatingwebhookconfigurations", name)], ca_path
            )
            assert rec.reconcile_once() == 1
            cfg = server.cluster.get("validatingwebhookconfigurations", name, namespace="")
            want = base64.b64encode(open(ca_path, "rb").read()).decode()
            assert cfg.webhooks[0]["clientConfig"]["caBundle"] == want
            assert cfg.webhooks[0]["rules"][0]["resources"] == ["provisioners"]
        finally:
            server.stop()
