"""The decision audit log (docs/decisions.md): per-round records, the
capped replayable ring, the unschedulable event loop, debug endpoints on
both health servers, fleet indexing, and the offline replay tool."""

from __future__ import annotations

import json
import os
import random
import time
import urllib.request

import pytest

from karpenter_tpu import metrics, obs
from karpenter_tpu.cloudprovider.fake import (
    FakeCloudProvider,
    instance_types,
)
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.kube.events import DECISION_ID_ANNOTATION
from karpenter_tpu.obs import decisions as dec
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.solver import explain as expl
from tests.factories import make_pod, make_provisioner


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset_for_tests()
    dec.set_enabled(True)
    yield
    obs.reset_for_tests()


def solved_context(pods, catalog=None, n_types=10):
    """One accelerated solve through the production facade, returning
    (nodes, consumed decision context)."""
    catalog = catalog or instance_types(n_types)
    prov = make_provisioner(solver="tpu")
    c = prov.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    sched = Scheduler(Cluster(), rng=random.Random(1))
    nodes = sched.solve(prov, catalog, pods)
    return nodes, sched.last_decision_context()


def stuck_pods(n_ok=3, n_stuck=1):
    pods = [make_pod(requests={"cpu": "0.5"}) for _ in range(n_ok)]
    pods += [
        make_pod(name=f"stuck-{i}", requests={"cpu": "100000"})
        for i in range(n_stuck)
    ]
    return pods


def _counter(metric, **labels):
    child = metric.labels(**labels) if labels else metric
    return child._value.get()


class TestDecisionRecord:
    def test_round_recorded_with_attribution_and_provenance(self):
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        log = dec.DecisionLog()
        rec = log.record_round(
            "default", pods, nodes, context=ctx, trace_id="t-1",
            state={"fenced": False},
        )
        assert rec["pods_considered"] == 4
        assert rec["unschedulable_count"] == 1
        assert rec["route"] in ("native", "device")
        assert rec["trace_id"] == "t-1"
        v = rec["unschedulable"][0]
        assert v["pod"].endswith("stuck-0")
        assert v["top_reason"] == expl.REASON_RESOURCE
        assert v["reasons"][expl.REASON_RESOURCE] == 10
        # lazy listings materialize on read
        out = log.recent(limit=1)[0]
        assert out["packing"], "chosen packing must be listed"
        assert out["packing"][0]["instance_type"]
        assert out["pod_keys"]

    def test_explain_lookup_unplaced_and_placed(self):
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        log = dec.DecisionLog()
        log.record_round("default", pods, nodes, context=ctx)
        bad = log.explain("stuck-0")
        assert bad["placed"] is False
        assert bad["top_reason"] == expl.REASON_RESOURCE
        assert bad["candidates"]
        good = log.explain(pods[0].metadata.name)
        assert good["placed"] is True
        assert good["instance_type"]
        assert log.explain("no-such-pod") is None

    def test_disabled_plane_records_nothing(self):
        dec.set_enabled(False)
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        log = dec.DecisionLog()
        assert log.record_round("default", pods, nodes, context=ctx) is None
        assert log.recent() == []

    def test_ffd_context_falls_back_to_key_difference(self):
        pods = stuck_pods()
        nodes, _ = solved_context(pods)
        log = dec.DecisionLog()
        rec = log.record_round("default", pods, nodes, context={})
        assert rec["unschedulable_count"] == 1
        assert rec["unschedulable"] == []  # no tensors, no attribution

    def test_streak_reuse_and_refresh(self):
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        log = dec.DecisionLog()
        r1 = log.record_round("default", pods, nodes, context=ctx)
        v1 = r1["unschedulable"][0]
        # mid-streak rounds reuse the cached verdict object
        r2 = log.record_round("default", pods, nodes, context=ctx)
        assert r2["unschedulable"][0] is v1
        assert log.failure_streak(v1["pod"]) == 2

    def test_placement_resets_streak(self):
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        log = dec.DecisionLog()
        log.record_round("default", pods, nodes, context=ctx)
        assert log.failure_streak(ctx["batch"].pods[-1].key) >= 0
        stuck_key = next(
            p.key for p in pods if p.metadata.name == "stuck-0"
        )
        assert log.failure_streak(stuck_key) == 1
        # a later round where the pod PLACES resets the streak
        ok_pods = [p for p in pods if p.metadata.name != "stuck-0"]
        ok_pods.append(make_pod(name="stuck-0", requests={"cpu": "0.5"}))
        nodes2, ctx2 = solved_context(ok_pods)
        log.record_round("default", ok_pods, nodes2, context=ctx2)
        assert log.failure_streak(stuck_key) == 0


class TestDecisionRing:
    def test_ring_cap_evicts_and_counts(self, tmp_path):
        before = _counter(metrics.DECISIONS_DROPPED, reason="evicted")
        log = dec.DecisionLog(
            directory=str(tmp_path), cap=3, write_interval=0.0
        )
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        for _ in range(6):
            log.record_round("default", pods, nodes, context=ctx)
            assert log.flush(10.0)
        names = [n for n in os.listdir(tmp_path) if n.endswith(".json")]
        assert len(names) == 3
        assert _counter(metrics.DECISIONS_DROPPED, reason="evicted") >= before + 3
        # replay sidecars are pruned with their records
        stems = {n[:-len(".json")] for n in names}
        for n in os.listdir(tmp_path):
            if n.endswith(".npz"):
                assert n[:-len(".npz")] in stems

    def test_full_disk_never_fails_the_round(self, tmp_path, monkeypatch):
        log = dec.DecisionLog(directory=str(tmp_path), write_interval=0.0)
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)

        def enospc(*a, **k):
            raise OSError(28, "No space left on device")

        # every disk touch fails (chmod tricks don't bind when the test
        # runs as root); the reconcile-side contract must hold anyway
        monkeypatch.setattr(dec.np, "savez", enospc)
        before = _counter(metrics.DECISIONS_DROPPED, reason="write_failed")
        rec = log.record_round("default", pods, nodes, context=ctx)
        assert rec is not None  # the round's record still exists
        assert log.flush(10.0)
        assert (
            _counter(metrics.DECISIONS_DROPPED, reason="write_failed")
            == before + 1
        )
        assert log.recent(limit=1)  # memory ring intact

    def test_write_interval_thins_disk_not_memory(self, tmp_path):
        log = dec.DecisionLog(directory=str(tmp_path), write_interval=3600.0)
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        for _ in range(5):
            log.record_round("default", pods, nodes, context=ctx)
        assert log.flush(10.0)
        files = [n for n in os.listdir(tmp_path) if n.endswith(".json")]
        assert len(files) == 1  # one write per interval
        assert len(log.recent(limit=10)) == 5  # memory keeps every round

    def test_recorded_counter_and_explain_histogram(self):
        before = _counter(metrics.DECISIONS_RECORDED)
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        log = dec.DecisionLog()
        log.record_round("default", pods, nodes, context=ctx)
        assert _counter(metrics.DECISIONS_RECORDED) == before + 1

    def test_unschedulable_gauge_by_reason(self):
        pods = stuck_pods(n_stuck=2)
        nodes, ctx = solved_context(pods)
        log = dec.DecisionLog()
        log.record_round("default", pods, nodes, context=ctx)
        assert (
            metrics.PODS_UNSCHEDULABLE.labels(
                reason=expl.REASON_RESOURCE
            )._value.get() == 2
        )


class TestReplay:
    def test_replay_reproduces_persisted_assignment_bit_exact(self, tmp_path):
        from karpenter_tpu.solver.native import native_available
        from tools import replay_decision as rd

        if not native_available(wait=240.0):
            pytest.skip("native packer unavailable")
        log = dec.DecisionLog(directory=str(tmp_path), write_interval=0.0)
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        rec = log.record_round("default", pods, nodes, context=ctx)
        assert log.flush(10.0)
        path = rd.find_record(str(tmp_path))
        assert path is not None
        verdict = rd.replay(rd.load_record(path), record_path=path)
        assert verdict["ok"] is True
        assert verdict["decision_id"] == rec["id"]
        assert verdict["replay_unschedulable"] == 1
        # the CLI entry agrees
        assert rd.main(["--decision-dir", str(tmp_path)]) == 0

    def test_replay_detects_a_divergent_assignment(self, tmp_path):
        from karpenter_tpu.solver.native import native_available
        from tools import replay_decision as rd

        if not native_available(wait=240.0):
            pytest.skip("native packer unavailable")
        log = dec.DecisionLog(directory=str(tmp_path), write_interval=0.0)
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        # corrupt the served assignment: replay must catch the lie
        ctx["assignment"] = ctx["assignment"].copy()
        ctx["assignment"][0] = 7
        log.record_round("default", pods, nodes, context=ctx)
        assert log.flush(10.0)
        path = rd.find_record(str(tmp_path))
        verdict = rd.replay(rd.load_record(path), record_path=path)
        assert verdict["ok"] is False
        assert "differs" in verdict["diff"]
        assert rd.main(["--decision-dir", str(tmp_path)]) == 1

    def test_memory_only_record_is_not_replayable(self):
        from tools import replay_decision as rd

        with pytest.raises(ValueError):
            rd.replay({"id": "d-x"}, record_path="")


class TestKubernetesLoop:
    def _provision_rounds(self, rounds, threshold=3):
        cluster = Cluster()
        catalog = instance_types(10)
        provider = FakeCloudProvider(catalog)
        controller = ProvisioningController(
            cluster, provider, start_workers=False,
            unschedulable_event_rounds=threshold,
        )
        prov = make_provisioner(solver="tpu")
        cluster.create("provisioners", prov)
        controller.apply(prov)
        worker = controller.workers[prov.name]
        worker.batcher.idle_duration = 0.01
        pods = stuck_pods()
        for p in pods:
            cluster.create("pods", p)
        for _ in range(rounds):
            for p in pods:
                worker.batcher.add(p)
            worker.provision_once()
        controller.stop()
        return cluster, worker

    def test_pod_unschedulable_event_after_n_rounds(self):
        cluster, worker = self._provision_rounds(3, threshold=3)
        events = [
            e for e in cluster.list("events", None)
            if e.reason == "PodUnschedulable"
        ]
        assert events, "threshold crossed: the Warning event must exist"
        ev = events[0]
        assert ev.type == "Warning"
        assert ev.involved_name == "stuck-0"
        assert expl.REASON_RESOURCE in ev.message
        # the decision id rides the annotation (karplint event-decision-id)
        assert ev.metadata.annotations[DECISION_ID_ANNOTATION].startswith("d-")
        assert worker.last_decision_id.startswith("d-")

    def test_repeated_rounds_aggregate_into_one_event(self):
        """The event message is streak-count-free by design: rounds past
        the threshold BUMP the existing Event (EventRecorder aggregates
        on the message) instead of minting a fresh apiserver object per
        round — one stuck pod must not become an event storm."""
        cluster, _ = self._provision_rounds(6, threshold=3)
        events = [
            e for e in cluster.list("events", None)
            if e.reason == "PodUnschedulable"
        ]
        assert len(events) == 1
        assert events[0].count >= 3  # rounds 3..6 bumped, never re-created
        assert "3+" in events[0].message

    def test_deleted_pod_stops_eventing_and_drops_from_tracker(self):
        """A pod deleted while stuck never re-enters a batch to reset its
        streak — the emit path's existence check must drop the ghost
        instead of eventing a nonexistent object every round forever."""
        cluster = Cluster()
        log = obs.decision_log()
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        stuck_key = next(p.key for p in pods if p.metadata.name == "stuck-0")
        for _ in range(3):
            log.record_round("default", pods, nodes, context=ctx)
        assert log.failure_streak(stuck_key) == 3
        # the pod does NOT exist in this cluster (deleted while stuck)
        emitted = log.emit_unschedulable_events(cluster, threshold=3)
        assert emitted == 0
        assert log.failure_streak(stuck_key) == 0
        assert not [
            e for e in cluster.list("events", None)
            if e.reason == "PodUnschedulable"
        ]

    def test_no_event_below_threshold(self):
        cluster, _ = self._provision_rounds(2, threshold=3)
        assert not [
            e for e in cluster.list("events", None) if e.reason == "PodUnschedulable"
        ]

    def test_round_span_carries_decision_id(self):
        self._provision_rounds(1)
        trees = obs.exporter().trees()
        rounds = [t for t in trees if t.get("name") == "provision.round"]
        assert rounds
        assert rounds[-1]["attrs"]["decision_id"].startswith("d-")

    def test_admission_failure_classified_as_taint(self):
        from karpenter_tpu.api.objects import Taint

        log = obs.decision_log()
        pod = make_pod(requests={"cpu": "1"})
        prov = make_provisioner(taints=[Taint(key="dedicated", value="x")])
        errs = prov.spec.constraints.validate_pod(pod)
        assert errs
        verdict = log.note_admission_failure(pod, errs)
        assert verdict["top_reason"] == expl.REASON_TAINT
        assert log.failure_streak(pod.key) == 1

    def test_selection_feed_emits_event_at_threshold(self):
        from karpenter_tpu.api.objects import Taint
        from karpenter_tpu.controllers.selection import (
            NoProvisionerMatched,
            SelectionController,
        )

        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(5))
        controller = ProvisioningController(
            cluster, provider, start_workers=False,
            unschedulable_event_rounds=2,
        )
        prov = make_provisioner(taints=[Taint(key="dedicated", value="x")])
        cluster.create("provisioners", prov)
        controller.apply(prov)
        selection = SelectionController(cluster, controller, wait=False)
        pod = make_pod(requests={"cpu": "1"})
        cluster.create("pods", pod)
        for _ in range(2):
            with pytest.raises(NoProvisionerMatched):
                selection.select_provisioner(pod)
        controller.stop()
        events = [
            e for e in cluster.list("events", None) if e.reason == "PodUnschedulable"
        ]
        assert events
        assert "tolerate" in events[0].message
        assert expl.REASON_TAINT in events[0].message


class TestDebugSurface:
    def test_payload_builders(self):
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        obs.decision_log().record_round(
            "default", pods, nodes, context=ctx, trace_id="t-9"
        )
        body = obs.debug_decisions_payload("limit=5")
        assert len(body["decisions"]) == 1
        assert body["decisions"][0]["trace_id"] == "t-9"
        assert obs.debug_decisions_payload("provisioner=nope")["decisions"] == []
        ex = obs.debug_explain_payload("pod=stuck-0")
        assert ex["explain"]["top_reason"] == expl.REASON_RESOURCE
        assert ex["explain"]["consecutive_failures"] == 1
        assert obs.debug_explain_payload("")["explain"] is None
        # both payloads must be JSON-serializable end to end
        json.dumps(body)
        json.dumps(ex)

    def test_sidecar_health_server_serves_decisions_and_explain(self):
        from karpenter_tpu.solver.service import SolverService, _serve_health

        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        obs.decision_log().record_round("default", pods, nodes, context=ctx)
        service = SolverService()
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        httpd = _serve_health(service, port)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/decisions?limit=2", timeout=5
            ) as resp:
                doc = json.loads(resp.read())
            assert doc["decisions"][0]["provisioner"] == "default"
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/explain?pod=stuck-0", timeout=5
            ) as resp:
                doc = json.loads(resp.read())
            assert doc["explain"]["top_reason"] == expl.REASON_RESOURCE
        finally:
            httpd.shutdown()

    def test_controller_health_server_parity(self):
        """The controller server routes through the same obs.debug_*
        helpers (karplint enforces it); serve one real runtime's health
        endpoint and read both bodies."""
        from karpenter_tpu.main import build_runtime, _serve_endpoints
        from karpenter_tpu.options import Options
        import socket

        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        obs.decision_log().record_round("default", pods, nodes, context=ctx)
        for s in (socket.socket(), socket.socket()):
            s.close()
        with socket.socket() as s1, socket.socket() as s2:
            s1.bind(("127.0.0.1", 0))
            s2.bind(("127.0.0.1", 0))
            mport, hport = s1.getsockname()[1], s2.getsockname()[1]
        options = Options(metrics_port=mport, health_probe_port=hport)
        runtime = build_runtime(options, start_workers=False)
        try:
            _serve_endpoints(runtime)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{hport}/debug/decisions", timeout=5
            ) as resp:
                doc = json.loads(resp.read())
            assert doc["decisions"][0]["provisioner"] == "default"
            with urllib.request.urlopen(
                f"http://127.0.0.1:{hport}/debug/explain?pod=stuck-0",
                timeout=5,
            ) as resp:
                doc = json.loads(resp.read())
            assert doc["explain"]["pod"].endswith("stuck-0")
        finally:
            runtime.stop()


class TestFleetIndexing:
    def test_member_payload_ships_decision_summaries(self):
        from karpenter_tpu.obs.collector import member_payload

        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        obs.decision_log().record_round(
            "default", pods, nodes, context=ctx, trace_id="t-f"
        )
        payload = member_payload("replica-a", "controller")
        assert payload["decisions"]
        d = payload["decisions"][0]
        assert d["unschedulable_count"] == 1
        assert d["top_reasons"] == [expl.REASON_RESOURCE]
        json.dumps(payload["decisions"])

    def test_dead_members_decisions_survive_in_fleet_payload(self, tmp_path):
        """A member flushes its decisions to the file backend and DIES;
        the collector still indexes its rounds in /debug/fleet."""
        from karpenter_tpu.obs.collector import (
            FileTelemetryBackend,
            TelemetryCollector,
        )

        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        obs.decision_log().record_round("default", pods, nodes, context=ctx)
        from karpenter_tpu.obs.collector import member_payload

        backend = FileTelemetryBackend(str(tmp_path), identity="dead-replica")
        backend.publish(member_payload("dead-replica", "controller"))
        # the dead replica's process state is gone; only the file remains
        obs.reset_for_tests()
        dec.set_enabled(True)
        collector = TelemetryCollector(
            [FileTelemetryBackend(str(tmp_path), identity="survivor")]
        )
        collector.refresh()
        fleet = collector.fleet_payload()
        assert fleet["decisions"]
        assert fleet["decisions"][0]["member"] == "dead-replica"
        assert fleet["decisions"][0]["unschedulable_count"] == 1


class TestWriterLifecycle:
    def test_replaced_log_writer_thread_exits(self, tmp_path):
        """configure_decisions replaces the log; the old writer must
        drain and EXIT instead of surviving as an immortal once-a-second
        thread pinning the old memory ring."""
        log = obs.configure_decisions(str(tmp_path), write_interval=0.0)
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        log.record_round("default", pods, nodes, context=ctx)
        assert log.flush(10.0)
        writer = log._writer
        assert writer is not None and writer.is_alive()
        obs.configure_decisions("")  # replaces + closes the old log
        writer.join(timeout=5.0)
        assert not writer.is_alive()

    def test_reader_gets_a_stable_copy_not_the_live_dict(self, tmp_path, monkeypatch):
        """recent() returns copies taken under the lock: the async
        writer later inserts `path` into the live record, and a reader
        json-serializing the live dict at that moment would crash."""
        log = dec.DecisionLog(directory=str(tmp_path), write_interval=0.0)
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        # gate the writer so the snapshot deterministically precedes the
        # disk write (on a warm machine the write can win the race)
        import threading

        release = threading.Event()
        real_write = log._write_now

        def gated(*a, **k):
            release.wait(timeout=10.0)
            return real_write(*a, **k)

        monkeypatch.setattr(log, "_write_now", gated)
        rec = log.record_round("default", pods, nodes, context=ctx)
        snapshot = log.recent(limit=1)[0]
        assert snapshot is not rec
        release.set()
        assert log.flush(10.0)
        assert "path" in rec  # the live record gained the key...
        assert "path" not in snapshot  # ...the reader's copy did not move


class TestQueueContainment:
    def test_full_write_queue_drops_and_counts(self, tmp_path, monkeypatch):
        log = dec.DecisionLog(directory=str(tmp_path), write_interval=0.0)
        pods = stuck_pods()
        nodes, ctx = solved_context(pods)
        # wedge the writer so the queue can only fill
        monkeypatch.setattr(
            log, "_write_now", lambda *a, **k: time.sleep(0.2)
        )
        before = _counter(metrics.DECISIONS_DROPPED, reason="queue_full")
        for _ in range(dec.MAX_WRITE_QUEUE + 4):
            log.record_round("default", pods, nodes, context=ctx)
        assert (
            _counter(metrics.DECISIONS_DROPPED, reason="queue_full") > before
        )
        # every round's record still landed in memory
        assert len(log.recent(limit=50)) == dec.MAX_WRITE_QUEUE + 4
