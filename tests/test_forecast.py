"""Arrival-rate forecasting unit tests (karpenter_tpu/forecast/): the
models, the per-shard bucket accumulator, the tracer finish-hook, and the
obs wiring (configure/shutdown + /debug/forecast payload)."""

import math

import pytest

from karpenter_tpu import obs
from karpenter_tpu.forecast import (
    DEFAULT_HORIZON_S,
    MAX_HORIZON_S,
    MIN_HORIZON_S,
    MODEL_EWMA,
    MODEL_HOLT_WINTERS,
    ArrivalForecaster,
    Ewma,
    HoltWinters,
    ShardForecast,
    build_model,
)
from karpenter_tpu.obs.trace import Span


def _span(name, **attrs):
    """A bare finished span — the hook only reads .name and .attrs."""
    return Span(name=name, trace_id="t" * 32, span_id="s" * 16,
                parent_id=None, parent=None, attrs=attrs)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestEwma:
    def test_cold_start_adopts_first_value(self):
        m = Ewma(alpha=0.3)
        assert m.predict() == 0.0
        m.update(10.0)
        assert m.level == 10.0
        assert m.predict() == 10.0
        assert m.std() == 0.0

    def test_converges_toward_series(self):
        m = Ewma(alpha=0.5)
        for _ in range(20):
            m.update(4.0)
        assert m.predict() == pytest.approx(4.0)

    def test_variance_widens_on_surprise_then_decays(self):
        m = Ewma(alpha=0.5)
        for _ in range(10):
            m.update(2.0)
        calm = m.std()
        m.update(50.0)
        assert m.std() > calm
        spiked = m.std()
        for _ in range(30):
            m.update(2.0)
        assert m.std() < spiked

    def test_prediction_is_flat_regardless_of_steps(self):
        m = Ewma()
        m.update(3.0)
        m.update(5.0)
        assert m.predict(1) == m.predict(100)

    @pytest.mark.parametrize("alpha", [0.0, -0.1, 1.5])
    def test_alpha_validation(self, alpha):
        with pytest.raises(ValueError):
            Ewma(alpha=alpha)


class TestHoltWinters:
    def test_cold_start(self):
        m = HoltWinters(season_len=4)
        assert m.predict() == 0.0
        m.update(7.0)
        assert m.level == 7.0

    def test_learns_seasonal_shape_better_than_ewma(self):
        """A strict square wave with period == season_len: after a few
        seasons HW predicts the NEXT phase's value; EWMA can only sit in
        the middle."""
        season = [0.0, 0.0, 10.0, 10.0]
        hw = HoltWinters(alpha=0.3, beta=0.0, gamma=0.5, season_len=4)
        ew = Ewma(alpha=0.3)
        series = season * 12
        for v in series:
            hw.update(v)
            ew.update(v)
        # next value is series[48 % 4] == 0.0
        assert abs(hw.predict(1) - 0.0) < abs(ew.predict(1) - 0.0)

    def test_predict_never_negative(self):
        m = HoltWinters(alpha=0.9, beta=0.9, season_len=2)
        m.update(10.0)
        m.update(0.0)
        m.update(0.0)
        assert m.predict(5) >= 0.0

    def test_trend_tracks_ramp(self):
        m = HoltWinters(alpha=0.5, beta=0.5, gamma=0.0, season_len=2)
        for v in range(1, 20):
            m.update(float(v))
        assert m.trend > 0.0
        assert m.predict(1) > m.level

    @pytest.mark.parametrize(
        "kwargs",
        [{"alpha": 0.0}, {"beta": -0.1}, {"gamma": 2.0}, {"season_len": 1}],
    )
    def test_validation(self, kwargs):
        with pytest.raises(ValueError):
            HoltWinters(**kwargs)


class TestBuildModel:
    def test_grammar(self):
        assert isinstance(build_model(MODEL_EWMA), Ewma)
        assert isinstance(build_model(MODEL_HOLT_WINTERS, season_len=6),
                          HoltWinters)
        with pytest.raises(ValueError):
            build_model("arima")


class TestShardForecast:
    def test_rate_zero_until_first_closed_bucket(self):
        s = ShardForecast(bucket_s=10.0)
        s.observe(5, now=0.0)
        point, upper = s.rate(now=5.0)  # same bucket still open
        assert point == 0.0 and upper == 0.0

    def test_closed_bucket_feeds_rate(self):
        s = ShardForecast(bucket_s=10.0, alpha=1.0)
        s.observe(20, now=0.0)
        point, upper = s.rate(now=10.0)  # bucket closed: 20 pods / 10s
        assert point == pytest.approx(2.0)
        assert upper >= point

    def test_silence_decays_rate(self):
        s = ShardForecast(bucket_s=10.0, alpha=0.5)
        s.observe(20, now=0.0)
        busy, _ = s.rate(now=10.0)
        quiet, _ = s.rate(now=60.0)  # four empty buckets replayed
        assert 0.0 <= quiet < busy

    def test_long_gap_resets_without_replay_storm(self):
        s = ShardForecast(bucket_s=1.0, alpha=0.5)
        s.observe(10, now=0.0)
        s.rate(now=1.0)
        obs_before = s.model.observations
        # a week of silence: bounded number of updates, rate near zero
        point, _ = s.rate(now=7 * 24 * 3600.0)
        assert s.model.observations <= obs_before + 2
        assert point == pytest.approx(0.0, abs=1e-9)

    def test_total_arrivals_accumulates(self):
        s = ShardForecast(bucket_s=10.0)
        s.observe(3, now=0.0)
        s.observe(4, now=1.0)
        assert s.total_arrivals == 7

    def test_negative_counts_clamped(self):
        s = ShardForecast(bucket_s=10.0)
        s.observe(-5, now=0.0)
        assert s.total_arrivals == 0
        point, _ = s.rate(now=10.0)
        assert point == 0.0


class TestArrivalForecaster:
    def _engine(self, **kwargs):
        kwargs.setdefault("bucket_s", 10.0)
        kwargs.setdefault("clock", FakeClock())
        return ArrivalForecaster(**kwargs)

    def test_all_zero_before_any_round(self):
        eng = self._engine()
        out = eng.predict("nobody")
        assert out["rate_point_per_s"] == 0.0
        assert out["rate_upper_per_s"] == 0.0
        assert out["predicted_pods_upper"] == 0.0
        assert out["observations"] == 0

    def test_round_spans_feed_the_shard(self):
        clock = FakeClock()
        eng = self._engine(clock=clock, alpha=1.0)
        eng(_span("provision.round", provisioner="p1", batch=30))
        clock.t = 10.0  # close the bucket
        out = eng.predict("p1")
        assert out["rate_point_per_s"] == pytest.approx(3.0)
        assert out["predicted_pods"] == pytest.approx(3.0 * out["horizon_s"])
        assert out["rate_upper_per_s"] >= out["rate_point_per_s"]
        assert eng.provisioners() == ["p1"]

    def test_rounds_shard_per_provisioner(self):
        clock = FakeClock()
        eng = self._engine(clock=clock, alpha=1.0)
        eng(_span("provision.round", provisioner="a", batch=10))
        eng(_span("provision.round", provisioner="b", batch=40))
        clock.t = 10.0
        assert eng.predict("b")["rate_point_per_s"] > eng.predict("a")[
            "rate_point_per_s"
        ]

    def test_hook_ignores_malformed_spans(self):
        eng = self._engine()
        eng(_span("provision.round", batch=5))  # no provisioner
        eng(_span("provision.round", provisioner="p", batch="not-a-number"))
        eng(_span("node.ready", since_creation_s="nan?"))
        eng(_span("node.ready", since_creation_s=-3))
        eng(_span("some.other.span", provisioner="p", batch=99))
        assert eng.provisioners() == []
        assert eng.horizon_s() == DEFAULT_HORIZON_S

    def test_horizon_defaults_then_tracks_ready_p99(self):
        eng = self._engine(default_horizon_s=45.0)
        assert eng.horizon_s() == 45.0
        for _ in range(50):
            eng(_span("node.ready", since_creation_s=120.0))
        h = eng.horizon_s()
        # log-linear sketch: ~2.5% bucket error around the true 120s
        assert h == pytest.approx(120.0, rel=0.1)

    def test_horizon_clamps(self):
        eng = self._engine()
        for _ in range(20):
            eng(_span("node.ready", since_creation_s=0.01))
        assert eng.horizon_s() == MIN_HORIZON_S
        for _ in range(400):
            eng(_span("node.ready", since_creation_s=86400.0))
        assert eng.horizon_s() == MAX_HORIZON_S

    def test_pods_per_node_floor_and_ewma(self):
        eng = self._engine()
        assert eng.pods_per_node() == 1.0
        eng(_span("provision.round", provisioner="p", batch=12, nodes=3))
        assert eng.pods_per_node() == pytest.approx(4.0)
        eng(_span("provision.round", provisioner="p", batch=1, nodes=4))
        assert eng.pods_per_node() >= 1.0  # never below one pod per node

    def test_snapshot_and_panel_shapes(self):
        clock = FakeClock()
        eng = self._engine(clock=clock)
        eng(_span("provision.round", provisioner="p", batch=5))
        clock.t = 10.0
        snap = eng.snapshot()
        assert snap["model"] == MODEL_EWMA
        assert "p" in snap["shards"]
        assert snap["shards"]["p"]["observations"] == 1
        panel = eng.panel()
        assert set(panel) == {"horizon_s", "shards"}
        assert "p" in panel["shards"]


class TestObsWiring:
    def test_configure_installs_tracer_hook(self):
        eng = obs.configure_forecast(bucket_s=10.0, clock=FakeClock())
        try:
            assert obs.forecaster() is eng
            with obs.tracer().span(
                "provision.round", attrs={"provisioner": "wired", "batch": 4}
            ):
                pass
            assert eng.provisioners() == ["wired"]
            payload = obs.debug_forecast_payload()
            assert "wired" in payload["forecast"]["shards"]
        finally:
            obs.shutdown_forecast(eng)
        assert obs.forecaster() is None
        assert obs.debug_forecast_payload() == {"forecast": {}}

    def test_shutdown_is_ownership_checked(self):
        eng1 = obs.configure_forecast(bucket_s=10.0)
        eng2 = obs.configure_forecast(bucket_s=10.0)
        try:
            obs.shutdown_forecast(eng1)  # stale owner: must not detach eng2
            assert obs.forecaster() is eng2
        finally:
            obs.shutdown_forecast(eng2)
        assert obs.forecaster() is None

    def test_configure_rejects_bad_model_eagerly(self):
        with pytest.raises(ValueError):
            obs.configure_forecast(model="prophet")
        assert obs.forecaster() is None

    def test_forecast_arrivals_metric_increments(self):
        from karpenter_tpu import metrics

        eng = ArrivalForecaster(bucket_s=10.0, clock=FakeClock())
        before = metrics.FORECAST_ARRIVALS.labels(
            provisioner="metered"
        )._value.get()
        eng(_span("provision.round", provisioner="metered", batch=6))
        assert metrics.FORECAST_ARRIVALS.labels(
            provisioner="metered"
        )._value.get() == before + 6
