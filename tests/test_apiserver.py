"""Real-apiserver backend tests (the envtest analog — reference:
pkg/test/environment.go:53-98): the full controller stack exercised across
a genuine HTTP + Kubernetes-wire-format boundary via ``TestApiServer``
(kube/testserver.py) and ``ApiCluster`` (kube/apiserver.py)."""

import json
import time

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import (
    LabelSelector,
    Lease,
    ObjectMeta,
    OwnerReference,
    PodDisruptionBudget,
)
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.kube import serde
from karpenter_tpu.kube.apiserver import ApiCluster
from karpenter_tpu.kube.client import Cluster, Conflict, NotFound
from karpenter_tpu.kube.leader import KubeLease
from karpenter_tpu.kube.testserver import TestApiServer, merge_patch
from tests.factories import make_node, make_pod, make_provisioner


class _ExternalEnv:
    """Conformance escape hatch (VERDICT r2 #5): run this suite against a
    REAL kube-apiserver instead of the in-process protocol double —

        KARPENTER_TEST_APISERVER=http://127.0.0.1:8001 pytest tests/test_apiserver.py

    (e.g. `kubectl proxy` against a kind/minikube scratch cluster with the
    karpenter.sh CRD from deploy/crd.yaml applied). The suite creates
    fixed-name objects, so point it at a disposable cluster. A protocol
    double written by the client's own author cannot catch shared
    misunderstandings of field casing, patch semantics, or subresource
    status codes — a periodic run of this suite against the real thing can.
    """

    def __init__(self, url: str):
        self.url = url
        self._clients = []
        # the server-side handle tests use for direct setup/assertions is
        # just another client of the real apiserver
        self.cluster = self._new_client()

    def _new_client(self, **kw) -> ApiCluster:
        c = ApiCluster(self.url, **kw)
        c.start()
        assert c.wait_for_sync(30)
        self._clients.append(c)
        return c

    def connect(self, **kw) -> ApiCluster:
        return self._new_client(**kw)

    def stop(self) -> None:
        for c in self._clients:
            c.stop()


@pytest.fixture()
def env():
    import os

    external = os.environ.get("KARPENTER_TEST_APISERVER")
    if external:
        e = _ExternalEnv(external)
        yield e
        e.stop()
        return
    server = TestApiServer()
    server.start()
    clients = []

    def connect(**kw) -> ApiCluster:
        c = ApiCluster(server.url, **kw)
        c.start()
        assert c.wait_for_sync(10)
        clients.append(c)
        return c

    server.connect = connect
    yield server
    for c in clients:
        c.stop()
    server.stop()


class TestSerde:
    def test_wire_is_kubernetes_shaped(self):
        pod = make_pod(requests={"cpu": "0.5", "memory": "512Mi"})
        doc = serde.to_wire("pods", pod)
        assert doc["apiVersion"] == "v1" and doc["kind"] == "Pod"
        c = doc["spec"]["containers"][0]
        assert c["resources"]["requests"]["cpu"] == "0.5"
        assert c["resources"]["requests"]["memory"] == str(512 * 1024 * 1024)

    def test_provisioner_round_trip(self):
        prov = make_provisioner(solver="tpu", limits={"cpu": "100"})
        doc = json.loads(json.dumps(serde.to_wire("provisioners", prov)))
        assert doc["apiVersion"] == "karpenter.sh/v1alpha5"
        back = serde.from_wire("provisioners", doc)
        assert back.spec.solver == "tpu"
        assert back.spec.limits.resources["cpu"] == 100.0
        assert back.metadata.namespace == ""  # cluster-scoped convention

    def test_merge_patch_semantics(self):
        target = {"a": {"b": 1, "c": 2}, "keep": True}
        patch = {"a": {"b": 3, "c": None}, "new": "x"}
        assert merge_patch(target, patch) == {"a": {"b": 3}, "keep": True, "new": "x"}


class TestRestSurface:
    def test_crud_and_conflict(self, env):
        c = env.connect()
        node = make_node(name="n1", capacity={"cpu": "4"})
        c.create("nodes", node)
        with pytest.raises(Conflict):
            c.create("nodes", make_node(name="n1"))
        got = c.get("nodes", "n1", namespace="")
        got.metadata.labels["x"] = "y"
        c.update("nodes", got)
        # stale resourceVersion PUT → 409 (optimistic concurrency)
        stale = serde.from_wire("nodes", serde.to_wire("nodes", got))
        stale.metadata.resource_version = 1
        with pytest.raises(Conflict):
            c.update("nodes", stale)
        c.delete("nodes", "n1", namespace="")
        with pytest.raises(NotFound):
            env.cluster.get("nodes", "n1", namespace="")

    def test_watch_propagates_between_clients(self, env):
        a = env.connect()
        b = env.connect()
        seen = []
        b.watch("pods", lambda e, o: seen.append((e, o.metadata.name)))
        a.create("pods", make_pod(name="w1", requests={"cpu": "1"}))
        deadline = time.time() + 15
        while time.time() < deadline and not any(n == "w1" for _, n in seen):
            time.sleep(0.02)
        assert any(n == "w1" for _, n in seen)
        assert b.try_get("pods", "w1") is not None

    def test_bind_subresource(self, env):
        c = env.connect()
        pod = make_pod(name="p1", requests={"cpu": "1"})
        c.create("pods", pod)
        c.bind(pod, "some-node")
        assert env.cluster.get("pods", "p1").spec.node_name == "some-node"

    def test_evict_respects_pdb_with_429(self, env):
        c = env.connect()
        env.cluster.create(
            "pdbs",
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb"),
                selector=LabelSelector(match_labels={"app": "a"}),
                min_available=1,
            ),
        )
        pod = make_pod(name="only", labels={"app": "a"}, requests={"cpu": "1"})
        c.create("pods", pod)
        assert c.evict(pod) is False  # PDB floor → 429
        env.cluster.create("pods", make_pod(name="second", labels={"app": "a"}))
        assert c.evict(pod) is True

    def test_finalizer_aware_delete_and_patch(self, env):
        c = env.connect()
        node = make_node(name="fin")
        node.metadata.finalizers = [lbl.TERMINATION_FINALIZER]
        c.create("nodes", node)
        c.delete("nodes", "fin", namespace="")
        pinned = env.cluster.get("nodes", "fin", namespace="")
        assert pinned.metadata.deletion_timestamp is not None  # terminating
        obj = c.get("nodes", "fin", namespace="")
        c.remove_finalizer("nodes", obj, lbl.TERMINATION_FINALIZER)
        assert env.cluster.try_get("nodes", "fin", namespace="") is None
        assert c.try_get("nodes", "fin", namespace="") is None

    def test_flow_control_throttles(self, env):
        c = env.connect(qps=20, burst=1)
        t0 = time.perf_counter()
        for i in range(5):
            c.create("pods", make_pod(name=f"q{i}", requests={"cpu": "1"}))
        elapsed = time.perf_counter() - t0
        assert elapsed >= 4 / 20  # 4 post-burst tokens at 20 QPS


class TestKubeLeaderElection:
    def test_two_contenders_one_leader(self, env):
        a = env.connect()
        b = env.connect()
        # generous duration: a loaded CI box must not expire the lease
        # between acquire and renew
        la = KubeLease(a, identity="a", duration=120)
        lb = KubeLease(b, identity="b", duration=120)
        first = la.try_acquire()
        assert first is True
        assert lb.try_acquire() is False  # held and unexpired
        assert lb.holder() == "a"
        assert la.renew() is True
        la.release()
        assert lb.try_acquire() is True  # released → immediately acquirable
        assert lb.holder() == "b"
        assert la.renew() is False  # lost it

    def test_takeover_after_expiry(self, env):
        now = [1000.0]
        a = env.connect(clock=lambda: now[0])
        la = KubeLease(a, identity="a", duration=2)
        lb = KubeLease(a, identity="b", duration=2)
        assert la.try_acquire()
        now[0] += 3  # holder stops renewing past the lease duration
        assert lb.try_acquire() is True
        assert lb.holder() == "b"
        assert la.renew() is False


class TestFullRuntime:
    def test_provision_bind_terminate_over_apiserver(self, env):
        """The complete loop against the apiserver protocol: a 'kubectl'
        client creates a Provisioner and pending pods; the controller
        runtime (its own ApiCluster) provisions + binds; node delete drains
        and the cloud instance is released."""
        from karpenter_tpu.main import build_runtime
        from karpenter_tpu.options import Options

        kubectl = env.connect()
        controller_cluster = env.connect()
        provider = FakeCloudProvider(instance_types(10))
        rt = build_runtime(
            Options(), cluster=controller_cluster, cloud_provider=provider,
            start_workers=True,
        )
        rt.manager.start()
        try:
            kubectl.create("provisioners", make_provisioner())
            deadline = time.time() + 30
            while time.time() < deadline and "default" not in rt.provisioning.workers:
                time.sleep(0.05)
            assert "default" in rt.provisioning.workers
            rt.provisioning.workers["default"].batcher.idle_duration = 0.1

            for i in range(3):
                kubectl.create("pods", make_pod(name=f"app-{i}", requests={"cpu": "1"}))
            deadline = time.time() + 60
            while time.time() < deadline:
                bound = [p for p in env.cluster.pods() if p.spec.node_name]
                if len(bound) == 3:
                    break
                time.sleep(0.05)
            assert len([p for p in env.cluster.pods() if p.spec.node_name]) == 3
            nodes = env.cluster.nodes()
            assert len(nodes) == 1
            assert lbl.TERMINATION_FINALIZER in nodes[0].metadata.finalizers

            # mark ready so the drain path treats it as a live node
            name = nodes[0].metadata.name
            kubectl.delete("nodes", name, namespace="")
            deadline = time.time() + 60
            while time.time() < deadline and env.cluster.try_get("nodes", name, namespace="") is not None:
                time.sleep(0.05)
            assert env.cluster.try_get("nodes", name, namespace="") is None
            assert provider.delete_calls == [name]
        finally:
            rt.stop()


class TestReviewRegressions:
    def test_pdb_percentage_thresholds(self, env):
        c = env.connect()
        env.cluster.create(
            "pdbs",
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pct"),
                selector=LabelSelector(match_labels={"app": "p"}),
                min_available="50%",
            ),
        )
        pods = [make_pod(name=f"pp{i}", labels={"app": "p"}) for i in range(4)]
        for p in pods:
            c.create("pods", p)
        # the 50% floor resolves against the current matching count
        # (ceil, conservative): 4→2 ok, 3→2 ok, 2→1 ok, but the last pod
        # (1 matching, min ceil(0.5)=1) may never be evicted
        assert c.evict(pods[0]) is True
        assert c.evict(pods[1]) is True
        assert c.evict(pods[2]) is True
        assert c.evict(pods[3]) is False

    def test_maxunavailable_percentage_rounds_up(self, env):
        # the disruption controller resolves maxUnavailable with
        # roundUp=true: 50% of 3 pods allows 2 evictions, not 1
        c = env.connect()
        env.cluster.create(
            "pdbs",
            PodDisruptionBudget(
                metadata=ObjectMeta(name="mu"),
                selector=LabelSelector(match_labels={"app": "m"}),
                max_unavailable="50%",
            ),
        )
        pods = [make_pod(name=f"mu{i}", labels={"app": "m"}) for i in range(3)]
        for p in pods:
            # finalizers keep evicted pods present-but-unhealthy, so the
            # budget is charged against a stable matching count
            p.metadata.finalizers = ["test/hold"]
            c.create("pods", p)
        assert c.evict(pods[0]) is True
        # 2 of 3 disrupted ≤ ceil(1.5)=2 — round-DOWN would forbid this
        assert c.evict(pods[1]) is True
        assert c.evict(pods[2]) is False  # 3 of 3 disrupted > 2

    def test_watch_resumes_from_rv_without_relist(self, env, monkeypatch):
        # an idle stream end (server timeoutSeconds) must NOT trigger a full
        # re-list — the watch resumes from the last-seen resourceVersion and
        # later events still arrive (client-go behavior; ADVICE r2)
        monkeypatch.setattr("karpenter_tpu.kube.apiserver.WATCH_TIMEOUT_SECONDS", 1)
        relists = []
        orig = ApiCluster._relist

        def counting_relist(self, kind):
            relists.append(kind)
            return orig(self, kind)

        monkeypatch.setattr(ApiCluster, "_relist", counting_relist)
        c = env.connect()
        baseline = len(relists)
        # outlive at least two server-side stream timeouts
        time.sleep(2.5)
        env.cluster.create("pods", make_pod(name="after-resume"))
        deadline = time.time() + 5
        while time.time() < deadline:
            if c.try_get("pods", "after-resume") is not None:
                break
            time.sleep(0.05)
        assert c.try_get("pods", "after-resume") is not None
        assert len(relists) == baseline  # resumed, never re-listed

    def test_default_watch_kinds_exclude_leases(self, env):
        # the shipped RBAC grants leases get/create/update only — a lease
        # informer would 403 forever and fail wait_for_sync (ADVICE r2 high);
        # leader election reads its Lease with uncached get_live instead
        c = env.connect()
        assert "leases" not in c._watch_kinds
        assert set(c._watch_kinds) < set(Cluster.KINDS)

    def test_kube_lease_requires_apiserver_cluster(self):
        from karpenter_tpu.main import run_controller_process
        from karpenter_tpu.options import Options

        with pytest.raises(ValueError, match="kube: leader election requires"):
            run_controller_process(
                Options(leader_election_lease="kube:karpenter-leader-election"),
                serve=False,
            )

    def test_stopped_cluster_drops_late_events(self, env):
        c = env.connect()
        seen = []
        c.watch("pods", lambda e, o: seen.append(o.metadata.name))
        c.stop()
        env.cluster.create("pods", make_pod(name="late", requests={"cpu": "1"}))
        time.sleep(0.5)
        assert "late" not in seen


class TestConsolidationOverApiserver:
    def test_rebind_rejected_by_apiserver(self, env):
        """The protocol double enforces real Binding semantics: a bound pod
        cannot be rebound (why consolidation needs the evict mode); a
        same-node retry is treated as idempotent success by the client."""
        c = env.connect()
        pod = make_pod(name="bound", requests={"cpu": "1"})
        c.create("pods", pod)
        c.bind(pod, "node-a")
        c.bind(pod, "node-a")  # lost-response retry: no error
        with pytest.raises(Conflict):
            c.bind(pod, "node-b")

    def test_evict_mode_consolidates_via_drain_and_recreate(self, env):
        """Full evict-mode flow over the apiserver: old nodes drain
        (evictions through the real subresource), a workload-controller
        stand-in recreates the pods, the recreated pending pods drive the
        provisioner to launch right-sized capacity, and the total new
        price realizes the plan's savings. No replacements are
        pre-launched (nothing in an autoscaler fills them — that's the
        kube-scheduler's job)."""
        import threading

        from karpenter_tpu.api.objects import PodCondition
        from karpenter_tpu.controllers.consolidation import ConsolidationController
        from karpenter_tpu.controllers.termination import TerminationController
        from karpenter_tpu.main import build_runtime
        from karpenter_tpu.options import Options

        kubectl = env.connect()
        controller_cluster = env.connect()
        provider = FakeCloudProvider(instance_types(30))
        rt = build_runtime(
            Options(), cluster=controller_cluster, cloud_provider=provider,
            start_workers=True,
        )
        rt.manager.start()

        # workload-controller stand-in: recreate evicted pods as pending
        recreated = []
        lock = threading.Lock()

        def recreate(event, pod):
            if event != "DELETED" or not pod.metadata.labels.get("workload"):
                return
            with lock:
                if pod.metadata.name in recreated:
                    return
                recreated.append(pod.metadata.name)
            fresh = make_pod(
                name=f"{pod.metadata.name}-r", labels=dict(pod.metadata.labels),
                requests={"cpu": "1"},
            )
            try:
                kubectl.create("pods", fresh)
            except Conflict:
                pass

        kubectl.watch("pods", recreate)
        try:
            kubectl.create("provisioners", make_provisioner())
            deadline = time.time() + 30
            while time.time() < deadline and "default" not in rt.provisioning.workers:
                time.sleep(0.05)

            # two expensive under-utilized nodes, one small pod each
            for i in range(2):
                node = make_node(
                    name=f"old-{i}",
                    capacity={"cpu": "64", "memory": "256Gi", "pods": "100"},
                    provisioner_name="default",
                    labels={
                        lbl.INSTANCE_TYPE: "fake-it-29",  # priciest in catalog
                        lbl.TOPOLOGY_ZONE: "test-zone-1",
                        lbl.CAPACITY_TYPE: "on-demand",
                    },
                )
                node.status.conditions = [PodCondition(type="Ready", status="True")]
                node.metadata.finalizers = [lbl.TERMINATION_FINALIZER]
                kubectl.create("nodes", node)
                pod = make_pod(
                    name=f"w-{i}", labels={"workload": "a"},
                    requests={"cpu": "1"}, node_name=f"old-{i}", unschedulable=False,
                    # evict-mode candidates require a recreating controller
                    owner=OwnerReference(api_version="apps/v1", kind="ReplicaSet", name="w"),
                )
                kubectl.create("pods", pod)

            # the controller's informer cache learns of kubectl's writes via
            # its watch stream — wait for it before planning (production
            # plans on watch-driven reconciles, so this race is test-only)
            deadline = time.time() + 15
            while time.time() < deadline and (
                len([n for n in controller_cluster.nodes() if n.metadata.name.startswith("old-")]) < 2
                or len([p for p in controller_cluster.pods() if p.metadata.name.startswith("w-")]) < 2
            ):
                time.sleep(0.05)
            assert (
                len([n for n in controller_cluster.nodes() if n.metadata.name.startswith("old-")]) == 2
            ), "controller cache never saw the nodes"
            assert (
                len([p for p in controller_cluster.pods() if p.metadata.name.startswith("w-")]) == 2
            ), "controller cache never saw the pods"

            consolidation = ConsolidationController(
                controller_cluster, provider, enabled=True
            )
            assert consolidation.migration == "evict"  # auto on ApiCluster
            prov = controller_cluster.get("provisioners", "default", namespace="")
            plan = consolidation.plan(prov)
            assert plan.worthwhile, (plan.current_price, plan.proposed_price)
            rt.provisioning.workers["default"].batcher.idle_duration = 0.1
            launched = consolidation.execute(plan)
            assert launched == []  # evict mode pre-launches nothing

            # termination controller drains the old nodes (manager watches
            # handle it; poll until both are gone and pods re-landed)
            deadline = time.time() + 60
            while time.time() < deadline:
                old = [n for n in env.cluster.nodes() if n.metadata.name.startswith("old-")]
                recreated_bound = [
                    p for p in env.cluster.pods()
                    if p.metadata.name.endswith("-r") and p.spec.node_name
                ]
                if not old and len(recreated_bound) == 2:
                    break
                time.sleep(0.1)
            assert [n for n in env.cluster.nodes() if n.metadata.name.startswith("old-")] == []
            landed = [
                p.spec.node_name for p in env.cluster.pods() if p.metadata.name.endswith("-r")
            ]
            assert len(landed) == 2 and all(landed)
            assert all(not n.startswith("old-") for n in landed)
            # savings realized: the rebuilt capacity must decisively beat
            # the old price. (It may exceed the plan's single-batch optimum
            # when drain timing splits the recreations across provisioning
            # batches — the next consolidation tick re-packs those.)
            catalog_prices = {
                it.name: it.effective_price() for it in provider.get_instance_types()
            }
            new_price = sum(
                catalog_prices.get(n.metadata.labels.get(lbl.INSTANCE_TYPE, ""), 0.0)
                for n in env.cluster.nodes()
            )
            assert new_price < plan.current_price * 0.5
        finally:
            rt.stop()

    def test_bind_migration_rejected_on_apiserver(self, env):
        from karpenter_tpu.controllers.consolidation import ConsolidationController

        c = env.connect()
        with pytest.raises(ValueError, match="bind migration cannot work"):
            ConsolidationController(c, FakeCloudProvider(instance_types(5)), migration="bind")

    def test_ownerless_pods_block_evict_candidacy(self, env):
        """Voluntary disruption must not destroy workloads: a node hosting a
        pod without a recreating controller is not an evict-mode candidate."""
        from karpenter_tpu.api.objects import PodCondition
        from karpenter_tpu.controllers.consolidation import ConsolidationController

        c = env.connect()
        provider = FakeCloudProvider(instance_types(30))
        c.create("provisioners", make_provisioner())
        node = make_node(
            name="bare-host", capacity={"cpu": "64", "memory": "256Gi", "pods": "100"},
            provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: "fake-it-29", lbl.TOPOLOGY_ZONE: "test-zone-1",
                    lbl.CAPACITY_TYPE: "on-demand"},
        )
        node.status.conditions = [PodCondition(type="Ready", status="True")]
        c.create("nodes", node)
        c.create("pods", make_pod(name="bare", requests={"cpu": "1"},
                                  node_name="bare-host", unschedulable=False))
        consolidation = ConsolidationController(c, provider, enabled=True)
        plan = consolidation.plan(c.get("provisioners", "default", namespace=""))
        assert plan.nodes == []  # the bare pod pins its node


class TestNodeLifecycleOverApiserver:
    def test_ready_node_loses_startup_taint_via_merge_patch(self, env):
        """The node controller's single merge patch (not a full-object PUT)
        lands the not-ready taint removal + emptiness annotation on the
        server with no resourceVersion races."""
        from karpenter_tpu.api.objects import PodCondition
        from karpenter_tpu.controllers.node import NodeController

        c = env.connect()
        c.create("provisioners", make_provisioner(ttl_after_empty=600))
        node = make_node(
            name="young", provisioner_name="default", capacity={"cpu": "4"},
        )
        from karpenter_tpu.api.objects import Taint

        node.spec.taints = [Taint(key=lbl.NOT_READY_TAINT_KEY, effect="NoSchedule")]
        node.status.conditions = [PodCondition(type="Ready", status="True")]
        c.create("nodes", node)
        controller = NodeController(c)
        controller.reconcile("young")
        server_node = env.cluster.get("nodes", "young", namespace="")
        assert all(t.key != lbl.NOT_READY_TAINT_KEY for t in server_node.spec.taints)
        # empty node got the emptiness clock annotation
        assert lbl.EMPTINESS_TIMESTAMP_ANNOTATION in server_node.metadata.annotations
        # and the termination finalizer was ensured
        assert lbl.TERMINATION_FINALIZER in server_node.metadata.finalizers

    def test_emptiness_annotation_removed_when_pod_lands(self, env):
        from karpenter_tpu.api.objects import PodCondition
        from karpenter_tpu.controllers.node import NodeController

        c = env.connect()
        c.create("provisioners", make_provisioner(ttl_after_empty=600))
        node = make_node(name="busy", provisioner_name="default", capacity={"cpu": "4"})
        node.status.conditions = [PodCondition(type="Ready", status="True")]
        c.create("nodes", node)
        controller = NodeController(c)
        controller.reconcile("busy")
        assert (
            lbl.EMPTINESS_TIMESTAMP_ANNOTATION
            in env.cluster.get("nodes", "busy", namespace="").metadata.annotations
        )
        c.create("pods", make_pod(name="tenant", requests={"cpu": "1"},
                                  node_name="busy", unschedulable=False))
        controller.reconcile("busy")
        assert (
            lbl.EMPTINESS_TIMESTAMP_ANNOTATION
            not in env.cluster.get("nodes", "busy", namespace="").metadata.annotations
        )


class TestStatusSubresource:
    """Provisioners declare ``subresources: {status: {}}`` (deploy/crd.yaml),
    so — like a real apiserver — main-resource writes keep the current
    status and status changes only land through ``/status``."""

    def test_main_resource_put_drops_status(self, env):
        c = env.connect()
        c.create("provisioners", make_provisioner())
        c.patch_status(
            "provisioners", "default",
            {"resources": {"cpu": "4"}}, namespace="",
        )
        live = c.get_live("provisioners", "default", namespace="")
        assert live.status.resources == {"cpu": 4.0}
        # a full-object PUT carrying a mutated status must NOT change it
        live.status.resources = {}
        live.spec.solver = "tpu"
        c.update("provisioners", live)
        after = c.get_live("provisioners", "default", namespace="")
        assert after.spec.solver == "tpu"  # spec write landed
        assert after.status.resources == {"cpu": 4.0}  # status kept

    def test_main_resource_patch_drops_status(self, env):
        c = env.connect()
        c.create("provisioners", make_provisioner())
        c.merge_patch(
            "provisioners", "default",
            {"spec": {"solver": "tpu"}, "status": {"resources": {"cpu": "9"}}},
            namespace="",
        )
        after = c.get_live("provisioners", "default", namespace="")
        assert after.spec.solver == "tpu"
        assert after.status.resources == {}

    def test_active_condition_lands_over_the_wire(self, env):
        from karpenter_tpu.controllers.provisioning import ProvisioningController
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types

        c = env.connect()
        c.create("provisioners", make_provisioner())
        controller = ProvisioningController(
            c, FakeCloudProvider(instance_types(5)), start_workers=False
        )
        controller.reconcile("default")
        live = c.get_live("provisioners", "default", namespace="")
        cond = live.status.condition()
        assert cond is not None and (cond.type, cond.status) == ("Active", "True")
        assert cond.last_transition_time is not None
        controller.stop()
