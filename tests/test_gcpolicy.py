"""The post-warmup GC policy: idempotent freeze, full restore, and the
runtime wiring that must never freeze after stop() has restored."""

import gc
import threading
import time
import types

from karpenter_tpu.utils import gcpolicy


def test_freeze_restore_round_trip():
    before = gc.get_threshold()
    try:
        gcpolicy.freeze_after_warmup(gen0_threshold=12345)
        assert gc.get_threshold()[0] == 12345
        gcpolicy.freeze_after_warmup(gen0_threshold=99999)  # idempotent
        assert gc.get_threshold()[0] == 12345
    finally:
        gcpolicy.restore()
    assert gc.get_threshold() == before
    gcpolicy.restore()  # idempotent
    assert gc.get_threshold() == before


def test_stop_cancels_pending_freeze():
    """A worker warming AFTER Runtime.stop must not re-freeze the heap —
    the stop()-then-freeze race the cancel event exists to close."""
    from karpenter_tpu.main import _freeze_gc_when_warm

    before = gc.get_threshold()
    warmed = threading.Event()
    worker = types.SimpleNamespace(warmed=warmed)
    provisioning = types.SimpleNamespace(workers={"p": worker})
    runtime = types.SimpleNamespace(provisioning=provisioning, _gc_freeze_cancel=None)
    try:
        _freeze_gc_when_warm(runtime, timeout=5.0)
        assert runtime._gc_freeze_cancel is not None
        # stop() semantics: cancel BEFORE any freeze can land
        runtime._gc_freeze_cancel.set()
        warmed.set()
        deadline = time.time() + 0.5
        while time.time() < deadline and gc.get_threshold() == before:
            time.sleep(0.02)  # a freeze would flip thresholds; none may
        assert gc.get_threshold() == before, "freeze landed after cancel"
    finally:
        gcpolicy.restore()
    assert gc.get_threshold() == before


def test_freeze_skipped_when_cancelled_inside_lock():
    """The check-then-freeze window is closed INSIDE gcpolicy: a cancel
    event set before the locked check always wins, even if the caller
    already passed its own check."""
    before = gc.get_threshold()
    cancel = threading.Event()
    cancel.set()
    try:
        gcpolicy.freeze_after_warmup(unless=cancel)
        assert gc.get_threshold() == before
    finally:
        gcpolicy.restore()


def test_freeze_fires_once_worker_warms():
    from karpenter_tpu.main import _freeze_gc_when_warm

    before = gc.get_threshold()
    warmed = threading.Event()
    worker = types.SimpleNamespace(warmed=warmed)
    provisioning = types.SimpleNamespace(workers={"p": worker})
    runtime = types.SimpleNamespace(provisioning=provisioning, _gc_freeze_cancel=None)
    try:
        _freeze_gc_when_warm(runtime, timeout=10.0)
        warmed.set()
        deadline = time.time() + 5
        while time.time() < deadline and gc.get_threshold() == before:
            time.sleep(0.05)
        assert gc.get_threshold() != before, "freeze never fired after warmup"
    finally:
        runtime._gc_freeze_cancel.set()
        gcpolicy.restore()
    assert gc.get_threshold() == before
