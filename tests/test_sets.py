"""Complement-set algebra tests (mirrors pkg/utils/sets semantics)."""

from karpenter_tpu.utils.sets import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
    ValueSet,
    set_for_operator,
)


class TestIntersection:
    def test_finite_finite(self):
        a = ValueSet.of("a", "b")
        b = ValueSet.of("b", "c")
        assert a.intersection(b) == ValueSet.of("b")

    def test_finite_complement(self):
        a = ValueSet.of("a", "b")
        b = ValueSet.complement_of("b")
        assert a.intersection(b) == ValueSet.of("a")

    def test_complement_finite(self):
        a = ValueSet.complement_of("a")
        b = ValueSet.of("a", "b")
        assert a.intersection(b) == ValueSet.of("b")

    def test_complement_complement(self):
        a = ValueSet.complement_of("a")
        b = ValueSet.complement_of("b")
        out = a.intersection(b)
        assert out.complement and out.values == frozenset({"a", "b"})

    def test_universe_identity(self):
        a = ValueSet.of("x")
        assert ValueSet.universe().intersection(a) == a


class TestOpType:
    def test_types(self):
        assert ValueSet.of("a").op_type() == OP_IN
        assert ValueSet.empty().op_type() == OP_DOES_NOT_EXIST
        assert ValueSet.complement_of("a").op_type() == OP_NOT_IN
        assert ValueSet.universe().op_type() == OP_EXISTS


class TestMembership:
    def test_has(self):
        assert ValueSet.of("a").has("a")
        assert not ValueSet.of("a").has("b")
        assert ValueSet.complement_of("a").has("b")
        assert not ValueSet.complement_of("a").has("a")
        assert ValueSet.universe().has("anything")

    def test_cardinality(self):
        assert ValueSet.of("a", "b").cardinality == 2
        assert ValueSet.empty().cardinality == 0
        assert ValueSet.universe().cardinality > 1 << 60
        # complement of one value is still "infinite"
        assert ValueSet.complement_of("a").cardinality > 1 << 60


class TestOperatorConstruction:
    def test_all_ops(self):
        assert set_for_operator(OP_IN, ["a"]) == ValueSet.of("a")
        assert set_for_operator(OP_NOT_IN, ["a"]) == ValueSet.complement_of("a")
        assert set_for_operator(OP_EXISTS) == ValueSet.universe()
        assert set_for_operator(OP_DOES_NOT_EXIST) == ValueSet.empty()
