"""Resilience-layer unit tests: retry policy (jitter, deadline, budget
interaction), circuit breaker transitions (closed/open/half-open/close),
the per-round budget, miss-tracked liveness, and the seams the layer is
threaded through (metered provider, wire transport, solver degradation)."""

import random
import threading

import pytest

from karpenter_tpu.resilience import (
    BreakerBoard,
    BreakerOpen,
    Budget,
    CircuitBreaker,
    MissTracker,
    RetryPolicy,
    decorrelated_jitter,
    default_retryable,
)


class FakeClock:
    def __init__(self, start: float = 0.0):
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


class TestRetryPolicy:
    def _policy(self, clock=None, **kw):
        kw.setdefault("base", 0.001)
        kw.setdefault("cap", 0.002)
        kw.setdefault("sleep", lambda s: clock.advance(s) if clock else None)
        if clock:
            kw.setdefault("clock", clock)
        return RetryPolicy(**kw)

    def test_transient_failure_retried_to_success(self):
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] < 3:
                raise ConnectionError("blip")
            return "ok"

        assert self._policy(max_attempts=4).call(flaky) == "ok"
        assert calls[0] == 3

    def test_attempts_exhausted_raises_last_error(self):
        def dead():
            raise ConnectionError("still down")

        with pytest.raises(ConnectionError):
            self._policy(max_attempts=3).call(dead)

    def test_non_retryable_raises_immediately(self):
        calls = [0]

        def bad_input():
            calls[0] += 1
            raise ValueError("malformed")

        with pytest.raises(ValueError):
            self._policy(max_attempts=5).call(bad_input)
        assert calls[0] == 1

    def test_capacity_errors_never_retried(self):
        from karpenter_tpu.cloudprovider.gke import GkeStockoutError
        from karpenter_tpu.cloudprovider.simulated import InsufficientCapacityError

        assert not default_retryable(InsufficientCapacityError("all ICE"))
        assert not default_retryable(GkeStockoutError("stockout"))
        assert default_retryable(ConnectionError("reset"))
        assert default_retryable(RuntimeError("weird"))

    def test_deadline_cuts_retries_short(self):
        """The hard per-operation deadline wins over max_attempts: once the
        next backoff would cross it, the last error propagates."""
        clock = FakeClock()
        calls = [0]

        def dead():
            calls[0] += 1
            clock.advance(0.6)
            raise ConnectionError("down")

        policy = self._policy(
            clock=clock, max_attempts=10, base=0.5, cap=0.5, deadline=1.0
        )
        with pytest.raises(ConnectionError):
            policy.call(dead)
        assert calls[0] == 1  # 0.6 elapsed + ≥0.5 backoff > 1.0 deadline

    def test_budget_caps_the_deadline(self):
        """An active round budget tighter than the policy deadline wins; an
        exhausted budget degrades to a single attempt, never to no work."""
        clock = FakeClock()
        calls = [0]

        def dead():
            calls[0] += 1
            raise ConnectionError("down")

        policy = self._policy(clock=clock, max_attempts=5, deadline=60.0)
        with Budget(0.0, clock=clock).activate():
            with pytest.raises(ConnectionError):
                policy.call(dead)
        assert calls[0] == 1
        calls[0] = 0
        with pytest.raises(ConnectionError):
            policy.call(dead)  # no budget: the policy's own attempts apply
        assert calls[0] == 5

    def test_decorrelated_jitter_bounded(self):
        rng = random.Random(7)
        sleeps = []
        gen = decorrelated_jitter(0.05, cap=1.0, rng=rng)
        for _ in range(50):
            sleeps.append(next(gen))
        assert all(0.05 <= s <= 1.0 for s in sleeps)
        assert len(set(round(s, 6) for s in sleeps)) > 10  # actually jittered


class TestBudget:
    def test_remaining_counts_down(self):
        clock = FakeClock()
        budget = Budget(10.0, clock=clock)
        assert budget.remaining() == 10.0
        clock.advance(4.0)
        assert budget.remaining() == 6.0
        clock.advance(7.0)
        assert budget.remaining() == 0.0
        assert budget.expired

    def test_shared_across_threads(self):
        """The launch pool re-activates ONE budget per thread: every thread
        sees the same countdown."""
        from karpenter_tpu.resilience import current_budget

        clock = FakeClock()
        budget = Budget(10.0, clock=clock)
        seen = []

        def worker():
            with budget.activate():
                seen.append(current_budget.get().remaining())

        clock.advance(3.0)
        threads = [threading.Thread(target=worker) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert seen == [7.0, 7.0, 7.0]
        assert current_budget.get() is None  # never leaks out of activate()


class TestCircuitBreaker:
    def _breaker(self, clock, **kw):
        kw.setdefault("window", 4)
        kw.setdefault("min_volume", 2)
        kw.setdefault("failure_rate", 0.5)
        kw.setdefault("open_seconds", 10.0)
        return CircuitBreaker("dep", clock=clock, **kw)

    def test_opens_on_windowed_failure_rate(self):
        clock = FakeClock()
        b = self._breaker(clock)
        assert not b.record_failure()  # volume 1 < min_volume
        assert b.state == "closed"
        assert b.record_failure()  # 2/2 failures ≥ 0.5
        assert b.state == "open"
        assert b.trips == 1
        assert not b.allow()

    def test_low_failure_rate_stays_closed(self):
        """A chaos-level ~10% error rate must NOT trip the breaker."""
        clock = FakeClock()
        b = self._breaker(clock, window=20, min_volume=5)
        rng = random.Random(3)
        for _ in range(200):
            if rng.random() < 0.1:
                b.record_failure()
            else:
                b.record_success()
            assert b.state == "closed"

    def test_half_open_probe_closes_on_success(self):
        clock = FakeClock()
        b = self._breaker(clock)
        b.record_failure()
        b.record_failure()
        assert b.state == "open"
        clock.advance(10.1)
        assert b.available()
        assert b.allow()  # the half-open probe slot
        assert b.state == "half-open"
        assert not b.allow()  # only one probe in flight
        b.record_success()
        assert b.state == "closed"
        assert b.allow()

    def test_half_open_probe_failure_reopens(self):
        clock = FakeClock()
        b = self._breaker(clock)
        b.record_failure()
        b.record_failure()
        clock.advance(10.1)
        assert b.allow()
        assert b.record_failure()  # probe failed → re-open, counted as a trip
        assert b.state == "open"
        assert b.trips == 2
        assert not b.allow()
        clock.advance(10.1)
        assert b.allow()  # a fresh cool-off earns a fresh probe

    def test_call_raises_breaker_open_without_calling(self):
        clock = FakeClock()
        b = self._breaker(clock)
        for _ in range(2):
            with pytest.raises(ConnectionError):
                b.call(lambda: (_ for _ in ()).throw(ConnectionError("x")))
        calls = [0]
        with pytest.raises(BreakerOpen):
            b.call(lambda: calls.__setitem__(0, calls[0] + 1))
        assert calls[0] == 0

    def test_board_tracks_open_dependencies(self):
        clock = FakeClock()
        board = BreakerBoard(clock=clock, window=4, min_volume=1,
                             failure_rate=0.5, open_seconds=10.0)
        board.get("a").record_failure()
        board.get("b").record_success()
        assert board.open_dependencies() == ["a"]
        clock.advance(10.1)
        board.get("a").allow()
        board.get("a").record_success()
        assert board.open_dependencies() == []

    def test_state_gauge_published(self):
        from prometheus_client import generate_latest

        from karpenter_tpu import metrics

        clock = FakeClock()
        b = CircuitBreaker("gauge-dep", window=2, min_volume=1,
                           failure_rate=0.5, open_seconds=10.0, clock=clock)
        b.record_failure()
        out = generate_latest(metrics.REGISTRY).decode()
        assert 'karpenter_resilience_breaker_state{dependency="gauge-dep"} 1.0' in out


class TestMissTracker:
    def test_requires_consecutive_misses(self):
        t = MissTracker(threshold=3)
        assert not t.observe("i-1", present=False)
        assert not t.observe("i-1", present=False)
        assert t.observe("i-1", present=False)

    def test_sighting_resets_the_count(self):
        t = MissTracker(threshold=3)
        t.observe("i-1", present=False)
        t.observe("i-1", present=False)
        t.observe("i-1", present=True)  # one flaky streak, then it shows up
        assert not t.observe("i-1", present=False)
        assert t.misses("i-1") == 1


class TestMeteredProviderResilience:
    """The (provider, method) breaker + retry wrap on the metered decorator."""

    def _metered(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.cloudprovider.metrics import decorate

        provider = FakeCloudProvider(instance_types(4))
        metered = decorate(provider)
        # tests must not sleep through real backoff
        for policy in metered._policies.values():
            policy._sleep = lambda s: None
        return provider, metered

    def test_transient_catalog_failure_retried(self):
        provider, metered = self._metered()
        original = provider.get_instance_types
        fail = [2]

        def flaky(p=None):
            if fail[0]:
                fail[0] -= 1
                raise ConnectionError("catalog blip")
            return original(p)

        provider.get_instance_types = flaky
        assert len(metered.get_instance_types()) == 4

    def test_dead_dependency_trips_then_fails_fast(self):
        from karpenter_tpu.cloudprovider.metrics import (
            BREAKER_MIN_VOLUME,
            BREAKER_WINDOW,
        )

        provider, metered = self._metered()
        calls = [0]

        def dead(p=None):
            calls[0] += 1
            raise ConnectionError("dead")

        provider.get_instance_types = dead
        for _ in range(BREAKER_WINDOW):
            with pytest.raises((ConnectionError, BreakerOpen)):
                metered.get_instance_types()
        with pytest.raises(BreakerOpen):
            metered.get_instance_types()
        before = calls[0]
        with pytest.raises(BreakerOpen):
            metered.get_instance_types()
        assert calls[0] == before  # open breaker: the delegate isn't touched
        assert calls[0] >= BREAKER_MIN_VOLUME

    def test_capacity_error_does_not_trip_breaker(self):
        """An ICE storm is a capacity condition, not unavailability: the
        create breaker must stay closed so recovery launches flow the
        moment capacity returns."""
        from karpenter_tpu.cloudprovider.simulated import InsufficientCapacityError

        provider, metered = self._metered()

        def all_ice(request):
            raise InsufficientCapacityError("all pools exhausted")

        provider.create = all_ice
        for _ in range(30):
            with pytest.raises(InsufficientCapacityError):
                metered.create(None)
        assert metered.breakers.get("fake:create").state == "closed"

    def test_open_poll_breaker_yields_empty_drain(self):
        provider, metered = self._metered()

        def dead_poll():
            raise ConnectionError("event wire down")

        provider.poll_disruptions = dead_poll
        for _ in range(25):
            try:
                metered.poll_disruptions()
            except ConnectionError:
                pass
        # breaker now open: the poll degrades to an empty drain, keeping
        # the interruption controller's cadence alive
        assert metered.breakers.get("fake:poll_disruptions").state == "open"
        assert metered.poll_disruptions() == []


class TestSolverDegradation:
    def test_pack_failure_degrades_to_ffd_and_breaker_routes_immediately(self):
        """A broken accelerated path serves the batch via FFD (pods still
        schedule); after the shape's breaker opens, the kernel isn't even
        attempted until the cool-off expires."""
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler
        from karpenter_tpu.testing import make_pod, make_provisioner

        catalog = instance_types(4)
        constraints = make_provisioner(solver="tpu").spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        sched = TpuScheduler(Cluster(), rng=random.Random(0))
        pack_calls = [0]

        def broken_pack(batch):
            pack_calls[0] += 1
            raise RuntimeError("device ladder exploded")

        sched._pack = broken_pack
        pods = [make_pod(requests={"cpu": "0.5"}) for _ in range(4)]
        for _ in range(2):  # two failures open the shape's breaker
            nodes = sched.solve(constraints, catalog, list(pods))
            assert nodes and sum(len(n.pods) for n in nodes) == 4
        attempted = pack_calls[0]
        nodes = sched.solve(constraints, catalog, list(pods))
        assert nodes and sum(len(n.pods) for n in nodes) == 4
        assert pack_calls[0] == attempted  # breaker open: FFD immediately
        assert sched.last_profile.get("packer_backend") == "ffd-degraded"

    def test_invalid_pack_quarantines_shape_and_serves_ffd(self):
        """A decoded device/remote plan that fails the host-side sanity
        check (here: one pod assigned to two nodes) must never reach the
        bind path: the batch is re-served via FFD, the violation counts as
        `degraded_solves_total{reason="invalid_pack"}`, and the shape
        class's pack breaker trips IMMEDIATELY (correctness, not an
        availability blip — no waiting out the failure-rate window)."""
        from prometheus_client import generate_latest

        from karpenter_tpu import metrics
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler
        from karpenter_tpu.testing import make_pod, make_provisioner

        def degraded_invalid() -> float:
            # address="" — the in-process path's provenance label
            out = generate_latest(metrics.REGISTRY).decode()
            for line in out.splitlines():
                if line.startswith(
                    "karpenter_solver_degraded_solves_total"
                ) and 'reason="invalid_pack"' in line:
                    return float(line.rsplit(" ", 1)[1])
            return 0.0

        catalog = instance_types(4)
        constraints = make_provisioner(solver="tpu").spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        sched = TpuScheduler(Cluster(), rng=random.Random(0))
        real_decode = sched._decode
        decode_calls = [0]

        def corrupting_decode(*args, **kwargs):
            decode_calls[0] += 1
            nodes = real_decode(*args, **kwargs)
            # corrupt the plan: double-place an already-assigned pod
            placed = [n for n in nodes if n.pods]
            if placed and len(nodes) > 1:
                target = nodes[1] if nodes[1] is not placed[0] else nodes[0]
                target.pods.append(placed[0].pods[0])
            elif placed:
                placed[0].pods.append(placed[0].pods[0])
            return nodes

        sched._decode = corrupting_decode
        pods = [make_pod(requests={"cpu": "0.5"}) for _ in range(4)]
        before = degraded_invalid()
        nodes = sched.solve(constraints, catalog, list(pods))
        # pods still schedule, exactly once each, via the FFD floor
        assert nodes and sum(len(n.pods) for n in nodes) == 4
        keys = [p.key for n in nodes for p in n.pods]
        assert len(keys) == len(set(keys))
        assert sched.last_profile.get("packer_backend") == "ffd-degraded"
        assert degraded_invalid() == before + 1
        # ONE violation quarantined the shape outright: the next solve
        # routes straight to FFD without re-attempting the pack
        attempted = decode_calls[0]
        nodes = sched.solve(constraints, catalog, list(pods))
        assert nodes and sum(len(n.pods) for n in nodes) == 4
        assert decode_calls[0] == attempted

    def test_remote_breaker_half_open_recovers(self):
        from karpenter_tpu.solver.backend import TpuScheduler

        clock = FakeClock()
        sched = TpuScheduler.__new__(TpuScheduler)  # breaker behavior only
        from karpenter_tpu.resilience import CircuitBreaker

        b = CircuitBreaker("solver-service:x", window=4, min_volume=1,
                           failure_rate=0.5, open_seconds=30.0, clock=clock)
        assert b.record_failure()  # first RPC failure trips (round-1 contract)
        assert b.state == "open"
        assert not b.available()  # fused route free to claim the device
        clock.advance(30.1)
        assert b.allow()
        b.record_success()
        assert b.state == "closed"
