#!/usr/bin/env python
"""Metric naming/documentation check — a thin shim over karplint.

The actual pass lives in ``tools/karplint/rules/metric_names.py`` (the
``metric-name`` rule): Prometheus naming conventions, collision detection,
and the docs/metrics.md listing requirement for every metric registered in
``karpenter_tpu/metrics.py`` and ``karpenter_tpu/cloudprovider/metrics.py``.
This entrypoint exists for CI steps and hooks that want ONLY the metric
pass without the rest of the rule set::

    python hack/check_metrics_names.py
"""

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.karplint.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(
        main(
            [
                "--root", str(REPO_ROOT),
                "--rules", "metric-name",
                "karpenter_tpu",
            ]
        )
    )
