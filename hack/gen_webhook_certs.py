#!/usr/bin/env python
"""Generate the webhook CA + serving cert and print the install steps.

The serving cert is mounted from the ``karpenter-tpu-webhook-certs``
Secret (deploy/webhook.yaml), so pod restarts never mint a new CA — the
``caBundle`` registered in the webhook configurations stays valid for the
CA's lifetime. Usage::

    python hack/gen_webhook_certs.py [certs-dir]
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")

from karpenter_tpu.kube.certs import ca_bundle_b64, ensure_serving_cert  # noqa: E402

SERVICE = "karpenter-tpu-webhook"
NAMESPACE = "karpenter"


def main() -> int:
    cert_dir = sys.argv[1] if len(sys.argv) > 1 else "webhook-certs"
    dns = [
        SERVICE,
        f"{SERVICE}.{NAMESPACE}",
        f"{SERVICE}.{NAMESPACE}.svc",
        f"{SERVICE}.{NAMESPACE}.svc.cluster.local",
    ]
    cert, key, ca = ensure_serving_cert(cert_dir, dns)
    print(f"# certs ready in {cert_dir}/ (CA reused if already present)")
    print("# 1. store the serving cert as the Secret the Deployment mounts:")
    print(
        f"kubectl -n {NAMESPACE} create secret generic {SERVICE}-certs "
        f"--from-file=tls.crt={cert} --from-file=tls.key={key} "
        f"--from-file=ca.crt={ca} --dry-run=client -o yaml | kubectl apply -f -"
    )
    print("# 2. register the webhooks with the CA bundle injected:")
    print(f"make webhook-cabundle CA={ca} | kubectl apply -f -")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
