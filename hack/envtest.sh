#!/usr/bin/env bash
# Run tests/test_apiserver.py against a REAL kube-apiserver (VERDICT r3
# ask #8; reference boots apiserver+etcd per suite — pkg/test/environment.go).
#
# Downloads the kubebuilder-tools tarball (etcd + kube-apiserver + kubectl),
# boots a single-node control plane the way controller-runtime's envtest
# does, exposes it as plain HTTP via `kubectl proxy`, applies the
# karpenter.sh CRD, and drives the suite through the
# KARPENTER_TEST_APISERVER escape hatch (tests/test_apiserver.py:32).
#
# Usage: hack/envtest.sh [k8s-version]
# Fails LOUDLY at every step — a silently-skipped conformance run is a gap.
set -euo pipefail

K8S_VERSION="${1:-1.28.0}"
ARCH="$(uname -m | sed 's/x86_64/amd64/;s/aarch64/arm64/')"
WORK="${ENVTEST_DIR:-/tmp/karpenter-envtest}"
BIN="$WORK/kubebuilder/bin"
PROXY_PORT="${PROXY_PORT:-8001}"

mkdir -p "$WORK"
cd "$WORK"

if [ ! -x "$BIN/kube-apiserver" ]; then
  echo ">> fetching kubebuilder-tools $K8S_VERSION ($ARCH)"
  curl -fsSL "https://storage.googleapis.com/kubebuilder-tools/kubebuilder-tools-${K8S_VERSION}-linux-${ARCH}.tar.gz" \
    | tar xz
fi
export PATH="$BIN:$PATH"

echo ">> generating service-account keypair + admin token"
mkdir -p certs
[ -f certs/sa.key ] || openssl genrsa -out certs/sa.key 2048 2>/dev/null
[ -f certs/sa.pub ] || openssl rsa -in certs/sa.key -pubout -out certs/sa.pub 2>/dev/null
echo 'envtest-token,envtest-admin,envtest-admin,system:masters' > certs/tokens.csv

cleanup() {
  kill "${PROXY_PID:-0}" "${APISERVER_PID:-0}" "${ETCD_PID:-0}" 2>/dev/null || true
}
trap cleanup EXIT

echo ">> starting etcd"
etcd --data-dir "$WORK/etcd-data" \
  --listen-client-urls http://127.0.0.1:2379 \
  --advertise-client-urls http://127.0.0.1:2379 \
  >"$WORK/etcd.log" 2>&1 &
ETCD_PID=$!

echo ">> starting kube-apiserver"
kube-apiserver \
  --etcd-servers=http://127.0.0.1:2379 \
  --cert-dir="$WORK/certs" \
  --secure-port=6443 \
  --service-account-issuer=https://kubernetes.default.svc \
  --service-account-key-file="$WORK/certs/sa.pub" \
  --service-account-signing-key-file="$WORK/certs/sa.key" \
  --token-auth-file="$WORK/certs/tokens.csv" \
  --authorization-mode=AlwaysAllow \
  --disable-admission-plugins=ServiceAccount \
  >"$WORK/apiserver.log" 2>&1 &
APISERVER_PID=$!

echo ">> writing kubeconfig + waiting for readiness"
cat > "$WORK/kubeconfig" <<EOF
apiVersion: v1
kind: Config
clusters:
- name: envtest
  cluster: {server: "https://127.0.0.1:6443", insecure-skip-tls-verify: true}
users:
- name: envtest
  user: {token: envtest-token}
contexts:
- name: envtest
  context: {cluster: envtest, user: envtest}
current-context: envtest
EOF
export KUBECONFIG="$WORK/kubeconfig"
for i in $(seq 1 60); do
  kubectl get --raw /readyz >/dev/null 2>&1 && break
  [ "$i" = 60 ] && { echo "apiserver never became ready"; tail -40 "$WORK/apiserver.log"; exit 1; }
  sleep 1
done

echo ">> applying the karpenter.sh CRD + exposing plain HTTP via kubectl proxy"
REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
kubectl apply -f "$REPO_ROOT/deploy/crd.yaml"
kubectl proxy --port="$PROXY_PORT" >"$WORK/proxy.log" 2>&1 &
PROXY_PID=$!
for i in $(seq 1 30); do
  curl -fsS "http://127.0.0.1:$PROXY_PORT/readyz" >/dev/null 2>&1 && break
  [ "$i" = 30 ] && { echo "kubectl proxy never came up"; exit 1; }
  sleep 1
done

echo ">> running the conformance suite against the REAL apiserver"
cd "$REPO_ROOT"
KARPENTER_TEST_APISERVER="http://127.0.0.1:$PROXY_PORT" \
  python -m pytest tests/test_apiserver.py -q
