#!/usr/bin/env python
"""Minimal chart renderer for CI (helm-compatible template subset).

Supports exactly the constructs charts/karpenter-tpu/templates use:
``{{ .Values.dotted.path }}`` substitution (scalars inline; mappings as
flow-style YAML) and whole-line ``{{- if .Values.flag }}`` /
``{{- if not .Values.flag }}`` / ``{{- end }}`` boolean gates. Real deployments can use helm directly — the templates stay
inside helm's syntax — this exists so `make chart` verifies rendering
without a helm binary.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

try:
    import yaml  # type: ignore
except ImportError:
    yaml = None


def load_values(path: Path) -> dict:
    if yaml is not None:
        return yaml.safe_load(path.read_text())
    raise SystemExit("pyyaml required")


def lookup(values: dict, dotted: str):
    cur = values
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            raise KeyError(f"missing value: .Values.{dotted}")
        cur = cur[part]
    return cur


def fmt(value) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, dict):
        # flow-style mapping, valid inline YAML
        inner = ", ".join(f"{k!r}: {fmt(v)}" for k, v in value.items())
        return "{" + inner + "}"
    return str(value)


def render(template: str, values: dict) -> str:
    out_lines = []
    skip_depth = 0
    for line in template.splitlines():
        m_if = re.match(r"\s*\{\{-? if (not )?\.Values\.([\w.]+) \}\}\s*$", line)
        m_end = re.match(r"\s*\{\{-? end \}\}\s*$", line)
        if m_if:
            truthy = bool(lookup(values, m_if.group(2)))
            if m_if.group(1):
                truthy = not truthy
            if skip_depth or not truthy:
                skip_depth += 1
            continue
        if m_end:
            if skip_depth:
                skip_depth -= 1
            continue
        if skip_depth:
            continue
        line = re.sub(
            r"\{\{ \.Values\.([\w.]+) \}\}",
            lambda m: fmt(lookup(values, m.group(1))),
            line,
        )
        out_lines.append(line)
    return "\n".join(out_lines) + "\n"


def main() -> int:
    chart = Path(sys.argv[1] if len(sys.argv) > 1 else "charts/karpenter-tpu")
    values = load_values(chart / "values.yaml")
    docs = []
    for crd in sorted((chart / "crds").glob("*.yaml")):
        docs.append(crd.read_text())
    for tpl in sorted((chart / "templates").glob("*.yaml")):
        rendered = render(tpl.read_text(), values)
        if rendered.strip():
            docs.append(rendered)
    out = "\n---\n".join(docs)
    if yaml is not None:  # validate every rendered document parses
        for doc in out.split("\n---\n"):
            for parsed in yaml.safe_load_all(doc):
                pass
    sys.stdout.write(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
