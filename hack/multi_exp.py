"""Scratch experiment: TPU sharded multi-solve vs native CPU loop at several B."""
import random
import sys
import time

sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as PS

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.parallel.sharding import make_solver_mesh, sharded_multi_solve
from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
from karpenter_tpu.scheduling.topology import Topology
from karpenter_tpu.solver import encode as enc
from karpenter_tpu.solver.native import native_available, pack_native

assert native_available(wait=120), "native packer build failed"
from karpenter_tpu.testing import diverse_pods, make_provisioner

catalog = sorted(instance_types(400), key=lambda it: it.effective_price())
FIELDS = ("pod_valid", "pod_open_sig", "pod_core", "pod_host",
          "pod_host_in_base", "pod_open_host", "pod_req",
          "join_table", "frontiers", "daemon")


def build(B, n_pods):
    batches = []
    for b in range(B):
        provisioner = make_provisioner(name=f"prov-{b}")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(100 + b)))
        cc = c.clone()
        Topology(Cluster(), rng=random.Random(b)).inject(cc, pods)
        daemon = daemon_overhead(Cluster(), cc)
        batches.append(enc.encode(cc, catalog, pods, daemon))
    return batches


x = np.zeros(8, np.float32)
f = jax.jit(lambda a: a + 1)
jax.device_get(f(x))
rtts = []
for i in range(5):
    t0 = time.perf_counter()
    jax.device_get(f(x + (i + 1) * 1e-6))
    rtts.append(time.perf_counter() - t0)
floor = min(rtts)
print(f"rtt floor {floor*1e3:.1f} ms", flush=True)

n_pods = 1250
for B in (8, 32, 64):
    batches = build(B, n_pods)
    arrays = tuple(np.stack([np.asarray(getattr(b, fl)) for b in batches]) for fl in FIELDS)
    sig_type_mask = np.stack([b.type_mask_matrix() for b in batches])
    prices = np.array([it.effective_price() for it in catalog], np.float32)
    mesh = make_solver_mesh()
    n_max = max(256, len(batches[0].pod_valid) // 4)
    n_real = batches[0].n_pods

    pad_mask = np.zeros(arrays[6].shape, np.float32)
    pad_mask[:, n_real:, :] = 1.0
    sh = NamedSharding(mesh, PS("data", None, None))
    base_req = jax.device_put(arrays[6], sh)
    mask_dev = jax.device_put(pad_mask, sh)
    perturb = jax.jit(lambda base, m, eps: base + m * eps)
    placed = list(arrays)

    def run(eps):
        placed[6] = perturb(base_req, mask_dev, eps)
        result, cheapest, _ = sharded_multi_solve(
            mesh, tuple(placed), sig_type_mask, batches[0].usable, prices, n_max=n_max
        )
        jax.device_get((result.n_nodes, cheapest[:, 0]))
        return result

    result = run(0.0)
    specs = [PS("data")] * 6 + [None, PS("data", None, None),
                                PS("data", None, None, None), PS("data", None)]
    for i, s in enumerate(specs):
        if i == 6:
            continue
        placed[i] = jax.device_put(arrays[i], NamedSharding(mesh, s))
    run(0.0)
    times = []
    for it in range(6):
        t0 = time.perf_counter()
        run((it + 1) * 1e-7)
        times.append(time.perf_counter() - t0)
    best = min(times)
    scheduled = int((np.asarray(result.assignment)[:, :n_real] >= 0).sum())

    cpu_times = []
    for _ in range(4):
        t0 = time.perf_counter()
        tot = 0
        for b in batches:
            r = pack_native(*b.pack_args(), n_max=n_max)
            tot += int((np.asarray(r.assignment)[: b.n_pods] >= 0).sum())
        cpu_times.append(time.perf_counter() - t0)
    cpu_best = min(cpu_times)
    print(
        f"B={B:3d}: tpu wall {best*1e3:7.1f}ms adj {(best-floor)*1e3:7.1f}ms "
        f"{scheduled/best:10.0f} raw {scheduled/max(best-floor,1e-9):12.0f} adj pods/s | "
        f"cpu {cpu_best*1e3:6.1f}ms {tot/cpu_best:12.0f} pods/s",
        flush=True,
    )
