"""Replay a persisted decision record through the native packer, offline.

The PR-10 canary re-solves a SAMPLED live pack and quarantines on
disagreement; this is its forensic twin for the decision audit log
(docs/decisions.md): a record persisted into ``--decision-dir`` carries the
exact kernel tensors (``EncodedBatch.pack_args`` order) plus the served
assignment and node-table size, so any decision can be re-solved on the
native C++ packer long after the fact — on a laptop, from a support
bundle — and diffed bit-exact against what production actually did.

Usage::

    python -m tools.replay_decision <record.json>           # one file
    python -m tools.replay_decision --decision-dir DIR      # newest replayable
    python -m tools.replay_decision --decision-dir DIR --id d-abc123...

Exit codes: 0 = assignment reproduced bit-exact, 1 = divergence (prints
the first difference — the smoking gun), 2 = record unusable (no replay
blob: memory-only rounds and FFD-degraded rounds don't carry tensors).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

import numpy as np


def load_record(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def find_record(
    directory: str, record_id: Optional[str] = None
) -> Optional[str]:
    """Newest replayable record in the ring (lexicographic filename IS
    recency order — the flight-recorder discipline), or the one matching
    ``record_id``."""
    try:
        names = sorted(
            (
                n for n in os.listdir(directory)
                if n.startswith("decision-") and n.endswith(".json")
            ),
            reverse=True,
        )
    except OSError:
        return None
    for name in names:
        path = os.path.join(directory, name)
        try:
            rec = load_record(path)
        except (OSError, json.JSONDecodeError):
            continue
        if record_id is not None and rec.get("id") != record_id:
            continue
        if record_id is not None or "replay_file" in rec:
            return path
    return None


def replay(record: Dict[str, Any], record_path: str = "") -> Dict[str, Any]:
    """Re-solve the record's tensors on the native packer and diff.

    Returns ``{"ok": bool, "diff": str|None, ...}``; raises ValueError
    when the record has no replay sidecar."""
    from karpenter_tpu.obs.decisions import PACK_ARG_NAMES
    from karpenter_tpu.solver import native

    replay_file = record.get("replay_file")
    if not replay_file:
        raise ValueError(
            "record has no replay sidecar (memory-only or FFD-degraded round)"
        )
    npz_path = os.path.join(os.path.dirname(record_path) or ".", replay_file)
    blob = np.load(npz_path, allow_pickle=False)
    if not native.native_available(wait=180.0):
        raise RuntimeError("native packer unavailable (g++ build failed?)")

    def arg(name: str) -> np.ndarray:
        if name == "pod_req" and "pod_req" not in blob:
            # compact transfer form: re-gather the dense request matrix
            # from the unique vectors + per-pod ids (bit-identical to the
            # encode-side gather)
            return blob["uniq_req"][blob["pod_req_id"]]
        return blob[name]

    args = [arg(n) for n in PACK_ARG_NAMES]
    n_max = int(blob["n_max"])
    n_pods = int(blob["n_pods"])
    result = native.pack_native(*args, n_max=n_max)
    fresh = np.asarray(result.assignment)[:n_pods]
    out: Dict[str, Any] = {
        "decision_id": record.get("id"),
        "route": record.get("route"),
        "n_pods": n_pods,
        "n_max": n_max,
        "replay_nodes": int(result.n_nodes),
        "replay_unschedulable": int((fresh < 0).sum()),
    }
    if "assignment" not in blob:
        out["ok"] = None
        out["diff"] = "record carries no served assignment to diff against"
        return out
    served = np.asarray(blob["assignment"]).reshape(-1)[:n_pods]
    if np.array_equal(served, fresh):
        out["ok"] = True
        out["diff"] = None
        return out
    idx = np.flatnonzero(served != fresh)
    pod_keys: List[str] = record.get("pod_keys") or []
    first = int(idx[0])
    out["ok"] = False
    out["diverged_pods"] = int(len(idx))
    out["diff"] = (
        f"assignment differs for {len(idx)} pod(s); first: "
        f"{pod_keys[first] if first < len(pod_keys) else f'index {first}'} "
        f"served node {int(served[first])} vs replay {int(fresh[first])}"
    )
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="replay_decision",
        description="re-solve a persisted decision record on the native "
        "packer and diff the assignment bit-exact",
    )
    ap.add_argument("record", nargs="?", help="path to a decision-*.json")
    ap.add_argument("--decision-dir", default="",
                    help="ring directory; picks the newest replayable "
                    "record (or --id)")
    ap.add_argument("--id", default=None, help="decision id to replay")
    args = ap.parse_args(argv)

    path = args.record
    if not path and args.decision_dir:
        path = find_record(args.decision_dir, record_id=args.id)
    if not path:
        print("replay_decision: no record found", file=sys.stderr)
        return 2
    try:
        record = load_record(path)
        verdict = replay(record, record_path=path)
    except (ValueError, RuntimeError, OSError, json.JSONDecodeError) as e:
        print(f"replay_decision: {path}: {e}", file=sys.stderr)
        return 2
    print(json.dumps({"record": path, **verdict}))
    if verdict["ok"] is None:
        return 2  # nothing to diff against — not a pass, not a finding
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
