"""Retry/idempotency discipline.

``resilience.RetryPolicy`` re-invokes its callable on transient failure.
That is only sound when the callable is idempotent — and the codebase
marks that property explicitly with ``@idempotent``
(``karpenter_tpu.resilience.idempotent``). Two enforcement surfaces:

1. **Direct call sites**: ``policy.call(fn, ...)`` where ``policy`` is a
   ``RetryPolicy`` constructed with ``max_attempts > 1`` and ``fn``
   resolves to a def in the same file — the def must carry
   ``@idempotent``. Unresolvable callables (parameters, bound methods of
   arbitrary objects) are skipped, not guessed at.

2. **The provider interface**: concrete ``CloudProvider`` implementations
   (classes under ``cloudprovider/`` defining both ``create`` and
   ``delete``) are wrapped by the metered decorator, whose policy table
   retries ``delete`` / ``get_instance_types`` / ``poll_disruptions`` —
   those methods must be ``@idempotent``. ``create`` is two-sided since
   the launch-token work: a TOKEN-CARRYING create (its body consumes
   ``launch_token`` — the request's idempotency key that providers replay
   instead of double-launching) is retried by the metered policy table
   and must be ``@idempotent``; a token-LESS create marked
   ``@idempotent`` is itself a finding — without the token contract the
   marker would invite retries that orphan instances.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from tools.karplint.core import (
    P0,
    Finding,
    Project,
    Rule,
    SourceFile,
    decorator_names,
    dotted_name,
    register,
)

RETRIED_PROVIDER_METHODS = ("delete", "get_instance_types", "poll_disruptions")


def _has_idempotent(fn: ast.AST) -> bool:
    return any(dn.rsplit(".", 1)[-1] == "idempotent" for dn in decorator_names(fn))


def _token_aware(fn: ast.AST) -> bool:
    """Does this create's body consume the launch token? Token-carrying
    creates replay a committed token instead of double-launching, which is
    the property that makes the @idempotent marker (and therefore retries)
    sound. Detected syntactically: any ``launch_token`` name/attribute, or
    a ``launchToken`` wire-field string, in the body."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr == "launch_token":
            return True
        if isinstance(node, ast.Name) and node.id == "launch_token":
            return True
        if isinstance(node, ast.keyword) and node.arg in (
            "launch_token", "client_token",
        ):
            return True
        if isinstance(node, ast.Constant) and node.value == "launchToken":
            return True
    return False


def _is_abstract(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        dn = dotted_name(base) or ""
        if dn.rsplit(".", 1)[-1] == "ABC":
            return True
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(
                dn.rsplit(".", 1)[-1] == "abstractmethod"
                for dn in decorator_names(node)
            ):
                return True
    return False


def _max_attempts(call: ast.Call) -> int:
    for kw in call.keywords:
        if kw.arg == "max_attempts":
            if isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, int):
                return kw.value.value
            return 99  # dynamic — assume retrying
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, int
    ):
        return call.args[0].value
    return 3  # RetryPolicy's default


@register
class RetryIdempotentRule(Rule):
    name = "retry-idempotent"
    severity = P0
    doc = (
        "A callable retried by RetryPolicy lacks the @idempotent marker, "
        "or a token-less create-path mutator carries it — retrying a "
        "non-idempotent mutator double-applies it; marking a create that "
        "does not consume a launch token invites retries that orphan "
        "instances, while a token-carrying create IS retried by the "
        "metered policy table and must be marked."
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.files:
            self._check_call_sites(src, findings)
            if "cloudprovider/" in src.path:
                self._check_providers(src, findings)
        return findings

    def _check_call_sites(self, src: SourceFile, findings: List[Finding]) -> None:
        # policy name -> max_attempts, from RetryPolicy(...) constructions
        policies: Dict[str, int] = {}
        local_defs: Dict[str, ast.AST] = {}
        for node in src.nodes():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs.setdefault(node.name, node)
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target, value = node.targets[0], node.value
            tname = dotted_name(target)
            if tname is None:
                continue
            if isinstance(value, ast.Call) and (dotted_name(value.func) or "").endswith(
                "RetryPolicy"
            ):
                policies[tname] = _max_attempts(value)
            elif isinstance(value, ast.Dict):
                # a policy table: dict of RetryPolicy values; dynamic keying
                # means any retrying entry makes the table "retrying"
                attempts = [
                    _max_attempts(v)
                    for v in value.values
                    if isinstance(v, ast.Call)
                    and (dotted_name(v.func) or "").endswith("RetryPolicy")
                ]
                if attempts:
                    policies[tname] = max(attempts)

        if not policies:
            return
        for node in src.nodes():
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "call"
                and node.args
            ):
                continue
            receiver = node.func.value
            if isinstance(receiver, ast.Subscript):
                receiver = receiver.value
            rname = dotted_name(receiver)
            if rname is None or rname not in policies:
                continue
            if policies[rname] <= 1:
                continue  # breaker-only policy: no retry, no marker needed
            callee = node.args[0]
            if isinstance(callee, ast.Name) and callee.id in local_defs:
                if not _has_idempotent(local_defs[callee.id]):
                    findings.append(
                        self.finding(
                            src.path, node.lineno,
                            f"`{callee.id}` is retried by `{rname}` "
                            "(max_attempts > 1) but is not marked @idempotent",
                        )
                    )
            elif isinstance(callee, ast.Lambda):
                findings.append(
                    self.finding(
                        src.path, node.lineno,
                        f"a lambda is retried by `{rname}` — retried callables "
                        "must be named, @idempotent functions",
                    )
                )

    def _check_providers(self, src: SourceFile, findings: List[Finding]) -> None:
        for node in src.tree.body:
            if not isinstance(node, ast.ClassDef) or _is_abstract(node):
                continue
            methods = {
                m.name: m
                for m in node.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            if not all(m in methods for m in ("create", "delete", "get_instance_types")):
                continue  # not a CloudProvider implementation
            for name in RETRIED_PROVIDER_METHODS:
                m = methods.get(name)
                if m is not None and not _has_idempotent(m):
                    findings.append(
                        self.finding(
                            src.path, m.lineno,
                            f"`{node.name}.{name}` is retried by the metered "
                            "cloud decorator but is not marked @idempotent",
                        )
                    )
            create = methods["create"]
            if _has_idempotent(create) and not _token_aware(create):
                findings.append(
                    self.finding(
                        src.path, create.lineno,
                        f"`{node.name}.create` is marked @idempotent but never "
                        "consumes a launch token — without token replay a "
                        "retried create double-launches; thread "
                        "request.launch_token through (or remove the marker)",
                    )
                )
            elif _token_aware(create) and not _has_idempotent(create):
                findings.append(
                    self.finding(
                        src.path, create.lineno,
                        f"`{node.name}.create` consumes a launch token (same "
                        "token → same instance) and is retried by the metered "
                        "cloud decorator, but is not marked @idempotent",
                    )
                )
