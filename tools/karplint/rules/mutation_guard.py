"""Ownership/fencing reachability for cloud mutations (``mutation-guard``).

The karpenter-tpu fencing contract: before a controller mutates cloud
state — creating a fleet, deleting or terminating capacity — it must have
proven it still OWNS the resource and holds a valid fence (PR-6/PR-11:
a stale leader that kept deleting nodes after losing its lease). The
proof is a call to one of the guard predicates (``owned()`` / ``fenced()``
/ ``_owns()``) somewhere on every call-graph path from the reconcile
entry point to the mutation call site.

This rule checks exactly that, interprocedurally, over ``controllers/``,
``launch/`` and ``interruption/``:

- **mutation sites**: calls spelled ``<recv>.create(...)``,
  ``<recv>.create_fleet(...)``, ``<recv>.delete(...)`` or
  ``<recv>.terminate(...)`` whose receiver chain names a cloud surface
  (``cloud_provider`` / ``provider`` / ``terminator``);
- **guarded**: the enclosing function performs a guard call lexically
  before the mutation line, or every unguarded call-graph path from a
  ``reconcile*`` entry is cut by a function that performs a guard call;
- **exempt**: the site (or the line above it) carries
  ``# mutation-guard: exempt — <why>``. The marker is for paths where
  the cloud itself is the source of truth (e.g. interruption handling:
  the provider already announced the capacity is going away, so fencing
  adds nothing) and makes the exemption grep-able instead of implicit.

A mutation helper that is never reachable from any reconcile entry is
not flagged — the contract is about the reconcile loops, and dead or
externally-driven code would only produce noise. P0: an unfenced delete
from a stale leader is split-brain capacity loss, never baselineable.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.karplint.callgraph import FuncInfo, get_graph, walk_no_funcs
from tools.karplint.core import (
    P0,
    Finding,
    Project,
    Rule,
    dotted_name,
    register,
)

MUTATING_ATTRS = {"create", "create_fleet", "delete", "terminate"}
CLOUD_RECEIVERS = ("cloud_provider", "provider", "terminator")
GUARD_TAILS = {"owned", "owns", "fenced", "_owns", "is_owned", "is_fenced"}
EXEMPT_RE = re.compile(r"#\s*mutation-guard:\s*exempt")

SCOPED_DIRS = ("controllers/", "launch/", "interruption/")


def _is_mutation(call: ast.Call) -> Optional[str]:
    """Dotted receiver chain when ``call`` is a cloud mutation, else None."""
    func = call.func
    if not isinstance(func, ast.Attribute) or func.attr not in MUTATING_ATTRS:
        return None
    recv = dotted_name(func.value)
    if recv is None:
        return None
    segments = recv.split(".")
    if any(seg in CLOUD_RECEIVERS for seg in segments):
        return f"{recv}.{func.attr}"
    return None


def _is_guard_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dn = dotted_name(node.func)
    if dn is None:
        return False
    return dn.rsplit(".", 1)[-1] in GUARD_TAILS


def _checks_guard(fn: FuncInfo) -> bool:
    return any(_is_guard_call(n) for n in walk_no_funcs(fn.node))


def _guard_line_before(fn: FuncInfo, lineno: int) -> bool:
    """A guard call lexically at or before ``lineno`` in this function —
    covers both ``if not self.owned(): return`` prologues and guards in
    the ``if self.fenced(...):`` test whose body holds the mutation."""
    for node in walk_no_funcs(fn.node):
        if _is_guard_call(node) and node.lineno <= lineno:
            return True
    return False


def _exempt(fn: FuncInfo, lineno: int) -> bool:
    for ln in (lineno, lineno - 1):
        if EXEMPT_RE.search(fn.file.line_at(ln)):
            return True
    return False


@register
class MutationGuardRule(Rule):
    name = "mutation-guard"
    severity = P0
    doc = (
        "cloud mutation (create/create_fleet/delete/terminate) reachable "
        "from a reconcile entry with no owned()/fenced() check on the "
        "path — a stale leader would mutate capacity it no longer owns; "
        "guard it or mark `# mutation-guard: exempt — <why>`."
    )
    path_must_contain = SCOPED_DIRS

    def run(self, project: Project) -> List[Finding]:
        scoped = self.files(project)
        if not scoped:
            return []
        graph = get_graph(project)
        scoped_paths = {f.path for f in scoped}

        # BFS from reconcile* entries; a function that itself checks a
        # guard cuts the walk — everything it calls runs post-proof.
        unguarded: Set[int] = set()
        work: List[FuncInfo] = [
            fn
            for fn in graph.funcs
            if fn.file.path in scoped_paths and fn.name.startswith("reconcile")
        ]
        while work:
            fn = work.pop()
            if id(fn) in unguarded:
                continue
            unguarded.add(id(fn))
            if _checks_guard(fn):
                continue
            work.extend(graph.callees(fn))

        findings: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()
        for fn in graph.funcs:
            if fn.file.path not in scoped_paths:
                continue
            if id(fn) not in unguarded:
                continue
            for node in walk_no_funcs(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                target = _is_mutation(node)
                if target is None:
                    continue
                if _guard_line_before(fn, node.lineno):
                    continue
                if _exempt(fn, node.lineno):
                    continue
                key = (fn.file.path, node.lineno)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(
                    self.finding(
                        fn.file.path, node.lineno,
                        f"cloud mutation `{target}` in `{fn.qualname}` is "
                        "reachable from a reconcile entry with no owned()/"
                        "fenced() check on the path — a stale leader would "
                        "mutate capacity it no longer owns; add the guard "
                        "or `# mutation-guard: exempt — <why>`",
                    )
                )
        findings.sort(key=lambda f: (f.path, f.line))
        return findings
