"""Span discipline for the tracing subsystem (karpenter_tpu/obs).

Two invariants, one rule name (``span-closed``):

1. **Context-manager only.** Spans may only be opened via
   ``with tracer.span(...)``. A bare ``start_span`` call anywhere outside
   ``karpenter_tpu/obs/`` is a finding: the Span it returns never resets
   the ambient contextvar and never exports — every later span in that
   context silently mis-parents, which is exactly the class of corruption
   no test notices until a trace tree looks wrong in an incident.

2. **Tracer safety.** No ``obs`` call may be reachable from jit/vmap/
   pallas-traced solver code (reusing the tracer rules' cross-file call
   graph). A span is host-side Python — inside traced code it either
   breaks tracing outright or silently forces a host sync per solve,
   erasing the <100ms target while every correctness test stays green.
   P0, like the other tracer-safety rules.
"""

from __future__ import annotations

import ast
from typing import List

from tools.karplint.core import (
    P0,
    P1,
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted_name,
    register,
)
from tools.karplint.callgraph import get_graph, walk_no_funcs

OBS_MODULE = "karpenter_tpu.obs"


def _in_obs_package(path: str) -> bool:
    # segment match, not substring: a future jobs/ or blobs/ directory
    # must NOT inherit the obs implementation's exemption
    parts = path.split("/")
    return "obs" in parts[:-1] or parts[-1] == "obs.py"


def _obs_aliases(f: SourceFile) -> set:
    """Local names that refer to the obs package or its members."""
    from tools.karplint.core import import_tables

    modules, symbols = import_tables(f.tree)
    out = set()
    for alias, mod in modules.items():
        if mod == OBS_MODULE or mod.startswith(OBS_MODULE + "."):
            out.add(alias)
    for alias, (mod, _sym) in symbols.items():
        if mod == OBS_MODULE or mod.startswith(OBS_MODULE + "."):
            out.add(alias)
    return out


@register
class SpanClosedRule(Rule):
    name = "span-closed"
    severity = P1
    doc = (
        "Spans must be opened via `with tracer.span(...)` — a bare "
        "start_span call leaks an open span (P1); and no obs call may be "
        "reachable from jit/vmap/pallas-traced solver code (P0)."
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        self._check_start_span(project, findings)
        self._check_jit_reachable(project, findings)
        return findings

    # -- invariant 1: no bare start_span ------------------------------------
    def _check_start_span(self, project: Project, findings: List[Finding]) -> None:
        for f in project.files:
            if _in_obs_package(f.path):
                continue  # the implementation (and its tests' fixtures)
            for node in f.nodes():
                if not isinstance(node, ast.Call):
                    continue
                # match the attribute/name directly, not via dotted_name:
                # the receiver is usually itself a call (obs.tracer()),
                # which a Name/Attribute chain walk cannot resolve
                func = node.func
                called = (
                    func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name)
                    else ""
                )
                if called != "start_span":
                    continue
                findings.append(
                    self.finding(
                        f.path, node.lineno,
                        "bare `start_span` call — spans may only be opened "
                        "via `with tracer.span(...)` (an unmanaged span "
                        "never closes, never exports, and mis-parents every "
                        "later span in this context)",
                    )
                )

    # -- invariant 2: obs unreachable from traced code ----------------------
    def _check_jit_reachable(self, project: Project, findings: List[Finding]) -> None:
        files = project.matching(lambda p: "solver/" in p)
        if not files:
            return
        graph = get_graph(project, files)
        reachable = graph.reachable()
        for fn in reachable:
            aliases = _obs_aliases(fn.file)
            if not aliases:
                continue
            for node in walk_no_funcs(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                dn = dotted_name(node.func) or ""
                root = dn.split(".", 1)[0]
                if root in aliases:
                    findings.append(
                        self.finding(
                            fn.file.path, node.lineno,
                            f"obs call `{dn}` reachable from jit/vmap/pallas-"
                            f"traced code (via `{fn.qualname}`) — host-side "
                            "span machinery inside traced code serializes "
                            "the device pipeline",
                            severity=P0,
                        )
                    )
