"""Event auditability: decision-path Warning events carry a decision id.

The decision observability plane (docs/decisions.md) made every
provisioning round a recorded, replayable ``DecisionRecord`` — and the
``karpenter.sh/decision-id`` Event annotation is how an operator walks
from a ``kubectl describe`` Warning straight into ``/debug/decisions``
(and the ``--decision-dir`` ring ``tools/replay_decision.py`` re-solves).
A Warning emitted from a provisioning/consolidation decision path WITHOUT
the id is an audit dead end: the operator sees "pod shed" / "launch
failed" with no way back to the decision that caused it.

Detection: in any file on a decision path (path contains ``provision`` or
``consolidation``), every ``.event(...)`` call that passes
``type="Warning"`` must also pass a ``decision_id=`` keyword (the
recorder annotates it; an empty value is allowed — it means "before the
first record", which is honest). Normal events and non-decision-path
files stay clean.
"""

from __future__ import annotations

import ast
from typing import List

from tools.karplint.core import (
    P1,
    Finding,
    Project,
    Rule,
    SourceFile,
    register,
)


def _on_decision_path(path: str) -> bool:
    base = path.rsplit("/", 1)[-1]
    # incident files (the regression sentinel's IncidentDetected site)
    # are decision-path even under obs/: an incident whose window held
    # provisioning rounds must annotate one of their decision ids, or the
    # operator's path from the Warning into /debug/decisions is severed
    if "incident" in base:
        return True
    return ("provision" in base or "consolidation" in base) and not (
        "/obs/" in path or path.startswith("obs/")
    )


def _kw(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw
    return None


@register
class EventDecisionIdRule(Rule):
    name = "event-decision-id"
    severity = P1
    doc = (
        "a Warning event emitted from a provisioning/consolidation "
        "decision path does not carry the decision-id annotation "
        "(decision_id= keyword) — the operator's path from `kubectl "
        "describe` into /debug/decisions and the replayable ring is "
        "severed (docs/decisions.md)."
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.files:
            if not _on_decision_path(src.path):
                continue
            # cheap text prefilter: no Warning literal, no finding
            if "Warning" not in src.text:
                continue
            for node in src.nodes():
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if not (isinstance(func, ast.Attribute) and func.attr == "event"):
                    continue
                type_kw = _kw(node, "type")
                if type_kw is None or not (
                    isinstance(type_kw.value, ast.Constant)
                    and type_kw.value.value == "Warning"
                ):
                    continue
                if _kw(node, "decision_id") is None:
                    findings.append(self.finding(
                        src.path, node.lineno,
                        "Warning event on a decision path without a "
                        "decision_id= keyword; pass the current round's "
                        "decision id (empty string before the first "
                        "record) so the event annotates "
                        "karpenter.sh/decision-id",
                    ))
        return findings
