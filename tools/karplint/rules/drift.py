"""Cross-artifact drift gate (``drift-flag`` / ``drift-chart`` /
``drift-status``).

The configuration surface lives in five places that nothing previously
tied together: ``options.py`` (flags + env twins), ``docs/operations.md``
(the operator-facing flag table), ``deploy/*.yaml`` (the reference
manifests), the Helm chart (``values.yaml`` + templates), and the solver
wire constants (``STATUS_*`` / ``PROTO_*``) with their fuzz corpus. Every
past drift incident was a surface updated on one side only — a flag
shipped without a docs row, a manifest arg the chart cannot render, a
wire constant one codec end never learned. These rules parse each
artifact and cross-check, so the gap is a finding with a fix-it hint
instead of an operator surprise.

Scoping: findings must anchor at a Python file the analyzer scanned, so
each rule anchors at the artifact root's config surface (``options.py``
for flag/chart drift, the wire-constants module for status drift). The
artifact root is found by walking up from that file to the nearest
directory containing the sibling artifacts (``docs/`` / ``deploy/`` /
``charts/``, or ``tests/`` for the fuzz corpus) — which also lets the
fixture corpus carry self-contained artifact trees.

Deliberate non-goals: the solver/webhook entrypoints parse their own
small arg sets; only files *named* ``options.py`` are treated as a flag
surface. And the raw ``deploy/`` manifest is one concrete configuration
while the chart is the configurable superset — so the chart must be able
to render every deploy flag, but not vice versa.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.karplint.core import (
    P1,
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted_name,
    register,
)

FLAG_TOKEN_RE = re.compile(r"(?<![\w-])--([a-z][a-z0-9-]*)")
VALUES_REF_RE = re.compile(r"\.Values\.([A-Za-z0-9_.]+)")
WIRE_CONST_RE = re.compile(r"^(STATUS|PROTO)_[A-Z0-9_]+$")
ENV_FNS = {"_env", "env_bool", "env_float", "env_int", "env_str"}


def _nearest_root(project: Project, pypath: str, markers: Sequence[str]) -> Optional[str]:
    """Nearest ancestor dir (as a ''-or-'a/b' prefix relative to the
    project root) containing one of ``markers`` as a subdirectory."""
    parts = pypath.split("/")[:-1]
    while True:
        prefix = "/".join(parts)
        base = project.root / prefix if prefix else project.root
        if any((base / m).is_dir() for m in markers):
            return prefix
        if not parts:
            return None
        parts.pop()


def _read(project: Project, relpath: str) -> Optional[str]:
    p = project.root / relpath
    try:
        return p.read_text(encoding="utf-8")
    except OSError:
        return None


def _strip_comment(line: str) -> str:
    stripped = line.lstrip()
    if stripped.startswith("#"):
        return ""
    return line.split("#", 1)[0]


def _manifest_flags(text: str) -> Set[str]:
    out: Set[str] = set()
    for line in text.splitlines():
        out.update(FLAG_TOKEN_RE.findall(_strip_comment(line)))
    return out


class _FlagSurface:
    """Flags + env twins parsed out of one ``options.py``."""

    def __init__(self, src: SourceFile):
        self.src = src
        # canonical spelling -> (lineno, all spellings, is_boolean)
        self.flags: Dict[str, Tuple[int, List[str], bool]] = {}
        self.env_keys: Dict[str, int] = {}
        for node in src.nodes():
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func) or ""
            tail = fname.rsplit(".", 1)[-1]
            if tail == "add_argument":
                spellings = [
                    a.value[2:]
                    for a in node.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, str)
                    and a.value.startswith("--")
                ]
                if not spellings:
                    continue
                boolean = any(
                    kw.arg == "action"
                    and (dotted_name(kw.value) or "").endswith("BooleanOptionalAction")
                    for kw in node.keywords
                )
                self.flags[spellings[0]] = (node.lineno, spellings, boolean)
            elif tail in ENV_FNS and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    self.env_keys.setdefault(first.value, node.lineno)

    def spellings(self) -> Set[str]:
        return {s for _, ss, _ in self.flags.values() for s in ss}

    def defined(self, token: str) -> bool:
        """Is ``--token`` a valid spelling (incl. the --no-x boolean twin)?"""
        all_spellings = self.spellings()
        if token in all_spellings:
            return True
        if token.startswith("no-"):
            base = token[3:]
            return any(
                base in ss and boolean for _, ss, boolean in self.flags.values()
            )
        return False

    def normalize(self, token: str) -> str:
        """Map a manifest spelling to the flag's canonical spelling
        (``no-x`` -> ``x`` for booleans, aliases -> primary)."""
        if token.startswith("no-") and self.defined(token):
            token = token[3:]
        for canon, (_ln, ss, _b) in self.flags.items():
            if token in ss:
                return canon
        return token


def _flag_surfaces(project: Project) -> List[_FlagSurface]:
    return [
        _FlagSurface(f)
        for f in project.files
        if f.path.rsplit("/", 1)[-1] == "options.py"
    ]


@register
class DriftFlagRule(Rule):
    name = "drift-flag"
    severity = P1
    doc = (
        "flag/env surface drift: a defined flag or env twin missing from "
        "docs/operations.md, a documented flag nothing defines, or a "
        "deploy/chart manifest passing a flag no add_argument accepts."
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for surface in _flag_surfaces(project):
            root = _nearest_root(project, surface.src.path, ("docs", "deploy", "charts"))
            if root is None:
                continue
            prefix = f"{root}/" if root else ""
            docs_rel = f"{prefix}docs/operations.md"
            docs = _read(project, docs_rel)
            if docs is None:
                findings.append(
                    self.finding(
                        surface.src.path, 1,
                        f"{docs_rel} is missing — every flag and env twin "
                        "must be documented there",
                    )
                )
            else:
                for canon, (lineno, spellings, _b) in sorted(surface.flags.items()):
                    if not any(f"--{s}" in docs for s in spellings):
                        findings.append(
                            self.finding(
                                surface.src.path, lineno,
                                f"flag `--{canon}` has no row in {docs_rel} — "
                                "add it to the flag table (operators discover "
                                "knobs there, not in argparse help)",
                            )
                        )
                for key, lineno in sorted(surface.env_keys.items()):
                    if key not in docs:
                        findings.append(
                            self.finding(
                                surface.src.path, lineno,
                                f"env twin `{key}` is not mentioned in "
                                f"{docs_rel} — document it beside its flag",
                            )
                        )
                findings.extend(self._docs_ghosts(surface, docs, docs_rel))
            findings.extend(self._manifest_ghosts(project, surface, prefix))
        return findings

    def _docs_ghosts(
        self, surface: _FlagSurface, docs: str, docs_rel: str
    ) -> List[Finding]:
        """Documented flags nothing defines (docs rows only — prose may
        reference other processes' flags)."""
        out: List[Finding] = []
        seen: Set[str] = set()
        for line in docs.splitlines():
            if not line.startswith("|") or "--" not in line:
                continue
            cells = line.split("|")
            if len(cells) < 3:
                continue
            first = cells[1]
            if "sidecar" in first:
                continue  # the solver entrypoint's own arg set
            for token in FLAG_TOKEN_RE.findall(first):
                if token in seen or surface.defined(token):
                    continue
                seen.add(token)
                out.append(
                    self.finding(
                        surface.src.path, 1,
                        f"{docs_rel} documents `--{token}`, which no "
                        "add_argument defines — stale row or missing flag",
                    )
                )
        return out

    def _manifest_ghosts(
        self, project: Project, surface: _FlagSurface, prefix: str
    ) -> List[Finding]:
        out: List[Finding] = []
        for rel in _controller_manifests(project, prefix):
            text = _read(project, rel)
            if text is None:
                continue
            for token in sorted(_manifest_flags(text)):
                if not surface.defined(token):
                    out.append(
                        self.finding(
                            surface.src.path, 1,
                            f"{rel} passes `--{token}`, which no add_argument "
                            "defines — the process would die at startup",
                        )
                    )
        return out


def _controller_manifests(project: Project, prefix: str) -> List[str]:
    """Controller manifests under the artifact root: deploy/*controller*
    plus every chart template named *controller*."""
    out: List[str] = []
    base = project.root / prefix if prefix else project.root
    for pattern in ("deploy/*controller*.yaml", "charts/*/templates/*controller*.yaml"):
        for p in sorted(base.glob(pattern)):
            out.append(p.relative_to(project.root).as_posix())
    return out


def _parse_values_keys(text: str) -> Set[str]:
    """Two-level key paths from a values.yaml (hand-rolled: stdlib only).

    ``image: x`` -> ``image``; ``controller:`` + 2-space ``replicas:`` ->
    ``controller.replicas``. Deeper nesting collapses into its 2-level
    parent (templates address those via ``toYaml .Values.a.b``)."""
    keys: Set[str] = set()
    top: Optional[str] = None
    for raw in text.splitlines():
        line = raw.rstrip()
        if not line or line.lstrip().startswith("#"):
            continue
        indent = len(line) - len(line.lstrip())
        m = re.match(r"([A-Za-z0-9_-]+):", line.strip())
        if not m:
            continue
        key = m.group(1)
        if indent == 0:
            top = key
            keys.add(key)
        elif indent == 2 and top is not None:
            keys.add(f"{top}.{key}")
    return keys


@register
class DriftChartRule(Rule):
    name = "drift-chart"
    severity = P1
    doc = (
        "deploy/chart drift: the chart template cannot render a flag the "
        "deploy manifest sets, a template references a .Values key that "
        "values.yaml does not define, or a values.yaml key no template "
        "reads (a knob that silently does nothing)."
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for surface in _flag_surfaces(project):
            root = _nearest_root(project, surface.src.path, ("deploy", "charts"))
            if root is None:
                continue
            prefix = f"{root}/" if root else ""
            base = project.root / prefix if prefix else project.root
            findings.extend(self._deploy_vs_chart(project, surface, prefix))
            for chart_dir in sorted(base.glob("charts/*")):
                if not chart_dir.is_dir():
                    continue
                rel_chart = chart_dir.relative_to(project.root).as_posix()
                findings.extend(
                    self._values_vs_templates(project, surface, rel_chart)
                )
        return findings

    def _deploy_vs_chart(
        self, project: Project, surface: _FlagSurface, prefix: str
    ) -> List[Finding]:
        deploy_flags: Set[str] = set()
        chart_flags: Set[str] = set()
        base = project.root / prefix if prefix else project.root
        deploy_rels: List[str] = []
        for p in sorted(base.glob("deploy/*controller*.yaml")):
            rel = p.relative_to(project.root).as_posix()
            deploy_rels.append(rel)
            deploy_flags |= {
                surface.normalize(t) for t in _manifest_flags(_read(project, rel) or "")
            }
        for p in sorted(base.glob("charts/*/templates/*controller*.yaml")):
            rel = p.relative_to(project.root).as_posix()
            chart_flags |= {
                surface.normalize(t) for t in _manifest_flags(_read(project, rel) or "")
            }
        if not deploy_rels or not chart_flags:
            return []
        out: List[Finding] = []
        for token in sorted(deploy_flags - chart_flags):
            if not surface.defined(token):
                continue  # drift-flag already reports undefined tokens
            out.append(
                self.finding(
                    surface.src.path, 1,
                    f"{deploy_rels[0]} sets `--{token}` but the chart's "
                    "controller template cannot render it — add a values "
                    "key + template arg so chart installs can express the "
                    "reference configuration",
                )
            )
        return out

    def _values_vs_templates(
        self, project: Project, surface: _FlagSurface, rel_chart: str
    ) -> List[Finding]:
        values_rel = f"{rel_chart}/values.yaml"
        values_text = _read(project, values_rel)
        if values_text is None:
            return []
        keys = _parse_values_keys(values_text)
        refs: Set[str] = set()
        tmpl_dir = project.root / rel_chart / "templates"
        for p in sorted(tmpl_dir.glob("*.yaml")) if tmpl_dir.is_dir() else []:
            refs |= set(VALUES_REF_RE.findall(p.read_text(encoding="utf-8")))
        if not refs:
            return []
        out: List[Finding] = []

        def covered_by_keys(ref: str) -> bool:
            return any(
                ref == k or ref.startswith(k + ".") or k.startswith(ref + ".")
                for k in keys
            )

        def referenced(key: str) -> bool:
            return any(
                r == key or r.startswith(key + ".") or key.startswith(r + ".")
                for r in refs
            )

        for ref in sorted(refs):
            if not covered_by_keys(ref):
                out.append(
                    self.finding(
                        surface.src.path, 1,
                        f"chart template references `.Values.{ref}` but "
                        f"{values_rel} defines no such key — `helm install` "
                        "renders an empty value",
                    )
                )
        for key in sorted(keys):
            if not referenced(key):
                out.append(
                    self.finding(
                        surface.src.path, 1,
                        f"{values_rel} defines `{key}` but no template reads "
                        "it — a knob that silently does nothing; wire it or "
                        "delete it",
                    )
                )
        return out


@register
class DriftStatusRule(Rule):
    name = "drift-status"
    severity = P1
    doc = (
        "wire-constant drift: a STATUS_*/PROTO_* constant that only one "
        "codec end knows, or one the serde fuzz corpus never exercises — "
        "the next protocol bump breaks the peer silently."
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.files:
            consts = self._wire_constants(src)
            if len(consts) < 2:
                continue
            findings.extend(self._check(project, src, consts))
        return findings

    @staticmethod
    def _wire_constants(src: SourceFile) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for node in src.tree.body:
            if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Constant):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) and WIRE_CONST_RE.match(t.id):
                    out.setdefault(t.id, node.lineno)
        return out

    def _check(
        self, project: Project, src: SourceFile, consts: Dict[str, int]
    ) -> List[Finding]:
        out: List[Finding] = []
        # (1) both codec ends: each constant referenced somewhere beyond
        # its own definition line (the client end may live in the same
        # module — RemoteSolver does — so this is a same-file-allowed
        # used-at-all check, not a cross-file one)
        for name, lineno in sorted(consts.items()):
            pattern = re.compile(rf"\b{re.escape(name)}\b")
            referenced = False
            for other in project.files:
                for i, text_line in enumerate(other.lines, start=1):
                    if other.path == src.path and i == lineno:
                        continue
                    if pattern.search(text_line):
                        referenced = True
                        break
                if referenced:
                    break
            if not referenced:
                out.append(
                    self.finding(
                        src.path, lineno,
                        f"wire constant `{name}` is defined here but nothing "
                        "dispatches on it — a one-sided protocol surface "
                        "(both codec ends must know every status/capability)",
                    )
                )
        # (2) fuzz coverage: every constant exercised by the serde corpus
        root = _nearest_root(project, src.path, ("tests",))
        if root is None:
            return out
        tests_dir = project.root / (f"{root}/tests" if root else "tests")
        fuzz_texts: List[Tuple[str, str]] = []
        for p in sorted(tests_dir.rglob("test_serde*.py")):
            rel = p.relative_to(project.root).as_posix()
            if rel == src.path:
                continue
            fuzz_texts.append((rel, p.read_text(encoding="utf-8")))
        if not fuzz_texts:
            out.append(
                self.finding(
                    src.path, 1,
                    "no serde fuzz corpus (tests/test_serde*.py) covers "
                    "these wire constants — codec changes land untested",
                )
            )
            return out
        combined = "\n".join(t for _, t in fuzz_texts)
        for name, lineno in sorted(consts.items()):
            if not re.search(rf"\b{re.escape(name)}\b", combined):
                out.append(
                    self.finding(
                        src.path, lineno,
                        f"wire constant `{name}` is never exercised by the "
                        f"serde fuzz corpus ({fuzz_texts[0][0]}) — add it to "
                        "the fuzzed status/capability sets",
                    )
                )
        return out
