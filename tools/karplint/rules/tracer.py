"""Tracer-safety rules for the solver package.

Functions reachable from a ``jax.jit`` / ``jax.vmap`` / ``pl.pallas_call``
entry point execute under tracing: Python control flow on traced values
raises ``TracerBoolConversionError`` at best, and host conversions
(``float()`` / ``.item()`` / ``np.asarray``) silently serialize the device
pipeline — the exact class of regression that erases the <100ms solve
target without failing any correctness test.

Reachability is a cross-file call graph over ``solver/``:

- roots: defs decorated ``@jax.jit`` / ``@partial(jax.jit, ...)``, names
  passed to ``jit``/``vmap``/``pmap`` calls, and kernels passed (bare or
  via ``partial``) to ``pallas_call``;
- edges: direct calls resolved through each file's import table (local
  defs, ``from x import f`` symbols, ``mod.f`` where ``mod`` is an
  imported module). Unresolvable receivers (``self.x``, arbitrary objects)
  are skipped — under-approximate, never noisy;
- lexical nesting: closures of a reachable function are reachable (that is
  how ``lax.scan``/``fori_loop`` bodies enter the graph).

Static values (safe to branch on): parameters named by ``static_argnames``,
keyword-only parameters (the ``partial``-bound kernel convention),
module-level constants, ``.shape``/``.ndim``/``.dtype`` reads, and
arithmetic thereof. A small forward taint pass propagates both sets through
straight-line assignments; anything derived from a non-static parameter is
traced.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from tools.karplint.callgraph import get_graph, walk_no_funcs
from tools.karplint.core import (
    P0,
    P1,
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted_name,
    register,
)

STATIC_CALLS = {
    "len", "max", "min", "abs", "int", "float", "bool", "range", "tuple",
    "divmod", "sorted", "isinstance",
}
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize"}


class _TaintScope:
    def __init__(self, static: Set[str], traced: Set[str], consts: Set[str]):
        self.static = set(static)
        self.traced = set(traced)
        self.consts = consts

    def is_static_expr(self, e: ast.AST) -> bool:
        if isinstance(e, ast.Constant):
            return True
        if isinstance(e, ast.Name):
            return (
                e.id in self.static
                or e.id in self.consts
                or e.id.isupper()
                or e.id in ("True", "False", "None")
            )
        if isinstance(e, ast.Attribute):
            if e.attr in STATIC_ATTRS:
                return True
            return self.is_static_expr(e.value)
        if isinstance(e, ast.Subscript):
            return self.is_static_expr(e.value) and self.is_static_expr(e.slice)
        if isinstance(e, ast.BinOp):
            return self.is_static_expr(e.left) and self.is_static_expr(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.is_static_expr(e.operand)
        if isinstance(e, ast.BoolOp):
            return all(self.is_static_expr(v) for v in e.values)
        if isinstance(e, ast.Compare):
            return self.is_static_expr(e.left) and all(
                self.is_static_expr(c) for c in e.comparators
            )
        if isinstance(e, (ast.Tuple, ast.List)):
            return all(self.is_static_expr(v) for v in e.elts)
        if isinstance(e, ast.IfExp):
            return all(
                self.is_static_expr(v) for v in (e.test, e.body, e.orelse)
            )
        if isinstance(e, ast.Call):
            dn = dotted_name(e.func) or ""
            tail = dn.rsplit(".", 1)[-1]
            if tail in STATIC_CALLS or tail == "bit_length":
                return all(self.is_static_expr(a) for a in e.args)
            return False
        return False

    def traced_names(self, e: ast.AST) -> Set[str]:
        # a name inside a static sub-expression (x.shape, len(x)) is not a
        # traced USE — collect names only from non-static subtrees
        if self.is_static_expr(e):
            return set()
        out: Set[str] = set()
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.Name):
                if child.id in self.traced:
                    out.add(child.id)
            else:
                out |= self.traced_names(child)
        if isinstance(e, ast.Name) and e.id in self.traced:
            out.add(e.id)
        return out

    def assign(self, targets: List[ast.AST], value: ast.AST) -> None:
        names: List[str] = []
        for t in targets:
            if isinstance(t, ast.Name):
                names.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                names.extend(e.id for e in t.elts if isinstance(e, ast.Name))
        if not names:
            return
        if self.is_static_expr(value):
            for n in names:
                self.static.add(n)
                self.traced.discard(n)
        elif self.traced_names(value):
            for n in names:
                self.traced.add(n)
                self.static.discard(n)
        else:
            for n in names:
                self.static.discard(n)
                self.traced.discard(n)


@register
class TracerBranchRule(Rule):
    name = "tracer-branch"
    severity = P0
    doc = (
        "Python if/while on a traced value inside jit/vmap/pallas-reachable "
        "solver code — use lax.cond/jnp.where; data-dependent host control "
        "flow either crashes tracing or forces a device sync."
    )
    path_must_contain = ("solver/",)

    def run(self, project: Project) -> List[Finding]:
        return _run_tracer(self, project, check="branch")


@register
class TracerHostSyncRule(Rule):
    name = "tracer-host-sync"
    severity = P0
    doc = (
        "Host conversion (float()/int()/bool() on a traced value, .item(), "
        "numpy op on a traced array, block_until_ready) inside "
        "jit/vmap/pallas-reachable solver code — serializes the device "
        "pipeline on the solve hot path."
    )
    path_must_contain = ("solver/",)

    def run(self, project: Project) -> List[Finding]:
        return _run_tracer(self, project, check="host-sync")


def _run_tracer(rule: Rule, project: Project, check: str) -> List[Finding]:
    files = rule.files(project)
    if not files:
        return []
    graph = get_graph(project, files)
    reachable = graph.reachable()
    reachable_ids = {id(fn) for fn in reachable}
    findings: List[Finding] = []
    for fn in reachable:
        if fn.parent is not None and id(fn.parent) in reachable_ids:
            continue  # analyzed inline under the outermost reachable def
        _analyze_function(
            fn.node, fn.file, fn.static_argnames,
            graph.module_consts.get(fn.file.path, set()),
            graph.imports[fn.file.path][0],
            rule, check, findings,
            inherited=None,
        )
    return findings


def _params(fn: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(positional-ish params, keyword-only params) minus self/cls."""
    a = fn.args
    pos = {p.arg for p in list(a.posonlyargs) + list(a.args)} - {"self", "cls"}
    if a.vararg:
        pos.add(a.vararg.arg)
    kwonly = {p.arg for p in a.kwonlyargs}
    if a.kwarg:
        kwonly.add(a.kwarg.arg)
    return pos, kwonly


def _analyze_function(
    fn_node: ast.AST,
    src: SourceFile,
    static_argnames: Set[str],
    consts: Set[str],
    module_imports: dict,
    rule: Rule,
    check: str,
    findings: List[Finding],
    inherited: Optional[_TaintScope],
) -> None:
    pos, kwonly = _params(fn_node)
    static = set(kwonly) | (static_argnames & (pos | kwonly))
    traced = pos - static
    if inherited is not None:
        static |= inherited.static - traced
        traced |= inherited.traced - static
    scope = _TaintScope(static, traced, consts)
    numpy_aliases = {
        alias for alias, mod in module_imports.items() if mod in ("numpy", "np")
    } | {"np", "numpy"}

    def flag(node: ast.AST, msg: str) -> None:
        findings.append(rule.finding(src.path, node.lineno, msg))

    def check_calls(stmt: ast.AST) -> None:
        if check != "host-sync":
            return
        for node in walk_no_funcs(stmt):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr == "item" and not node.args:
                    flag(node, "`.item()` forces a device→host sync in jit-reachable code")
                elif func.attr == "block_until_ready":
                    flag(node, "`.block_until_ready()` stalls the device pipeline in jit-reachable code")
                elif (
                    isinstance(func.value, ast.Name)
                    and func.value.id in numpy_aliases
                    and any(scope.traced_names(a) for a in node.args)
                ):
                    flag(
                        node,
                        f"host numpy op `np.{func.attr}` on a traced value — use jnp",
                    )
                elif func.attr == "device_get" and any(
                    scope.traced_names(a) for a in node.args
                ):
                    flag(node, "`device_get` on a traced value inside jit-reachable code")
            elif isinstance(func, ast.Name) and func.id in ("float", "int", "bool"):
                if any(
                    scope.traced_names(a) and not scope.is_static_expr(a)
                    for a in node.args
                ):
                    flag(
                        node,
                        f"`{func.id}()` on a traced value forces a device→host sync",
                    )

    def process(stmt: ast.AST) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _analyze_function(
                stmt, src, set(), consts, module_imports, rule, check,
                findings, inherited=scope,
            )
            return
        check_calls(stmt)
        if isinstance(stmt, ast.Assign):
            scope.assign(stmt.targets, stmt.value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            scope.assign([stmt.target], stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            scope.assign([stmt.target], stmt.value)
        elif isinstance(stmt, (ast.If, ast.While)):
            if check == "branch":
                tn = scope.traced_names(stmt.test)
                if tn and not scope.is_static_expr(stmt.test):
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    flag(
                        stmt,
                        f"Python `{kind}` on traced value(s) {sorted(tn)} — "
                        "use lax.cond/jnp.where or hoist to a static argument",
                    )
            for s in stmt.body + stmt.orelse:
                process(s)
            return
        elif isinstance(stmt, ast.For):
            if isinstance(stmt.target, (ast.Name, ast.Tuple, ast.List)):
                scope.assign([stmt.target], stmt.iter)
            for s in stmt.body + stmt.orelse:
                process(s)
            return
        elif isinstance(stmt, ast.With):
            for s in stmt.body:
                process(s)
            return
        elif isinstance(stmt, ast.Try):
            for s in stmt.body + stmt.orelse + stmt.finalbody:
                process(s)
            for h in stmt.handlers:
                for s in h.body:
                    process(s)
            return

    for s in fn_node.body:
        process(s)


# --- dtype contract ---------------------------------------------------------

import re as _re

_CONTRACT_RE = _re.compile(r"#.*\[[^\]]*\].*?\b(f32|f64|bf16|i64|i32|i16|i8|u8|bool|b8)\b")

_DTYPE_TOKENS = {
    "float32": "f32", "float64": "f64", "bfloat16": "bf16",
    "int64": "i64", "int32": "i32", "int16": "i16", "int8": "i8",
    "uint8": "u8", "bool_": "bool", "bool": "bool",
}

_ALIASES = {
    "frontiers": "frontier",
    "sig_type_mask": "type_mask",
    "usable": "usable_capacity",
}

_BUILTIN_CONTRACT = {"join_table": "i32"}  # kernel.pack's wire contract


def _parse_contract(sig_file: Optional[SourceFile]) -> Dict[str, str]:
    contract = dict(_BUILTIN_CONTRACT)
    if sig_file is None:
        return contract
    for node in ast.walk(sig_file.tree):
        name = None
        if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            name = node.target.id
        elif isinstance(node, ast.arg):
            name = node.arg
        if not name:
            continue
        m = _CONTRACT_RE.search(sig_file.line_at(node.lineno))
        if m:
            contract[name] = m.group(1)
    return contract


def _dtype_token(e: ast.AST) -> Optional[str]:
    if isinstance(e, ast.Attribute):
        return _DTYPE_TOKENS.get(e.attr)
    if isinstance(e, ast.Name):
        return _DTYPE_TOKENS.get(e.id)
    if isinstance(e, ast.Constant) and isinstance(e.value, str):
        return _DTYPE_TOKENS.get(e.value)
    return None


def _base_name(e: ast.AST) -> Optional[str]:
    if isinstance(e, ast.Name):
        return e.id
    if isinstance(e, ast.Attribute):
        return e.attr
    return None


@register
class TracerDtypeRule(Rule):
    name = "tracer-dtype"
    severity = P1
    doc = (
        "A dtype cast of a contract array (frontier/type_mask/usable/"
        "join_table) disagrees with the wire contract declared in "
        "solver/signature.py — a silent f32→i32 (or bool→i8) here corrupts "
        "the kernel's fit comparisons."
    )
    path_must_contain = ("solver/",)

    def run(self, project: Project) -> List[Finding]:
        files = self.files(project)
        sig = next(
            (f for f in project.files if f.path.endswith("solver/signature.py")),
            None,
        )
        contract = _parse_contract(sig)
        findings: List[Finding] = []
        for f in files:
            for node in f.nodes():
                if not isinstance(node, ast.Call):
                    continue
                base = token = None
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and len(node.args) == 1
                ):
                    base = _base_name(node.func.value)
                    token = _dtype_token(node.args[0])
                else:
                    dn = dotted_name(node.func) or ""
                    tail = dn.rsplit(".", 1)[-1]
                    if tail in ("asarray", "array") and len(node.args) >= 2:
                        base = _base_name(node.args[0])
                        token = _dtype_token(node.args[1])
                if base is None or token is None:
                    continue
                key = _ALIASES.get(base, base)
                want = contract.get(key) or contract.get(key.rstrip("s"))
                if want is not None and token != want:
                    findings.append(
                        self.finding(
                            f.path, node.lineno,
                            f"`{base}` cast to {token} but the signature.py "
                            f"contract declares {want}",
                        )
                    )
        return findings
