"""Debug-endpoint parity: ``/debug/*`` handlers route through ``obs``.

Two HTTP health servers serve the same debug surface — the controller's
(``main.py``) and the sidecar's (``solver/service.py``) — and history
shows they drift: the PR-8 ``?limit=``/``?name=`` filtering fix had to be
hand-patched into both because each had grown its own payload-building
code. The telemetry PR collapsed every ``/debug/*`` body into shared
``karpenter_tpu.obs.debug_*_payload`` helpers; this rule keeps it that
way: any ``do_GET`` branch outside ``obs/`` that matches a ``/debug/``
path must build its body through one of those helpers, never inline.

Detection: inside a ``do_GET`` function, every ``if``/``elif`` whose test
contains a string literal starting with ``/debug/`` must have at least one
call to a ``debug_*``-named function (``obs.debug_traces_payload(...)``,
or the bare name when imported) somewhere in that branch's body.
"""

from __future__ import annotations

import ast
from typing import List

from tools.karplint.core import (
    P1,
    Finding,
    Project,
    Rule,
    SourceFile,
    register,
)


def _in_obs(path: str) -> bool:
    return path.startswith("obs/") or "/obs/" in path


def _mentions_debug_path(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith("/debug/")
        ):
            return True
    return False


def _calls_debug_helper(body: List[ast.stmt]) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = None
            if isinstance(func, ast.Attribute):
                name = func.attr
            elif isinstance(func, ast.Name):
                name = func.id
            if name is not None and name.startswith("debug_"):
                return True
    return False


@register
class DebugEndpointRule(Rule):
    name = "debug-endpoint"
    severity = P1
    doc = (
        "a /debug/* branch in a do_GET handler outside obs/ builds its "
        "body inline instead of through a shared karpenter_tpu.obs "
        "debug_*_payload helper — the controller/sidecar parity drift "
        "the PR-8 filtering fix had to hand-patch."
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.files:
            if _in_obs(src.path):
                continue
            # cheap text prefilter: no /debug/ literal, no finding
            if "/debug/" not in src.text:
                continue
            for node in src.nodes():
                if (
                    isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == "do_GET"
                ):
                    findings.extend(self._check_handler(src, node))
        return findings

    def _check_handler(self, src: SourceFile, fn: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        # ast.walk visits each If in an elif chain individually (an elif
        # is an If inside the previous If's orelse), so every branch gets
        # its own body check — nested helpers can't vouch for siblings
        for node in ast.walk(fn):
            if isinstance(node, ast.If) and _mentions_debug_path(node.test):
                if not _calls_debug_helper(node.body):
                    findings.append(self.finding(
                        src.path, node.lineno,
                        "this /debug/ branch builds its payload inline; "
                        "route it through a shared karpenter_tpu.obs "
                        "debug_*_payload helper so both health servers "
                        "serve the same body",
                    ))
        return findings
