"""Bounded waits: no timeout-less park on a queue, event, or future.

Overload-control invariant (docs/overload.md): past saturation every
queue is bounded by DECISION, and every wait must be bounded too — a
``Queue.get()`` / ``Event.wait()`` / ``Condition.wait()`` /
``Future.result()`` with no timeout parks its thread until someone else
behaves, which under overload (a dead sidecar, a wedged flush, a shed
batch whose gate nobody will ever set) is forever. Production code waits
with a timeout and re-checks its stop/deadline condition; only tests may
park unboundedly (the fixture corpus and ``tests/`` are out of scope —
the analyzer gates ``karpenter_tpu`` only).

Detection is constructor-tracked to stay precise: the rule follows
assignments of ``threading.Event()`` / ``threading.Condition()`` /
``queue.Queue()``-family constructors to names and attributes WITHIN a
file, and flags timeout-less ``.wait()`` / ``.get()`` on those. A
``.result()`` with no timeout is flagged on any receiver — the only
stdlib ``result()`` worth calling is ``concurrent.futures.Future``'s,
and an unbounded one rode the PR-9 incident where a misbehaving gRPC
transport never resolved its future.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from tools.karplint.core import (
    P1,
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted_name,
    register,
)

# constructor dotted-names whose instances park on .wait()
EVENT_CTORS = {"threading.Event", "threading.Condition", "Event", "Condition"}
# ...and whose instances park on .get()
QUEUE_CTORS = {
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
}


def _has_timeout(call: ast.Call) -> bool:
    """True when the call bounds itself: any positional arg (both
    ``Event.wait`` and ``Queue.get`` take timeout positionally — and a
    positional block=False on get() is equally bounded) or an explicit
    ``timeout=`` keyword."""
    if call.args:
        return True
    return any(kw.arg == "timeout" for kw in call.keywords)


def _target_name(node: ast.AST) -> str:
    """`self._cv` -> `_cv`, `done` -> `done`, else ''."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


@register
class BoundedWaitRule(Rule):
    name = "bounded-wait"
    severity = P1
    doc = (
        "timeout-less Queue.get() / Event.wait() / Condition.wait() / "
        "Future.result() outside tests — under overload an unbounded park "
        "is forever; wait with a timeout and re-check the stop condition."
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in self.files(project):
            waiters, getters = self._tracked(src)
            for node in src.nodes():
                if not isinstance(node, ast.Call) or not isinstance(
                    node.func, ast.Attribute
                ):
                    continue
                method = node.func.attr
                recv = _target_name(node.func.value)
                if method == "result" and not _has_timeout(node):
                    findings.append(
                        self.finding(
                            src.path, node.lineno,
                            "`.result()` with no timeout parks forever on a "
                            "misbehaving transport — bound it "
                            "(`future.result(timeout=...)`)",
                        )
                    )
                elif (
                    method == "wait"
                    and recv in waiters
                    and not _has_timeout(node)
                ):
                    findings.append(
                        self.finding(
                            src.path, node.lineno,
                            f"`{recv}.wait()` with no timeout — a shed or "
                            "crashed setter leaves this thread parked "
                            "forever; wait a bounded slice and re-check",
                        )
                    )
                elif (
                    method == "get"
                    and recv in getters
                    and not _has_timeout(node)
                ):
                    findings.append(
                        self.finding(
                            src.path, node.lineno,
                            f"`{recv}.get()` with no timeout — an idle "
                            "producer (or a stopped one) blocks this "
                            "consumer forever; use `get(timeout=...)`",
                        )
                    )
        return findings

    @staticmethod
    def _tracked(src: SourceFile) -> tuple:
        """Names/attrs assigned an Event/Condition (waiters) or a Queue
        (getters) anywhere in this file."""
        waiters: Set[str] = set()
        getters: Set[str] = set()
        for node in src.nodes():
            value = None
            targets = []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            if not isinstance(value, ast.Call):
                continue
            ctor = dotted_name(value.func)
            if ctor is None:
                continue
            bucket = (
                waiters if ctor in EVENT_CTORS
                else getters if ctor in QUEUE_CTORS
                else None
            )
            if bucket is None:
                continue
            for target in targets:
                name = _target_name(target)
                if name:
                    bucket.add(name)
        return waiters, getters
