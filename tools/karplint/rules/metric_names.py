"""Metric naming + documentation listing (the folded
``hack/check_metrics_names.py`` pass).

Scans every ``metrics.py`` (the two registries: ``karpenter_tpu/metrics.py``
and ``karpenter_tpu/cloudprovider/metrics.py``) for
``Counter``/``Gauge``/``Histogram`` constructions, computes the full
exposed name (``namespace_subsystem_name``), and asserts:

- Prometheus naming: ``[a-z][a-z0-9_]*``, no ``__``, no leading/trailing
  underscore;
- counters end ``_total``; gauges don't; histograms end in a unit suffix
  (``_seconds``, ``_bytes``, ...);
- no two metrics expose the same full name;
- every full name is listed in ``docs/metrics.md`` — an undocumented
  metric is a dashboard nobody can find and a rename nobody will notice;
- the docs row's **labels** cell matches the registered label set — a
  doc that promises a ``provisioner`` label the metric doesn't carry
  breaks every dashboard query written from it. A parenthesized cell
  (``(node gauge labels)``) is shorthand for a shared set and is not
  checked; ``—`` means no labels.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from tools.karplint.core import (
    P1,
    Finding,
    Project,
    Rule,
    dotted_name,
    register,
)

METRIC_TYPES = ("Counter", "Gauge", "Histogram", "Summary")
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
HISTOGRAM_UNITS = (
    "_seconds", "_bytes", "_pods", "_ratio", "_items", "_size", "_count",
)


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _resolve_kwarg(call: ast.Call, name: str, module_consts: dict) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == name:
            s = _const_str(kw.value)
            if s is not None:
                return s
            if isinstance(kw.value, ast.Name):
                return module_consts.get(kw.value.id)
    return None


def _str_list(node: Optional[ast.AST], list_consts: dict) -> Optional[List[str]]:
    """A list/tuple of string constants (inline or via a module-level
    Name like NODE_GAUGE_LABELS), else None."""
    if isinstance(node, ast.Name):
        return list_consts.get(node.id)
    if isinstance(node, (ast.List, ast.Tuple)):
        out = []
        for el in node.elts:
            s = _const_str(el)
            if s is None:
                return None
            out.append(s)
        return out
    return None


def _metric_labels(call: ast.Call, list_consts: dict) -> Optional[List[str]]:
    """The label names a Counter/Gauge/Histogram registration declares:
    the third positional argument or the ``labelnames=`` kwarg. Returns
    [] for an explicitly label-less metric, None when undeterminable."""
    for kw in call.keywords:
        if kw.arg == "labelnames":
            return _str_list(kw.value, list_consts)
    if len(call.args) >= 3:
        return _str_list(call.args[2], list_consts)
    if len(call.args) == 2 and all(
        kw.arg not in (None, "labelnames") for kw in call.keywords
    ):
        return []  # (name, doc, **opts) — no label slot at all
    return None


_DOC_ROW_RE = re.compile(r"^\s*\|\s*`([a-z][a-z0-9_]*)`\s*\|")


def _docs_label_cells(docs_text: str) -> dict:
    """full metric name -> raw labels cell from the docs/metrics.md
    tables (``| `name` | type | labels | meaning |``)."""
    cells = {}
    for line in docs_text.splitlines():
        m = _DOC_ROW_RE.match(line)
        if not m:
            continue
        parts = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(parts) >= 3:
            cells[m.group(1)] = parts[2]
    return cells


def _parse_docs_labels(cell: str) -> Optional[List[str]]:
    """The label names a docs row promises. None = unchecked (shared-set
    shorthand like ``(node gauge labels)``), [] = explicitly label-less
    (``—``/``-``/empty)."""
    cell = cell.strip()
    if cell.startswith("("):
        return None
    if cell in ("", "—", "-", "–"):
        return []
    return [tok.strip().strip("`") for tok in cell.split(",") if tok.strip()]


@register
class MetricNameRule(Rule):
    name = "metric-name"
    severity = P1
    doc = (
        "A registered Prometheus metric violates naming conventions "
        "(charset, _total on counters, unit suffix on histograms), "
        "collides with another metric, or is missing from docs/metrics.md."
    )

    def run(self, project: Project) -> List[Finding]:
        metric_files = [
            f for f in project.files if f.path.rsplit("/", 1)[-1] == "metrics.py"
        ]
        if not metric_files:
            return []
        docs_path = project.root / "docs" / "metrics.md"
        docs_text = docs_path.read_text() if docs_path.exists() else None
        docs_labels = _docs_label_cells(docs_text) if docs_text else {}

        findings: List[Finding] = []
        seen: dict = {}
        for src in metric_files:
            module_consts = {
                t.id: node.value.value
                for node in src.tree.body
                if isinstance(node, ast.Assign)
                for t in node.targets
                if isinstance(t, ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            }
            list_consts = {}
            for node in src.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if isinstance(t, ast.Name):
                        vals = _str_list(node.value, {})
                        if vals is not None:
                            list_consts[t.id] = vals
            if docs_text is None:
                findings.append(
                    self.finding(
                        src.path, 1,
                        "docs/metrics.md is missing — every registered metric "
                        "must be listed there",
                    )
                )
            # single-level helpers (def _node_gauge(name, doc): return
            # Gauge(name, ...)): calls to them register metrics too
            helpers = {}
            for fn in src.tree.body:
                if not isinstance(fn, ast.FunctionDef):
                    continue
                for stmt in fn.body:
                    if (
                        isinstance(stmt, ast.Return)
                        and isinstance(stmt.value, ast.Call)
                        and (dotted_name(stmt.value.func) or "").rsplit(".", 1)[-1]
                        in METRIC_TYPES
                        and stmt.value.args
                        and isinstance(stmt.value.args[0], ast.Name)
                        and fn.args.args
                        and stmt.value.args[0].id == fn.args.args[0].arg
                    ):
                        helpers[fn.name] = (
                            (dotted_name(stmt.value.func) or "").rsplit(".", 1)[-1],
                            stmt.value,
                        )
            for node in src.nodes():
                if not isinstance(node, ast.Call):
                    continue
                dn = dotted_name(node.func) or ""
                mtype = dn.rsplit(".", 1)[-1]
                inner = node
                if mtype in helpers:
                    mtype, inner = helpers[mtype]
                elif mtype not in METRIC_TYPES:
                    continue
                base = _const_str(node.args[0]) if node.args else None
                if base is None:
                    continue  # the helper's own inner Call carries a Name arg
                ns = _resolve_kwarg(inner, "namespace", module_consts) or ""
                ss = _resolve_kwarg(inner, "subsystem", module_consts) or ""
                full = "_".join(p for p in (ns, ss, base) if p)
                line = node.lineno

                if not NAME_RE.match(full) or "__" in full or full.endswith("_"):
                    findings.append(
                        self.finding(
                            src.path, line,
                            f"metric `{full}` violates Prometheus naming "
                            "([a-z][a-z0-9_]*, no __, no trailing _)",
                        )
                    )
                if mtype == "Counter" and not full.endswith("_total"):
                    findings.append(
                        self.finding(
                            src.path, line,
                            f"counter `{full}` must end in `_total`",
                        )
                    )
                if mtype == "Gauge" and full.endswith("_total"):
                    findings.append(
                        self.finding(
                            src.path, line,
                            f"gauge `{full}` must not end in `_total` "
                            "(reads as a counter)",
                        )
                    )
                if mtype == "Histogram" and not full.endswith(HISTOGRAM_UNITS):
                    findings.append(
                        self.finding(
                            src.path, line,
                            f"histogram `{full}` should end in a unit suffix "
                            f"({', '.join(HISTOGRAM_UNITS)})",
                        )
                    )
                prior = seen.get(full)
                if prior is not None:
                    findings.append(
                        self.finding(
                            src.path, line,
                            f"metric `{full}` already registered at "
                            f"{prior[0]}:{prior[1]}",
                        )
                    )
                else:
                    seen[full] = (src.path, line)
                if docs_text is not None and full not in docs_text:
                    findings.append(
                        self.finding(
                            src.path, line,
                            f"metric `{full}` is not listed in docs/metrics.md",
                        )
                    )
                elif full in docs_labels:
                    promised = _parse_docs_labels(docs_labels[full])
                    declared = _metric_labels(inner, list_consts)
                    if (
                        promised is not None
                        and declared is not None
                        and sorted(promised) != sorted(declared)
                    ):
                        findings.append(
                            self.finding(
                                src.path, line,
                                f"metric `{full}` labels "
                                f"{sorted(declared)} don't match the "
                                f"docs/metrics.md row's labels cell "
                                f"{sorted(promised)} — dashboard queries "
                                "written from the doc will break",
                            )
                        )
        return findings
