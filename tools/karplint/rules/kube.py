"""Kube transport choke point: no bypassing ``kube/transport.py``.

Partition-tolerance invariant (docs/partition.md): every apiserver call
must cross the ONE transport choke point — per-verb retries, 429 handling,
mutation-priority flow control, the circuit breaker, and the
``karpenter_kube_request_*`` metrics all live there. A controller that
calls ``ApiCluster._request`` directly, or opens its own ``http.client``
connection, gets none of that: its calls are unmetered, unthrottled,
retry-free, and invisible to the breaker the rest of the fleet fences on.

Two detections, both scoped to files OUTSIDE ``kube/``:

- a call to ``<anything>._request(...)`` in a file that does not itself
  define a ``_request`` method (calling your own private wire helper —
  the cloud HTTP wire does — is that module's business; reaching into
  ANOTHER object's ``_request`` is the bypass);
- importing ``http.client`` (or its connection classes) at all — raw
  apiserver HTTP belongs in ``kube/``.
"""

from __future__ import annotations

import ast
from typing import List

from tools.karplint.core import (
    P1,
    Finding,
    Project,
    Rule,
    SourceFile,
    register,
)


def _in_kube(path: str) -> bool:
    return path.startswith("kube/") or "/kube/" in path


@register
class KubeTransportRule(Rule):
    name = "kube-transport"
    severity = P1
    doc = (
        "direct ApiCluster._request / raw http.client use outside kube/ "
        "bypasses the transport choke point (retries, flow control, "
        "breaker, kube metrics) — go through the Cluster surface."
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.files:
            if _in_kube(src.path):
                continue
            # cheap text prefilter: a file that never mentions either token
            # cannot produce a finding — skip its AST walk entirely
            if "_request" not in src.text and "http.client" not in src.text:
                continue
            # ONE walk per file: collect imports, `_request` definitions,
            # and `._request(...)` call sites together (the analyze gate
            # has a wall-clock budget; three walks per file blew ~2s of it)
            import_lines: List[int] = []
            call_lines: List[int] = []
            defines_request = False
            for node in src.nodes():
                if isinstance(node, ast.Import):
                    if any(
                        a.name == "http.client" or a.name.startswith("http.client.")
                        for a in node.names
                    ):
                        import_lines.append(node.lineno)
                elif isinstance(node, ast.ImportFrom):
                    if node.module == "http.client":
                        import_lines.append(node.lineno)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if node.name == "_request":
                        defines_request = True
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_request"
                ):
                    call_lines.append(node.lineno)
            for lineno in import_lines:
                findings.append(self.finding(
                    src.path, lineno,
                    "raw `http.client` outside kube/ — apiserver HTTP "
                    "belongs behind the kube/transport.py choke point",
                ))
            if not defines_request:
                # calling your OWN private wire helper (the cloud HTTP
                # wire's shape) is that module's transport discipline;
                # reaching into ANOTHER object's `_request` is the bypass
                for lineno in call_lines:
                    findings.append(self.finding(
                        src.path, lineno,
                        "direct `._request(...)` bypasses the kube transport "
                        "(no retries, no flow control, no breaker, no "
                        "metrics) — use the Cluster surface "
                        "(get_live/list_live/create/merge_patch/...)",
                    ))
        return findings
