"""Lock discipline: ``# guarded-by:`` annotations.

Shared state is declared at its initialization site::

    self._gauged: set = set()        # guarded-by: self._lock
    _default: Optional[T] = None     # guarded-by: _default_lock   (module)

Every MUTATION of a guarded attribute (assignment, augmented assignment,
subscript store/delete, or a call to a known mutating method — ``add``,
``pop``, ``append``, ...) must then sit lexically inside ``with <lock>:``
on the declared lock. This is the PR-1 lazy-init-race class made
un-reintroducible: the annotation is the contract, the analyzer is the
enforcement.

Accepted hold-proofs (lexical, intentionally conservative):

- a ``with self._lock:`` / ``with _lock:`` ancestor matching the declared
  lock expression;
- the enclosing function's name ends in ``_locked`` (the codebase's
  caller-holds-the-lock convention, e.g. ``_pump_delayed_locked``);
- the mutation is in ``__init__`` (for instance attributes) or at module
  level (for globals) — construction precedes sharing.

Reads are NOT checked: lock-free reads of monotonic or GIL-atomic state
are a deliberate pattern here (breaker fast paths, double-checked init),
and flagging them would teach people to suppress the rule.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.karplint.core import (
    P0,
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted_name,
    register,
)

MUTATING_METHODS = {
    "add", "append", "appendleft", "extend", "insert", "remove", "discard",
    "pop", "popleft", "popitem", "clear", "update", "setdefault",
    "put", "put_nowait", "push", "sort", "reverse",
}


def _lock_matches(context_expr: ast.AST, lock: str) -> bool:
    dn = dotted_name(context_expr)
    return dn == lock


def _held(src: SourceFile, node: ast.AST, lock: str) -> bool:
    for anc in src.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                if _lock_matches(item.context_expr, lock):
                    return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if anc.name.endswith("_locked"):
                return True
    return False


def _enclosing_function(src: SourceFile, node: ast.AST) -> Optional[ast.AST]:
    for anc in src.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _enclosing_class(src: SourceFile, node: ast.AST) -> Optional[ast.ClassDef]:
    for anc in src.ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


@register
class LockGuardRule(Rule):
    name = "lock-guard"
    severity = P0
    doc = (
        "An attribute or module global declared `# guarded-by: <lock>` is "
        "mutated outside a `with <lock>:` block — the unguarded lazy-init/"
        "shared-mutation race class."
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.files:
            findings.extend(self._check_file(src))
        return findings

    def _check_file(self, src: SourceFile) -> List[Finding]:
        # class qualname -> {attr -> lock}; "" -> module globals
        guarded: Dict[str, Dict[str, str]] = {}
        for node in src.nodes():
            target = None
            if isinstance(node, ast.AnnAssign):
                target = node.target
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            if target is None:
                continue
            lock = src.guarded_by(node.lineno)
            if lock is None:
                continue
            cls = _enclosing_class(src, node)
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
                and cls is not None
            ):
                guarded.setdefault(cls.name, {})[target.attr] = lock
            elif isinstance(target, ast.Name) and cls is None and (
                _enclosing_function(src, node) is None
            ):
                guarded.setdefault("", {})[target.id] = lock

        if not guarded:
            return []
        findings: List[Finding] = []
        for node in src.nodes():
            hit = self._mutation(src, node, guarded)
            if hit is None:
                continue
            name, lock, mut_node = hit
            fn = _enclosing_function(src, mut_node)
            if fn is None:
                continue  # module-level / class-body init
            if fn.name == "__init__" and name.startswith("self."):
                continue  # construction precedes sharing
            if _held(src, mut_node, lock):
                continue
            findings.append(
                self.finding(
                    src.path, mut_node.lineno,
                    f"`{name}` is declared guarded-by `{lock}` but is mutated "
                    f"outside `with {lock}:` (in `{fn.name}`)",
                )
            )
        return findings

    def _mutation(
        self, src: SourceFile, node: ast.AST, guarded: Dict[str, Dict[str, str]]
    ) -> Optional[Tuple[str, str, ast.AST]]:
        """(display name, lock, node) when ``node`` mutates a guarded target."""

        def lookup(target: ast.AST) -> Optional[Tuple[str, str]]:
            # self.attr (class scope) / bare Name (module scope); also
            # self.attr[k] and name[k] subscript stores
            if isinstance(target, ast.Subscript):
                target = target.value
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                cls = _enclosing_class(src, target)
                if cls is not None:
                    lock = guarded.get(cls.name, {}).get(target.attr)
                    if lock:
                        return f"self.{target.attr}", lock
                return None
            if isinstance(target, ast.Name):
                lock = guarded.get("", {}).get(target.id)
                if lock:
                    return target.id, lock
            return None

        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                hit = lookup(t)
                if hit:
                    return hit[0], hit[1], node
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                hit = lookup(t)
                if hit:
                    return hit[0], hit[1], node
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in MUTATING_METHODS:
                hit = lookup(node.func.value)
                if hit:
                    return f"{hit[0]}.{node.func.attr}()", hit[1], node
        return None
