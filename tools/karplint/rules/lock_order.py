"""Interprocedural lock-order and blocking-call analysis.

Both rules walk one shared structure (memoized per project): every
``with <lock>:`` acquisition site across the tree, the set of locks each
function may transitively acquire, and the first blocking operation each
function may transitively reach — all resolved through the shared
cross-file call graph (``tools/karplint/callgraph.py``).

**lock-order (P0).** Acquiring lock B while holding lock A draws a global
edge A→B — lexically nested ``with`` blocks and acquisitions reached
through resolved calls both count. A cycle in that graph is a lock-order
inversion: two threads entering the cycle from different points deadlock,
and no unit test will ever catch it because the interleaving needs
production concurrency. A self-edge on a non-reentrant ``threading.Lock``
(re-acquiring the lock you hold through a helper) is the degenerate
one-thread deadlock and reports under the same rule.

**lock-blocking (P1).** A blocking operation — ``time.sleep``, a Future
``.result()``, a tracked ``Queue.get()`` / foreign ``Event.wait()``,
``fcntl.flock``, ``urlopen``, or (in ``solver/``) a device fetch such as
``np.asarray`` on device output or ``.block_until_ready()`` — reachable
while a lock is held turns that lock into a convoy: every other thread
needing it stalls for the blocked operation's duration. This statically
pins the PR-4 invariant that the device fetch happens OFF the solve lock
(double-buffering depends on it). ``Condition.wait`` on the held lock's
own condition variable is the sanctioned sleep-releases-the-lock pattern
and is exempt.

Lock identity is lexical and scope-qualified: ``self._lock`` in class C of
file f is ``f::C._lock`` (per-class, matching the ``# guarded-by:``
convention), a module global ``_lock`` is ``f::_lock``. Identity is
per-declaration-site, so two instances of one class share an id — for
ORDER analysis that is exactly right (every instance pair orders the same
way); the self-edge check additionally requires a non-reentrant ctor
(``threading.Lock``) to avoid flagging RLock re-entry.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.karplint.callgraph import CallGraph, FuncInfo, get_graph
from tools.karplint.core import (
    P0,
    P1,
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted_name,
    register,
)

# receiver-name heuristic for "this with-statement takes a lock": the last
# dotted segment. Condition objects acquire their underlying lock on
# `with`, so they participate in ordering too.
_LOCKISH = ("lock", "mutex", "_mu", "_cv", "cond")

# queue-family constructors whose .get() parks (mirrors rules/waits.py)
_QUEUE_CTORS = {
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
}
_EVENT_CTORS = {"threading.Event", "threading.Condition", "Event", "Condition"}
_NONREENTRANT_CTORS = {"threading.Lock", "Lock"}

# numpy-ish host-materialization calls that fetch device output when they
# appear in solver/ code (the PR-4 fetch-off-the-lock invariant)
_DEVICE_FETCHES = {"asarray", "device_get", "block_until_ready"}


def _lockish_name(expr: ast.AST) -> Optional[str]:
    dn = dotted_name(expr)
    if dn is None:
        return None
    tail = dn.rsplit(".", 1)[-1].lower()
    if any(t in tail for t in _LOCKISH):
        return dn
    return None


@dataclass
class BlockWitness:
    desc: str          # what blocks, e.g. "time.sleep"
    path: str          # file of the blocking op
    line: int
    chain: List[str] = field(default_factory=list)  # call chain, outermost first


@dataclass
class _FnLocks:
    # (lock id, display name, with-node) for every lexical with-lock
    withs: List[Tuple[str, str, ast.With]] = field(default_factory=list)
    # lock ids this function acquires lexically (for transitive ACQ)
    lexical: Set[str] = field(default_factory=set)
    display: Dict[str, str] = field(default_factory=dict)


class LockAnalysis:
    """Whole-project lock map: built once, consumed by both rules."""

    def __init__(self, project: Project, graph: CallGraph):
        self.project = project
        self.graph = graph
        self.fn_locks: Dict[int, _FnLocks] = {}
        self.display: Dict[str, str] = {}
        self.nonreentrant: Set[str] = set()
        self.queueish: Dict[str, Set[str]] = {}   # file path -> attr/name set
        self.eventish: Dict[str, Set[str]] = {}
        # Condition(lock) wraps an existing lock: `with cv:` and `cv.wait()`
        # operate on the UNDERLYING lock, so the cv's id aliases to it
        self.cv_underlying: Dict[str, str] = {}
        self._acq_cache: Dict[int, Set[str]] = {}
        self._block_cache: Dict[int, Optional[BlockWitness]] = {}
        for f in graph.files:
            self._scan_ctors(f)
        for fn in graph.funcs:
            self.fn_locks[id(fn)] = self._collect(fn)

    # -- per-file constructor tracking --------------------------------------
    def _scan_ctors(self, f: SourceFile) -> None:
        queues: Set[str] = set()
        events: Set[str] = set()
        # f.parents already indexes every node — no re-walk needed
        for node in f.parents:
            value, targets = None, []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            if not isinstance(value, ast.Call):
                continue
            ctor = dotted_name(value.func)
            if ctor is None:
                continue
            names = []
            for t in targets:
                if isinstance(t, ast.Attribute):
                    names.append(t.attr)
                elif isinstance(t, ast.Name):
                    names.append(t.id)
            if ctor in _QUEUE_CTORS:
                queues.update(names)
            elif ctor in _EVENT_CTORS:
                events.update(names)
                if ctor.rsplit(".", 1)[-1] == "Condition" and value.args:
                    under = self._lock_id_for_expr(f, value.args[0])
                    for t in targets:
                        cv_id = self._lock_id_for_target(f, t)
                        if cv_id and under:
                            self.cv_underlying[cv_id] = under
            elif ctor in _NONREENTRANT_CTORS:
                for t in targets:
                    lock_id = self._lock_id_for_target(f, t)
                    if lock_id:
                        self.nonreentrant.add(lock_id)
        self.queueish[f.path] = queues
        self.eventish[f.path] = events

    def _lock_id_for_target(self, f: SourceFile, target: ast.AST) -> Optional[str]:
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            cls = next(
                (a.name for a in f.ancestors(target) if isinstance(a, ast.ClassDef)),
                None,
            )
            if cls:
                return f"{f.path}::{cls}.{target.attr}"
        if isinstance(target, ast.Name):
            return f"{f.path}::{target.id}"
        return None

    # an expression names a lock the same way a target does
    _lock_id_for_expr = _lock_id_for_target

    # -- per-function lock collection ---------------------------------------
    def lock_id(self, fn: FuncInfo, expr: ast.AST) -> Optional[Tuple[str, str]]:
        """(canonical id, display name) when ``expr`` names a lock."""
        dn = _lockish_name(expr)
        if dn is None:
            return None
        f = fn.file
        if dn.startswith("self.") and fn.cls:
            parts = dn.split(".")
            if len(parts) == 2:
                lid = f"{f.path}::{fn.cls}.{parts[1]}"
                return self.cv_underlying.get(lid, lid), f"{fn.cls}.{parts[1]}"
            # self.x.y — opaque but stable within the class
            lid = f"{f.path}::{fn.cls}.{'.'.join(parts[1:])}"
            return self.cv_underlying.get(lid, lid), dn
        lid = f"{f.path}::{dn}"
        return self.cv_underlying.get(lid, lid), dn

    def _collect(self, fn: FuncInfo) -> _FnLocks:
        out = _FnLocks()
        for node in self._walk_own(fn.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    hit = self.lock_id(fn, item.context_expr)
                    if hit:
                        lock_id, disp = hit
                        out.withs.append((lock_id, disp, node))
                        out.lexical.add(lock_id)
                        self.display[lock_id] = disp
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr == "acquire":
                    hit = self.lock_id(fn, node.func.value)
                    if hit:
                        out.lexical.add(hit[0])
                        self.display[hit[0]] = hit[1]
        return out

    @staticmethod
    def _walk_own(node: ast.AST):
        """Walk a function body without descending into nested defs —
        nested functions run at their own call time, not under this
        function's locks."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            cur = stack.pop()
            yield cur
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(cur))

    # -- transitive acquisition set -----------------------------------------
    def acquires(self, fn: FuncInfo, _stack: Optional[Set[int]] = None) -> Set[str]:
        cached = self._acq_cache.get(id(fn))
        if cached is not None:
            return cached
        if _stack is None:
            _stack = set()
        if id(fn) in _stack:
            return self.fn_locks[id(fn)].lexical  # cycle: lexical only
        _stack.add(id(fn))
        out = set(self.fn_locks[id(fn)].lexical)
        for callee in self.graph.callees(fn):
            out |= self.acquires(callee, _stack)
        _stack.discard(id(fn))
        self._acq_cache[id(fn)] = out
        return out

    # -- transitive blocking witness ----------------------------------------
    def _direct_block(self, fn: FuncInfo, node: ast.AST, held: Optional[str]) -> Optional[str]:
        """Description when ``node`` is a lexically blocking op in ``fn``."""
        if not isinstance(node, ast.Call):
            return None
        func = node.func
        dn = dotted_name(func) or ""
        tail = dn.rsplit(".", 1)[-1]
        in_solver = "solver/" in fn.file.path
        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr == "sleep":
                return f"`{dn or attr}()`"
            if attr == "result":
                return "`.result()` (RPC/future wait)"
            if attr == "flock":
                return "`flock()` (file-lock wait)"
            if attr == "urlopen":
                return "`urlopen()` (network RPC)"
            if attr == "block_until_ready":
                return "`.block_until_ready()` (device sync)"
            recv = (
                func.value.attr if isinstance(func.value, ast.Attribute)
                else func.value.id if isinstance(func.value, ast.Name)
                else ""
            )
            if attr == "get" and recv in self.queueish.get(fn.file.path, ()):
                return f"`{recv}.get()` (queue wait)"
            if attr == "wait" and recv in self.eventish.get(fn.file.path, ()):
                # Condition.wait on the HELD lock's own cv releases the
                # lock while parked — the sanctioned pattern; a wait on
                # any other object parks while still holding `held`
                hit = self.lock_id(fn, func.value)
                if hit and held is not None and hit[0] == held:
                    return None
                return f"`{recv}.wait()` (event/condition wait)"
            if in_solver and attr in _DEVICE_FETCHES:
                return f"`{dn or attr}()` (device fetch)"
        elif isinstance(func, ast.Name):
            if func.id == "sleep":
                return "`sleep()`"
            if func.id == "urlopen":
                return "`urlopen()` (network RPC)"
            if func.id == "flock":
                return "`flock()` (file-lock wait)"
        return None

    def block_witness(
        self, fn: FuncInfo, _stack: Optional[Set[int]] = None
    ) -> Optional[BlockWitness]:
        """First blocking op ``fn`` may reach (lexical, else via callees)."""
        if id(fn) in self._block_cache:
            return self._block_cache[id(fn)]
        if _stack is None:
            _stack = set()
        if id(fn) in _stack:
            return None
        _stack.add(id(fn))
        witness: Optional[BlockWitness] = None
        for node in self._walk_own(fn.node):
            desc = self._direct_block(fn, node, held=None)
            if desc is not None:
                witness = BlockWitness(desc, fn.file.path, node.lineno)
                break
        if witness is None:
            for callee in self.graph.callees(fn):
                sub = self.block_witness(callee, _stack)
                if sub is not None:
                    witness = BlockWitness(
                        sub.desc, sub.path, sub.line,
                        [callee.qualname] + sub.chain,
                    )
                    break
        _stack.discard(id(fn))
        self._block_cache[id(fn)] = witness
        return witness


def get_lock_analysis(project: Project) -> LockAnalysis:
    key = "lock-analysis"
    analysis = project.cache.get(key)
    if analysis is None:
        analysis = LockAnalysis(project, get_graph(project))
        project.cache[key] = analysis
    return analysis


def _chain_note(w: BlockWitness) -> str:
    if not w.chain:
        return ""
    return f" via `{' -> '.join(w.chain)}`"


@register
class LockOrderRule(Rule):
    name = "lock-order"
    severity = P0
    doc = (
        "lock-acquisition cycle reachable through the call graph (two "
        "threads entering from different points deadlock), or a helper "
        "re-acquiring a non-reentrant Lock the caller already holds."
    )

    def run(self, project: Project) -> List[Finding]:
        analysis = get_lock_analysis(project)
        graph = analysis.graph
        # global order edges: (A, B) -> list of (path, line, note)
        edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        for fn in graph.funcs:
            for lock_id, disp, with_node in analysis.fn_locks[id(fn)].withs:
                for node in LockAnalysis._walk_own(with_node):
                    if isinstance(node, ast.With):
                        for item in node.items:
                            hit = analysis.lock_id(fn, item.context_expr)
                            if hit:
                                edges.setdefault((lock_id, hit[0]), []).append(
                                    (fn.file.path, node.lineno, "")
                                )
                    elif isinstance(node, ast.Call):
                        for callee in graph.resolve_call(fn.file, node, cls=fn.cls, fn=fn):
                            for inner in analysis.acquires(callee):
                                edges.setdefault((lock_id, inner), []).append(
                                    (
                                        fn.file.path, node.lineno,
                                        f" via `{callee.qualname}`",
                                    )
                                )
        findings: List[Finding] = []
        adj: Dict[str, Set[str]] = {}
        for (a, b), _sites in edges.items():
            if a != b:
                adj.setdefault(a, set()).add(b)
        cyclic = _nodes_on_cycles(adj)
        seen: Set[Tuple[str, int, str, str]] = set()
        for (a, b), sites in sorted(edges.items()):
            disp_a = analysis.display.get(a, a)
            disp_b = analysis.display.get(b, b)
            if a == b:
                if a not in analysis.nonreentrant:
                    continue  # RLock / unknown ctor: re-entry is legal
                for path, line, note in sites:
                    key = (path, line, a, b)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(
                        self.finding(
                            path, line,
                            f"re-acquires non-reentrant lock `{disp_a}` "
                            f"already held here{note} — single-thread deadlock",
                        )
                    )
                continue
            if a in cyclic and b in cyclic and _reaches(adj, b, a):
                for path, line, note in sites:
                    key = (path, line, a, b)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(
                        self.finding(
                            path, line,
                            f"lock-order inversion: acquires `{disp_b}`"
                            f"{note} while holding `{disp_a}`, but the "
                            f"reverse order `{disp_b}` -> `{disp_a}` is also "
                            "reachable — two threads deadlock; pick one "
                            "global order (docs/static-analysis.md#lock-order)",
                        )
                    )
        return findings


def _reaches(adj: Dict[str, Set[str]], src: str, dst: str) -> bool:
    seen: Set[str] = set()
    work = [src]
    while work:
        cur = work.pop()
        if cur == dst:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        work.extend(adj.get(cur, ()))
    return False


def _nodes_on_cycles(adj: Dict[str, Set[str]]) -> Set[str]:
    """Nodes in a non-trivial SCC (Tarjan, iterative)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: Set[str] = set()
    counter = [0]
    nodes = set(adj) | {b for bs in adj.values() for b in bs}

    for root in nodes:
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, child_i = work[-1]
            if child_i == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            children = sorted(adj.get(node, ()))
            advanced = False
            for i in range(child_i, len(children)):
                ch = children[i]
                if ch not in index:
                    work[-1] = (node, i + 1)
                    work.append((ch, 0))
                    advanced = True
                    break
                elif ch in on_stack:
                    low[node] = min(low[node], index[ch])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    out.update(comp)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return out


@register
class LockBlockingRule(Rule):
    name = "lock-blocking"
    severity = P1
    doc = (
        "blocking operation (sleep/.result()/queue wait/flock/urlopen/"
        "device fetch) reachable while a lock is held — the lock becomes "
        "a convoy; move the wait off the lock (the PR-4 fetch-off-the-"
        "solve-lock invariant)."
    )

    def run(self, project: Project) -> List[Finding]:
        analysis = get_lock_analysis(project)
        graph = analysis.graph
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()

        def flag(path: str, line: int, lock_disp: str, msg: str) -> None:
            key = (path, line, lock_disp)
            if key in seen:
                return
            seen.add(key)
            findings.append(self.finding(path, line, msg))

        for fn in graph.funcs:
            for lock_id, disp, with_node in analysis.fn_locks[id(fn)].withs:
                for node in LockAnalysis._walk_own(with_node):
                    if not isinstance(node, ast.Call):
                        continue
                    desc = analysis._direct_block(fn, node, held=lock_id)
                    if desc is not None:
                        flag(
                            fn.file.path, node.lineno, disp,
                            f"{desc} while holding `{disp}` — every thread "
                            "needing the lock stalls behind this wait; move "
                            "it off the lock",
                        )
                        continue
                    for callee in graph.resolve_call(fn.file, node, cls=fn.cls, fn=fn):
                        w = analysis.block_witness(callee)
                        if w is not None:
                            flag(
                                fn.file.path, node.lineno, disp,
                                f"call to `{callee.qualname}` may block "
                                f"({w.desc} at {w.path}:{w.line}"
                                f"{_chain_note(w)}) while holding `{disp}` — "
                                "move the blocking work off the lock",
                            )
                            break
        return findings
