"""Reconcile purity: controller reconcile/poll bodies do no raw I/O.

A reconcile round runs under a wall-clock Budget and behind per-dependency
circuit breakers; a bare ``time.sleep`` or a direct ``socket`` /
``http.client`` / ``requests`` call bypasses all of it — unmetered latency
with no deadline, no retry classification, no breaker. I/O must route
through the metered cloud decorator (``cloudprovider.metrics.decorate``)
or ``resilience.RetryPolicy``.
"""

from __future__ import annotations

import ast
from typing import List

from tools.karplint.core import (
    P0,
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted_name,
    register,
)

RECONCILE_NAMES = ("reconcile", "poll")

BANNED_CALLS = {
    "time.sleep": "`time.sleep` stalls the reconcile round outside any Budget",
}
BANNED_PREFIXES = {
    "socket.": "raw socket I/O",
    "requests.": "bare `requests` call",
    "http.client": "raw `http.client` use",
    "urllib.request": "raw `urllib.request` use",
}


def _is_reconcile(fn: ast.AST) -> bool:
    name = getattr(fn, "name", "")
    return any(name == n or name.startswith(n + "_") for n in RECONCILE_NAMES)


@register
class ReconcileIORule(Rule):
    name = "reconcile-io"
    severity = P0
    doc = (
        "time.sleep / raw socket / bare HTTP call inside a controller "
        "reconcile or poll body — I/O must go through the metered cloud "
        "decorator or resilience.RetryPolicy."
    )
    path_must_contain = ("controllers/",)

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in self.files(project):
            sleep_aliases = self._sleep_aliases(src)
            for node in src.nodes():
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and _is_reconcile(node):
                    self._check_body(src, node, sleep_aliases, findings)
        return findings

    @staticmethod
    def _sleep_aliases(src: SourceFile) -> set:
        out = set()
        for node in src.nodes():
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for alias in node.names:
                    if alias.name == "sleep":
                        out.add(alias.asname or "sleep")
        return out

    def _check_body(
        self, src: SourceFile, fn: ast.AST, sleep_aliases: set, findings: List[Finding]
    ) -> None:
        for node in ast.walk(fn):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                mods = [a.name for a in node.names]
                if isinstance(node, ast.ImportFrom) and node.module:
                    mods = [node.module]
                for mod in mods:
                    for prefix, why in BANNED_PREFIXES.items():
                        if mod == prefix.rstrip(".") or mod.startswith(prefix):
                            findings.append(
                                self.finding(
                                    src.path, node.lineno,
                                    f"{why} imported inside `{fn.name}` — route "
                                    "through the metered provider or RetryPolicy",
                                )
                            )
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            if dn in BANNED_CALLS:
                findings.append(
                    self.finding(
                        src.path, node.lineno,
                        f"{BANNED_CALLS[dn]} (in `{fn.name}`)",
                    )
                )
            elif dn in sleep_aliases:
                findings.append(
                    self.finding(
                        src.path, node.lineno,
                        f"`time.sleep` stalls the reconcile round outside any "
                        f"Budget (in `{fn.name}`)",
                    )
                )
            else:
                for prefix, why in BANNED_PREFIXES.items():
                    if dn.startswith(prefix):
                        findings.append(
                            self.finding(
                                src.path, node.lineno,
                                f"{why} in `{fn.name}` — route through the "
                                "metered provider or RetryPolicy",
                            )
                        )
                        break
