"""Patch safety: RFC 7386 list-valued writes go through the RMW helpers.

JSON merge patch replaces arrays WHOLESALE: a patch carrying
``{"conditions": [mine]}`` erases every condition owned by another writer
(the PR-1 ``_set_active`` clobber). List-valued fields with multiple
writers — ``conditions``, ``taints`` — must be written as a
read-modify-write of the freshest cached object, through the helpers in
``karpenter_tpu.kube.patch`` (``upsert_condition`` / ``upsert_keyed`` /
``without_keyed``).

The rule inspects dict literals passed to ``merge_patch`` /
``patch_status`` (recursing through nested literals): a ``conditions`` /
``taints`` / ``finalizers`` key may carry

- a bare name (the builder pattern: the full RMW'd list built above), or
- a call to one of the RMW helpers;

a list literal, comprehension, or concatenation directly in the patch is
the clobber shape and fires.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from tools.karplint.core import (
    P0,
    Finding,
    Project,
    Rule,
    dotted_name,
    register,
)

PATCH_METHODS = ("merge_patch", "patch_status")
LIST_FIELDS = ("conditions", "taints", "finalizers")
RMW_HELPERS = {
    "upsert_condition", "upsert_keyed", "without_keyed", "without_value",
    "upsert_taint", "merge_conditions",
}


def _list_fields_in(d: ast.Dict) -> Iterator[Tuple[str, ast.AST]]:
    for key, value in zip(d.keys, d.values):
        if isinstance(key, ast.Constant) and key.value in LIST_FIELDS:
            yield key.value, value
        if isinstance(value, ast.Dict):
            yield from _list_fields_in(value)


def _is_rmw_value(value: ast.AST) -> bool:
    if isinstance(value, (ast.Name, ast.Attribute)):
        return True  # built (and RMW'd) above; the reader can audit one name
    if isinstance(value, ast.Call):
        dn = dotted_name(value.func) or ""
        return dn.rsplit(".", 1)[-1] in RMW_HELPERS
    return False


@register
class PatchLiteralListRule(Rule):
    name = "patch-literal-list"
    severity = P0
    doc = (
        "A merge-patch writes a list-valued field (conditions/taints) with "
        "a literal list — RFC 7386 replaces arrays wholesale, erasing other "
        "writers' entries; go through kube.patch's RMW helpers."
    )

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.files:
            if src.path.endswith("kube/patch.py"):
                continue  # the helpers themselves build the lists
            for node in src.nodes():
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in PATCH_METHODS
                ):
                    continue
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if not isinstance(arg, ast.Dict):
                        continue
                    for field, value in _list_fields_in(arg):
                        if not _is_rmw_value(value):
                            findings.append(
                                self.finding(
                                    src.path, value.lineno,
                                    f"`{field}` written with a literal list in a "
                                    f"{node.func.attr} payload — RFC 7386 replaces "
                                    "arrays wholesale; build the full list via "
                                    "kube.patch.upsert_keyed/upsert_condition",
                                )
                            )
        return findings
