"""karplint — project-invariant static analysis for karpenter-tpu.

Stdlib-only (pure ``ast``): it must run in any build stage — the slim
Docker image, CI before dependencies install, a contributor's bare
checkout — without importing the package under analysis.

Rule families (docs/static-analysis.md has the catalog and the incident
each rule descends from):

- ``tracer-*``   — tracer safety inside jit/vmap/pallas-reachable solver code
- ``lock-guard`` — ``# guarded-by:`` lock discipline for shared state
- ``reconcile-io`` — no raw sleeps/sockets/HTTP inside controller reconciles
- ``retry-idempotent`` — retried callables carry ``@idempotent``; create-path
  mutators must not
- ``patch-literal-list`` — RFC 7386 list-valued patches go through the RMW
  helpers
- ``metric-name`` — Prometheus naming conventions + docs listing
"""

from tools.karplint.core import (  # noqa: F401
    Analyzer,
    Baseline,
    Finding,
    Project,
    Rule,
    all_rules,
    rule_names,
)

__version__ = "1.0"
