"""Shared cross-file call graph (one build per fileset, memoized).

Every interprocedural rule — the tracer pair over ``solver/``, the
lock-order/lock-blocking pair and the mutation-guard rule over the whole
tree — resolves calls through this one structure. It is built from the
ASTs the :class:`~tools.karplint.core.Project` already parsed (no second
parse) and memoized per (project, fileset), so an ``Analyzer.run`` with
every rule enabled constructs at most one whole-tree graph plus one
``solver/``-scoped graph no matter how many rules consume them.

Resolution is best-effort and deliberately under-approximate:

- bare names: local defs, then ``from x import f`` symbols;
- ``mod.f`` where ``mod`` is an imported module in the fileset;
- ``self.f()`` / ``cls.f()``: methods of the lexically enclosing class in
  the same file (the controller-helper convention the lock and guard
  rules need);
- ``self.x.f()`` / ``self.x.y.f()``: when the attribute chain is typed by
  constructor assignment (``self.x = SomeClass(...)`` anywhere in the
  class, with ``SomeClass`` defined in the fileset), the call resolves to
  that class's method — this is how a controller's call into its
  orchestrator/terminator collaborators resolves across files;
- local collaborator aliases: ``t = self.termination.terminator`` then
  ``t.f()`` resolves through the same attribute-type map, and
  ``p = SomeClass(...)`` then ``p.f()`` through the constructor;
- anything else (arbitrary object attributes, dynamic dispatch,
  parameter-injected collaborators without a constructor call) resolves
  to nothing — silence over noise.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.karplint.core import (
    Project,
    SourceFile,
    dotted_name,
    import_tables,
)

JIT_WRAPPERS = ("jit", "vmap", "pmap")

# how many CallGraph constructions have run — the memoization acceptance
# test pins this so a rule can't quietly reintroduce a per-rule rebuild
BUILD_COUNT = 0


def walk_no_funcs(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/class defs."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(cur))


@dataclass
class FuncInfo:
    file: SourceFile
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    qualname: str
    parent: Optional["FuncInfo"]
    cls: Optional[str] = None  # enclosing class name, if a method
    children: List["FuncInfo"] = field(default_factory=list)
    static_argnames: Set[str] = field(default_factory=set)
    is_root: bool = False

    @property
    def name(self) -> str:
        return self.node.name


class CallGraph:
    """Function defs + best-effort resolved call edges across the fileset."""

    def __init__(self, files: Sequence[SourceFile]):
        global BUILD_COUNT
        BUILD_COUNT += 1
        self.files = list(files)
        self.funcs: List[FuncInfo] = []
        self.by_file_name: Dict[Tuple[str, str], List[FuncInfo]] = {}
        self.by_method: Dict[Tuple[str, str, str], List[FuncInfo]] = {}
        self.module_of: Dict[str, SourceFile] = {}
        self.imports: Dict[str, Tuple[dict, dict]] = {}
        self.module_consts: Dict[str, Set[str]] = {}
        # (path, class name) -> the class exists in the fileset
        self.classes: Set[Tuple[str, str]] = set()
        # (path, class, attr) -> (path2, class2): self.attr was assigned a
        # constructor call of a fileset class somewhere in the class body
        self.attr_types: Dict[Tuple[str, str, str], Tuple[str, str]] = {}
        for f in self.files:
            self.module_of[f.path[:-3].replace("/", ".")] = f
            # import tables survive across graph builds (the whole-tree and
            # solver-scoped graphs share files) — cache on the SourceFile
            cached = getattr(f, "_karplint_imports", None)
            if cached is None:
                cached = import_tables(f.tree)
                f._karplint_imports = cached
            self.imports[f.path] = cached
            self.module_consts[f.path] = {
                t.id
                for node in f.tree.body
                if isinstance(node, ast.Assign)
                for t in node.targets
                if isinstance(t, ast.Name) and isinstance(node.value, ast.Constant)
            }
            self._collect_funcs(f)
        for f in self.files:
            self._scan_roots_and_attr_types(f)
        self._callee_cache: Dict[int, List[FuncInfo]] = {}
        self._alias_cache: Dict[int, Dict[str, Tuple[str, str]]] = {}

    def _collect_funcs(self, f: SourceFile) -> None:
        def visit(node: ast.AST, parent: Optional[FuncInfo], prefix: str, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info = FuncInfo(
                        file=f, node=child,
                        qualname=f"{prefix}{child.name}", parent=parent, cls=cls,
                    )
                    info.static_argnames = _decorator_statics(child)
                    if _decorated_jit(child):
                        info.is_root = True
                    self.funcs.append(info)
                    if parent:
                        parent.children.append(info)
                    self.by_file_name.setdefault((f.path, child.name), []).append(info)
                    if cls:
                        self.by_method.setdefault(
                            (f.path, cls, child.name), []
                        ).append(info)
                    # a nested def is no longer a method of the class
                    visit(child, info, f"{info.qualname}.", None)
                elif isinstance(child, ast.ClassDef):
                    self.classes.add((f.path, child.name))
                    visit(child, parent, f"{prefix}{child.name}.", child.name)
                else:
                    visit(child, parent, prefix, cls)

        visit(f.tree, None, "", None)

    def _scan_roots_and_attr_types(self, f: SourceFile) -> None:
        """One pass over the file's nodes (reusing the parent-link index the
        :class:`SourceFile` already built — no re-walk): mark jit/vmap/pmap/
        pallas_call'd names as roots, and record ``self.x = SomeClass(...)``
        constructor assignments as attribute types."""
        for node in f.parents:
            if isinstance(node, ast.Call):
                dn = dotted_name(node.func) or ""
                tail = dn.rsplit(".", 1)[-1]
                if tail in JIT_WRAPPERS or tail == "pallas_call":
                    for target in _callable_args(node):
                        for info in self.by_file_name.get((f.path, target), []):
                            info.is_root = True
                            if tail in JIT_WRAPPERS:
                                info.static_argnames |= _call_statics(node)
                continue
            value, targets = None, []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            if not isinstance(value, ast.Call):
                continue
            attrs = [
                t
                for t in targets
                if isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ]
            if not attrs:
                continue
            cls = next(
                (a.name for a in f.ancestors(node) if isinstance(a, ast.ClassDef)),
                None,
            )
            if cls is None:
                continue
            typed = self._resolve_class(f, value.func)
            if typed is None:
                continue
            for t in attrs:
                self.attr_types[(f.path, cls, t.attr)] = typed

    def _resolve_class(self, f: SourceFile, ctor: ast.AST) -> Optional[Tuple[str, str]]:
        """(path, class) when ``ctor`` names a fileset class (same file,
        ``from x import Cls``, or ``mod.Cls``)."""
        dn = dotted_name(ctor)
        if dn is None:
            return None
        modules, symbols = self.imports[f.path]
        if "." not in dn:
            if (f.path, dn) in self.classes:
                return (f.path, dn)
            if dn in symbols:
                mod, sym = symbols[dn]
                target = self._file_for_module(mod)
                if target and (target.path, sym) in self.classes:
                    return (target.path, sym)
            return None
        root, attr = dn.rsplit(".", 1)
        if root in modules:
            target = self._file_for_module(modules[root])
            if target and (target.path, attr) in self.classes:
                return (target.path, attr)
        return None

    def _walk_attr_chain(
        self, start: Tuple[str, str], segs: Sequence[str]
    ) -> Optional[Tuple[str, str]]:
        cur: Optional[Tuple[str, str]] = start
        for seg in segs:
            if cur is None:
                return None
            cur = self.attr_types.get((cur[0], cur[1], seg))
        return cur

    def _local_aliases(self, fn: "FuncInfo") -> Dict[str, Tuple[str, str]]:
        """Local names in ``fn`` bound to a typed collaborator: either
        ``x = SomeClass(...)`` or ``x = self.a.b`` resolved through the
        attribute-type map."""
        cached = self._alias_cache.get(id(fn))
        if cached is not None:
            return cached
        out: Dict[str, Tuple[str, str]] = {}
        for node in walk_no_funcs(fn.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            if isinstance(node.value, ast.Call):
                typed = self._resolve_class(fn.file, node.value.func)
                if typed:
                    out[target.id] = typed
                continue
            dn = dotted_name(node.value)
            if dn and dn.startswith("self.") and fn.cls:
                typed = self._walk_attr_chain(
                    (fn.file.path, fn.cls), dn.split(".")[1:]
                )
                if typed:
                    out[target.id] = typed
        self._alias_cache[id(fn)] = out
        return out

    def resolve_call(
        self,
        f: SourceFile,
        call: ast.Call,
        cls: Optional[str] = None,
        fn: Optional["FuncInfo"] = None,
    ) -> List[FuncInfo]:
        """Targets of ``call`` made from file ``f`` (``cls`` = enclosing
        class of the caller, enabling ``self.method()`` edges; ``fn`` =
        the calling function, enabling local collaborator aliases)."""
        modules, symbols = self.imports[f.path]
        func = call.func
        if isinstance(func, ast.Name):
            local = self.by_file_name.get((f.path, func.id))
            if local:
                return local
            if func.id in symbols:
                mod, sym = symbols[func.id]
                target = self._file_for_module(mod)
                if target:
                    return self.by_file_name.get((target.path, sym), [])
            return []
        if not isinstance(func, ast.Attribute):
            return []
        recv_dn = dotted_name(func.value)
        if recv_dn is None:
            return []
        segs = recv_dn.split(".")
        if segs[0] in ("self", "cls") and cls is not None:
            if len(segs) == 1:
                return self.by_method.get((f.path, cls, func.attr), [])
            owner = self._walk_attr_chain((f.path, cls), segs[1:])
            if owner:
                return self.by_method.get((owner[0], owner[1], func.attr), [])
            return []
        if len(segs) == 1 and segs[0] in modules:
            target = self._file_for_module(modules[segs[0]])
            if target:
                hit = self.by_file_name.get((target.path, func.attr))
                if hit:
                    return hit
        if fn is not None:
            aliases = self._local_aliases(fn)
            if segs[0] in aliases:
                owner = self._walk_attr_chain(aliases[segs[0]], segs[1:])
                if owner:
                    return self.by_method.get((owner[0], owner[1], func.attr), [])
        return []

    def callees(self, fn: FuncInfo) -> List[FuncInfo]:
        """Resolved direct callees of ``fn``'s own body (not nested defs),
        memoized — the fixpoint passes in the lock/guard rules re-walk
        these edges many times."""
        cached = self._callee_cache.get(id(fn))
        if cached is not None:
            return cached
        out: List[FuncInfo] = []
        for node in walk_no_funcs(fn.node):
            if isinstance(node, ast.Call):
                out.extend(self.resolve_call(fn.file, node, cls=fn.cls, fn=fn))
        self._callee_cache[id(fn)] = out
        return out

    def _file_for_module(self, dotted: str) -> Optional[SourceFile]:
        for mod, f in self.module_of.items():
            if mod == dotted or mod.endswith("." + dotted) or dotted.endswith("." + mod):
                return f
        return None

    def reachable(self) -> List[FuncInfo]:
        seen: Set[int] = set()
        work = [fn for fn in self.funcs if fn.is_root]
        out: List[FuncInfo] = []
        while work:
            fn = work.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            out.append(fn)
            work.extend(fn.children)
            work.extend(self.callees(fn))
            # calls inside nested defs traverse when the child pops
        return out


def get_graph(project: Project, files: Optional[Sequence[SourceFile]] = None) -> CallGraph:
    """The memoized per-project graph over ``files`` (default: every file).

    Keyed by the fileset's paths, so the tracer rules' ``solver/``-scoped
    graph and the whole-tree graph coexist without rebuilding either."""
    files = list(files) if files is not None else project.files
    key = ("callgraph", tuple(f.path for f in files))
    graph = project.cache.get(key)
    if graph is None:
        graph = CallGraph(files)
        project.cache[key] = graph
    return graph


def _callable_args(call: ast.Call) -> List[str]:
    """Simple names passed as callables: bare ``f`` or ``partial(f, ...)``."""
    out = []
    for arg in call.args[:1] or []:
        if isinstance(arg, ast.Name):
            out.append(arg.id)
        elif isinstance(arg, ast.Call):
            dn = dotted_name(arg.func) or ""
            if dn.rsplit(".", 1)[-1] == "partial" and arg.args:
                first = arg.args[0]
                if isinstance(first, ast.Name):
                    out.append(first.id)
    return out


def _statics_from_value(value: ast.AST) -> Set[str]:
    if isinstance(value, ast.Constant) and isinstance(value.value, str):
        return {value.value}
    if isinstance(value, (ast.Tuple, ast.List)):
        return {
            e.value
            for e in value.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        }
    return set()


def _call_statics(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            return _statics_from_value(kw.value)
    return set()


def _decorated_jit(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        dn = dotted_name(target) or ""
        tail = dn.rsplit(".", 1)[-1]
        if tail in JIT_WRAPPERS:
            return True
        if tail == "partial" and isinstance(dec, ast.Call) and dec.args:
            inner = dotted_name(dec.args[0]) or ""
            if inner.rsplit(".", 1)[-1] in JIT_WRAPPERS:
                return True
    return False


def _decorator_statics(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for dec in getattr(fn, "decorator_list", []):
        if isinstance(dec, ast.Call):
            out |= _call_statics(dec)
    return out
