"""karplint CLI.

Usage (from the repo root)::

    python -m tools.karplint karpenter_tpu           # analyze the tree
    python -m tools.karplint drift karpenter_tpu     # drift-* rules only
    python -m tools.karplint --format sarif karpenter_tpu
    python -m tools.karplint --list-rules
    python -m tools.karplint --selftest tests/karplint_fixtures
    python -m tools.karplint --write-baseline karpenter_tpu

``drift`` (a leading positional) narrows the run to the ``drift-*``
cross-artifact rules — the fast pre-merge gate for docs/deploy/chart/test
edits that don't touch Python. ``--format sarif`` emits SARIF 2.1.0 for
CI annotation (text stays the default; ``json`` is the raw dump).

Exit codes: 0 clean, 1 findings (or a failed selftest), 2 usage/config
error. ``--selftest`` runs the analyzer over the fixture corpus and checks
each fixture's expectation header::

    # karplint-fixture: expect=rule-a,rule-b   (each rule must fire here)
    # karplint-fixture: clean=rule-a           (rule must NOT fire here)

and additionally requires every registered rule to be demonstrated by at
least one ``expect`` fixture — a rule nobody can make fire is a rule that
is silently broken.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
from pathlib import Path

# allow `python tools/karplint` as well as `python -m tools.karplint`
_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
if str(_REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(_REPO_ROOT))

from tools.karplint.core import Analyzer, Baseline, all_rules  # noqa: E402

FIXTURE_RE = re.compile(r"#\s*karplint-fixture:\s*(expect|clean)=([A-Za-z0-9_\-, ]+)")
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="karplint")
    ap.add_argument("paths", nargs="*", default=[], help="files/dirs to analyze")
    ap.add_argument("--root", default=".", help="project root (docs + relative paths)")
    ap.add_argument("--rules", help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE))
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--allow-p0-baseline", action="store_true")
    ap.add_argument("--format", choices=("text", "json", "sarif"), default="text")
    ap.add_argument("--selftest", metavar="CORPUS",
                    help="run the fixture corpus and verify every rule fires")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.name:22s} [{rule.severity}] {rule.doc}")
        return 0

    rules = [r.strip() for r in args.rules.split(",")] if args.rules else None

    # `karplint drift <paths>`: the cross-artifact gate, scoped to the
    # drift-* rules (composable with --rules to narrow further)
    if args.paths and args.paths[0] == "drift":
        args.paths = args.paths[1:]
        drift_rules = [r.name for r in all_rules() if r.name.startswith("drift-")]
        rules = [r for r in rules if r in drift_rules] if rules else drift_rules
        if not rules:
            print("karplint: --rules excludes every drift-* rule", file=sys.stderr)
            return 2
    root = Path(args.root)

    if args.selftest:
        return _selftest(Path(args.selftest), rules)

    paths = args.paths or ["karpenter_tpu"]
    try:
        analyzer = Analyzer(root, paths, rules=rules)
    except ValueError as e:
        print(f"karplint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        all_pairs = analyzer.fingerprints()
        pairs = [
            (f, fp) for f, fp in all_pairs
            if f.severity != "P0" or args.allow_p0_baseline
        ]
        Baseline.from_findings(pairs).save(Path(args.baseline))
        print(f"karplint: wrote {len(pairs)} entries to {args.baseline}")
        skipped = len(all_pairs) - len(pairs)
        if skipped:
            print(
                f"karplint: {skipped} P0 finding(s) NOT baselined — fix them",
                file=sys.stderr,
            )
            return 1
        return 0

    t0 = time.perf_counter()
    baseline = None if args.no_baseline else Baseline.load(Path(args.baseline))
    active, baselined = analyzer.run(
        baseline=baseline, allow_p0_baseline=args.allow_p0_baseline
    )
    elapsed = time.perf_counter() - t0

    if args.format == "sarif":
        print(json.dumps(_to_sarif(active, analyzer), indent=2))
    elif args.format == "json":
        print(json.dumps(
            {
                "findings": [f.__dict__ for f in active],
                "baselined": len(baselined),
                "parse_errors": analyzer.parse_errors,
                "elapsed_s": round(elapsed, 3),
            },
            indent=2,
        ))
    else:
        for f in active:
            print(f.render())
        for err in analyzer.parse_errors:
            print(f"parse error: {err}", file=sys.stderr)
        summary = (
            f"karplint: {len(active)} finding(s), {len(baselined)} baselined, "
            f"{len(analyzer.rules)} rules, {elapsed:.2f}s"
        )
        print(summary, file=sys.stderr)
    return 1 if active or analyzer.parse_errors else 0


def _to_sarif(active, analyzer) -> dict:
    """SARIF 2.1.0 document for CI annotation: one run, every registered
    rule described on the driver (so viewers can render the catalog),
    P0 → error, P1 → warning, parse errors as tool notifications."""
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "karplint",
                        "informationUri": "docs/static-analysis.md",
                        "rules": [
                            {
                                "id": rule.name,
                                "shortDescription": {"text": rule.doc},
                                "defaultConfiguration": {
                                    "level": (
                                        "error"
                                        if rule.severity == "P0"
                                        else "warning"
                                    ),
                                },
                                "properties": {"severity": rule.severity},
                            }
                            for rule in analyzer.rules
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": "error" if f.severity == "P0" else "warning",
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {
                                        "uri": f.path,
                                        "uriBaseId": "SRCROOT",
                                    },
                                    "region": {"startLine": f.line},
                                }
                            }
                        ],
                    }
                    for f in active
                ],
                "invocations": [
                    {
                        "executionSuccessful": not analyzer.parse_errors,
                        "toolExecutionNotifications": [
                            {"level": "error", "message": {"text": err}}
                            for err in analyzer.parse_errors
                        ],
                    }
                ],
            }
        ],
    }


def _selftest(corpus: Path, rules=None) -> int:
    if not corpus.is_dir():
        print(f"karplint: no fixture corpus at {corpus}", file=sys.stderr)
        return 2
    analyzer = Analyzer(corpus, ["."], rules=rules)
    active, _ = analyzer.run(baseline=None)
    by_file: dict = {}
    for f in active:
        by_file.setdefault(f.path, []).append(f)

    failures = []
    demonstrated = set()
    fixture_count = 0
    for src_path in sorted(p.relative_to(corpus).as_posix() for p in corpus.rglob("*.py")):
        text = (corpus / src_path).read_text()
        expects, cleans = set(), set()
        for kind, names in FIXTURE_RE.findall(text):
            names = {n.strip() for n in names.split(",") if n.strip()}
            (expects if kind == "expect" else cleans).update(names)
        if not expects and not cleans:
            continue
        fixture_count += 1
        fired = {f.rule for f in by_file.get(src_path, [])}
        for rule in sorted(expects):
            demonstrated.add(rule)
            if rule not in fired:
                failures.append(f"{src_path}: expected `{rule}` to fire; it did not")
        for rule in sorted(cleans):
            if rule in fired:
                lines = [
                    str(f.line) for f in by_file[src_path] if f.rule == rule
                ]
                failures.append(
                    f"{src_path}: `{rule}` fired on a near-miss "
                    f"(line {', '.join(lines)})"
                )

    registered = {r.name for r in analyzer.rules}
    for rule in sorted(registered - demonstrated):
        failures.append(
            f"rule `{rule}` has no firing fixture in {corpus} — add one"
        )

    if failures:
        for msg in failures:
            print(f"selftest FAIL: {msg}")
        return 1
    print(
        f"karplint selftest: {fixture_count} fixtures, "
        f"{len(registered)} rules demonstrated, corpus behaves"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
