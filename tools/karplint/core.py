"""karplint core: project model, rule registry, suppressions, baseline.

The analyzer parses every ``*.py`` under the scan paths ONCE into a
:class:`Project` (source text + ast + per-line suppressions), then hands the
whole project to each registered :class:`Rule`. Rules are project-scoped —
the tracer rules need a cross-file call graph, the metric rule needs the
docs tree — and file-local rules simply iterate ``project.files``.

Suppression syntax (per line, same line as the finding)::

    something_suspect()  # karplint: disable=rule-name
    something_else()     # karplint: disable          (all rules)

Baseline: a checked-in JSON of grandfathered finding fingerprints
(``tools/karplint/baseline.json``). A fingerprint hashes (rule, path,
normalized source line) — not the line NUMBER — so unrelated edits above a
grandfathered finding don't resurrect it. P0 findings are never
baselineable: the baseline exists to stage P1 cleanups, not to silence
races and host syncs.
"""

from __future__ import annotations

import ast
import gc
import hashlib
import json
import re
from contextlib import contextmanager
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple


@contextmanager
def _gc_paused():
    """Parsing a few hundred files allocates millions of AST/container
    objects, and every generational GC pass walks the host process's whole
    live heap — inside a loaded pytest process that heap dwarfs the
    analyzer's own. The analyzer builds essentially no reference cycles,
    so pause collection for the run and let the exit sweep reclaim."""
    was_enabled = gc.isenabled()
    gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()

SUPPRESS_RE = re.compile(r"#\s*karplint:\s*disable(?:=([A-Za-z0-9_\-, ]+))?")
GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")

P0 = "P0"  # must fix — never baselineable
P1 = "P1"  # should fix — baselineable while staged


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # posix, relative to the project root
    line: int
    severity: str
    message: str

    def fingerprint(self, source_line: str) -> str:
        basis = f"{self.rule}|{self.path}|{' '.join(source_line.split())}"
        return hashlib.sha1(basis.encode()).hexdigest()[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.severity}] {self.rule}: {self.message}"


class SourceFile:
    def __init__(self, root: Path, abspath: Path):
        self.abspath = abspath
        self.path = abspath.relative_to(root).as_posix()
        self.text = abspath.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(abspath))
        # line -> None (all rules) or set of rule names
        self.suppressions: Dict[int, Optional[Set[str]]] = {}
        for lineno, line in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if m:
                names = m.group(1)
                self.suppressions[lineno] = (
                    {n.strip() for n in names.split(",") if n.strip()}
                    if names
                    else None
                )
        # parent links: rules need lexical enclosure (with-blocks, classes)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, finding: Finding) -> bool:
        rules = self.suppressions.get(finding.line, False)
        if rules is False:
            return False
        return rules is None or finding.rule in rules

    def guarded_by(self, lineno: int) -> Optional[str]:
        """The ``# guarded-by: <lock>`` annotation on this line, if any."""
        m = GUARDED_BY_RE.search(self.line_at(lineno))
        return m.group(1) if m else None

    def nodes(self) -> Iterable[ast.AST]:
        """Every node except the Module root, in ``ast.walk`` order —
        rules iterate this instead of re-walking the tree (the parent
        index built at load already enumerated every node once)."""
        return self.parents.keys()

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)


class Project:
    def __init__(self, root: Path, files: Sequence[SourceFile]):
        self.root = root
        self.files = list(files)
        self.by_path = {f.path: f for f in self.files}
        # cross-rule memoization (call graphs, lock maps): one AST walk
        # per analysis structure per run, not per rule
        self.cache: Dict[object, object] = {}

    def matching(self, pred: Callable[[str], bool]) -> List[SourceFile]:
        return [f for f in self.files if pred(f.path)]


class Rule:
    """One invariant. Subclasses set ``name``/``severity``/``doc`` and
    implement ``run(project)``. ``path_must_contain`` (when set) restricts
    which files the convenience ``files()`` iterator yields — the rule
    itself decides whether to use it."""

    name: str = ""
    severity: str = P1
    doc: str = ""
    path_must_contain: Optional[Tuple[str, ...]] = None

    def files(self, project: Project) -> List[SourceFile]:
        if not self.path_must_contain:
            return project.files
        return project.matching(
            lambda p: any(s in p for s in self.path_must_contain)
        )

    def run(self, project: Project) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, path: str, line: int, message: str, severity: Optional[str] = None) -> Finding:
        return Finding(
            rule=self.name, path=path, line=line,
            severity=severity or self.severity, message=message,
        )


_REGISTRY: Dict[str, Rule] = {}


def register(rule_cls: type) -> type:
    rule = rule_cls()
    if not rule.name:
        raise ValueError(f"rule {rule_cls.__name__} has no name")
    if rule.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name}")
    _REGISTRY[rule.name] = rule
    return rule_cls


def all_rules() -> List[Rule]:
    _load_rules()
    return [_REGISTRY[n] for n in sorted(_REGISTRY)]


def rule_names() -> List[str]:
    _load_rules()
    return sorted(_REGISTRY)


_rules_loaded = False


def _load_rules() -> None:
    global _rules_loaded
    if _rules_loaded:
        return
    # import for side effect: each module registers its rules
    from tools.karplint.rules import (  # noqa: F401
        debug_endpoints,
        drift,
        events,
        kube,
        lock_order,
        locks,
        metric_names,
        mutation_guard,
        patch,
        purity,
        retry,
        spans,
        tracer,
        waits,
    )

    _rules_loaded = True


class Baseline:
    """Checked-in set of grandfathered finding fingerprints."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = entries or []
        self._index = {(e["rule"], e["path"], e["fingerprint"]) for e in self.entries}

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls()
        data = json.loads(path.read_text())
        return cls(data.get("findings", []))

    def save(self, path: Path) -> None:
        path.write_text(
            json.dumps(
                {"version": 1, "findings": self.entries}, indent=2, sort_keys=True
            )
            + "\n"
        )

    def contains(self, finding: Finding, fingerprint: str) -> bool:
        return (finding.rule, finding.path, fingerprint) in self._index

    @classmethod
    def from_findings(cls, pairs: List[Tuple[Finding, str]]) -> "Baseline":
        entries = [
            {
                "rule": f.rule,
                "path": f.path,
                "fingerprint": fp,
                "justification": "TODO: why this finding is grandfathered",
            }
            for f, fp in sorted(pairs, key=lambda p: (p[0].path, p[0].line))
        ]
        return cls(entries)


def _iter_py_files(root: Path, paths: Sequence[str]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        target = (root / p).resolve()
        if target.is_file() and target.suffix == ".py":
            out.append(target)
        elif target.is_dir():
            for f in sorted(target.rglob("*.py")):
                if "__pycache__" in f.parts or any(
                    part.startswith(".") for part in f.parts
                ):
                    continue
                out.append(f)
    return out


class Analyzer:
    def __init__(
        self,
        root: Path,
        paths: Sequence[str],
        rules: Optional[Sequence[str]] = None,
    ):
        self.root = root.resolve()
        self.paths = list(paths)
        wanted = set(rules) if rules else None
        self.rules = [
            r for r in all_rules() if wanted is None or r.name in wanted
        ]
        if wanted:
            unknown = wanted - {r.name for r in self.rules}
            if unknown:
                raise ValueError(f"unknown rules: {sorted(unknown)}")
        self.parse_errors: List[str] = []

    def load(self) -> Project:
        files = []
        for abspath in _iter_py_files(self.root, self.paths):
            try:
                files.append(SourceFile(self.root, abspath))
            except SyntaxError as e:
                self.parse_errors.append(f"{abspath}: {e}")
        return Project(self.root, files)

    def run(
        self, baseline: Optional[Baseline] = None, allow_p0_baseline: bool = False
    ) -> Tuple[List[Finding], List[Finding]]:
        """Returns (active findings, baselined findings)."""
        with _gc_paused():
            return self._run(baseline, allow_p0_baseline)

    def _run(
        self, baseline: Optional[Baseline], allow_p0_baseline: bool
    ) -> Tuple[List[Finding], List[Finding]]:
        project = self.load()
        active: List[Finding] = []
        baselined: List[Finding] = []
        for rule in self.rules:
            for f in rule.run(project):
                src = project.by_path[f.path]
                if src.suppressed(f):
                    continue
                fp = f.fingerprint(src.line_at(f.line))
                if (
                    baseline is not None
                    and baseline.contains(f, fp)
                    and (f.severity != P0 or allow_p0_baseline)
                ):
                    baselined.append(f)
                else:
                    active.append(f)
        active.sort(key=lambda f: (f.path, f.line, f.rule))
        baselined.sort(key=lambda f: (f.path, f.line, f.rule))
        return active, baselined

    def fingerprints(self) -> List[Tuple[Finding, str]]:
        """(finding, fingerprint) for every unsuppressed finding — the
        ``--write-baseline`` surface."""
        with _gc_paused():
            return self._fingerprints()

    def _fingerprints(self) -> List[Tuple[Finding, str]]:
        project = self.load()
        out = []
        for rule in self.rules:
            for f in rule.run(project):
                src = project.by_path[f.path]
                if src.suppressed(f):
                    continue
                out.append((f, f.fingerprint(src.line_at(f.line))))
        return out


# --- shared ast helpers used by several rules -------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def decorator_names(fn: ast.AST) -> List[str]:
    names = []
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        dn = dotted_name(target)
        if dn:
            names.append(dn)
    return names


def import_tables(tree: ast.Module) -> Tuple[Dict[str, str], Dict[str, Tuple[str, str]]]:
    """(module alias -> dotted module, symbol alias -> (dotted module, symbol)).

    ``import a.b as c`` -> modules['c'] = 'a.b'
    ``from a import b as c`` -> symbols['c'] = ('a', 'b')  AND, because
    ``b`` may itself be a module, modules['c'] = 'a.b'.
    """
    modules: Dict[str, str] = {}
    symbols: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                modules[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for alias in node.names:
                local = alias.asname or alias.name
                symbols[local] = (node.module, alias.name)
                modules[local] = f"{node.module}.{alias.name}"
    return modules, symbols
