"""Bench-trajectory regression gate: newest ``BENCH_r0x.json`` vs its
predecessor.

The repo's north star is a number (BASELINE: 10k pods x 400 types under
100ms p99) and the ``BENCH_r0x.json`` files are its trajectory — but until
now they were unchecked artifacts: a PR that halved ``pipelined_pods_per_sec``
would land green. This tool is the CI-side twin of the online SLO engine
(``karpenter_tpu/obs/slo.py``): offline, across runs, same philosophy —
a declared objective judged mechanically, with an allowlist (not silence)
for the regressions a human has explicitly accepted.

Usage (from the repo root)::

    python -m tools.bench_compare                 # newest two BENCH_r0x.json
    python -m tools.bench_compare OLD.json NEW.json
    python -m tools.bench_compare --report        # non-fatal (make benchmark)

Exit codes: 0 clean (or ``--report``), 1 regression beyond the threshold,
2 usage error (fewer than two bench files, unreadable JSON, bad allowlist).

Comparison semantics:

- Headline keys only (``HEADLINE_KEYS``): each carries a direction —
  ``pipelined_pods_per_sec`` up is good, ``device_p99_s`` down is good.
- A key missing on either side is reported but never fails the gate: bench
  legs are budgeted (``BENCH_BUDGET_S``) and a capped run drops legs; the
  record line itself may even be tail-truncated (see ``extract_record``).
- Regression = worse by more than ``--threshold`` (default 10%) and not
  covered by the allowlist (``tools/bench_allowlist.json``: a list of
  ``{"key": ..., "reason": ...}`` entries; an entry may pin ``"new"`` to
  the run basename so the waiver dies with the run it excused).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

# key -> direction: +1 higher is better, -1 lower is better
HEADLINE_KEYS: Dict[str, int] = {
    "value": +1,  # the headline pods-scheduled/sec record line
    "pipelined_pods_per_sec": +1,
    "device_p99_s": -1,
    "session_catalog_hit_rate": +1,
    "chaos_provision_success_rate": +1,
    # fleet telemetry plane (docs/telemetry.md): the stitched-attribution
    # keys — the worst live-wire solve's fleet-wide critical path and the
    # transport's share of it — plus the always-on profiler's
    # self-accounted cost (bar: < 1). Missing on pre-telemetry rounds is
    # reported, never fatal (the standard new-key salvage).
    "fleet_critical_path_ms": -1,
    "wire_share_pct": -1,
    "profiler_overhead_pct": -1,
    # streamed solver transport (docs/solver-transport.md § Streaming):
    # throughput over the persistent stream, its per-solve transport
    # floor, and the share of streamed solves that coalesced into shared
    # device dispatches. Missing on pre-stream rounds is reported, never
    # fatal (the standard new-key salvage).
    "streamed_pods_per_sec": +1,
    "streamed_rtt_floor_ms": -1,
    "stream_coalesced_dispatch_rate": +1,
    # decision observability plane (docs/decisions.md): the self-accounted
    # hot-path cost of per-round decision records + elimination
    # attribution on the headline leg (bar: < 1). Missing on pre-decision
    # rounds is reported, never fatal (the standard new-key salvage).
    "explain_overhead_pct": -1,
    # predictive provisioning (docs/forecasting.md): the warm pool's hit
    # rate and the resulting time-to-ready p99 on the forecast-storm leg.
    # Missing on pre-forecast rounds (or runs without the leg) is
    # reported, never fatal (the standard new-key salvage).
    "warm_hit_rate": +1,
    "time_to_ready_p99_s": -1,
    # disruption-safe consolidation (docs/consolidation.md): capacity the
    # storm leg actually handed back and the resulting $-delta (negative =
    # savings, so lower is better). Missing on pre-consolidation rounds is
    # reported, never fatal (the standard new-key salvage).
    "consolidation_nodes_reclaimed": +1,
    "consolidation_cost_delta_usd": -1,
    # resident delta encoding (docs/delta-encoding.md): the headline leg's
    # steady-state host-side cost per solve (sort+inject+encode+decode,
    # bar: < 10ms at the 10k-pod leg) and the fraction of measured
    # iterations any stage served from resident state. Missing on
    # pre-delta rounds is reported, never fatal (the standard new-key
    # salvage).
    "host_share_ms": -1,
    "delta_hit_rate": +1,
}

DEFAULT_ALLOWLIST = "tools/bench_allowlist.json"
_BENCH_RE = re.compile(r"^BENCH_r(\d+)\.json$")


def find_bench_files(root: Path) -> List[Path]:
    """All ``BENCH_r0x.json`` under ``root``, oldest round first."""
    out: List[Tuple[int, Path]] = []
    for p in root.iterdir():
        m = _BENCH_RE.match(p.name)
        if m:
            out.append((int(m.group(1)), p))
    return [p for _, p in sorted(out)]


def _salvage_tail(tail: str) -> Optional[Dict[str, Any]]:
    """Recover a record from a front-truncated JSON line.

    The bench harness stores only the last N chars of output (``tail``);
    a long record line loses its opening brace and some leading keys —
    possibly cutting inside a nested object. Reopen the object at each
    successive top-level-looking key boundary until one suffix parses:
    the first success is the maximal recoverable record.
    """
    line = tail.strip().splitlines()[-1] if tail.strip() else ""
    if not line:
        return None
    for m in re.finditer(r', "', line):
        try:
            got = json.loads("{" + line[m.start() + 2:])
        except json.JSONDecodeError:
            continue
        if isinstance(got, dict):
            return got
    return None


def extract_record(path: Path) -> Tuple[Dict[str, Any], bool]:
    """The bench record from one BENCH file: ``(record, truncated)``.

    Prefers the harness's ``parsed`` field; falls back to parsing the last
    line of ``tail``, then to suffix salvage (``truncated=True``) when the
    stored tail cut the record line's head off. A bare record line written
    by ``bench.py > out.json`` also works.
    """
    data = json.loads(path.read_text())
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        return data["parsed"], False
    if isinstance(data, dict) and "tail" in data:
        line = str(data["tail"]).strip().splitlines()[-1] if str(data["tail"]).strip() else ""
        try:
            got = json.loads(line)
            if isinstance(got, dict):
                return got, False
        except json.JSONDecodeError:
            pass
        got = _salvage_tail(str(data["tail"]))
        if got is not None:
            return got, True
        raise ValueError(f"{path}: no recoverable record line in tail")
    if isinstance(data, dict):
        return data, False
    raise ValueError(f"{path}: not a bench record")


def load_allowlist(path: Optional[Path]) -> List[Dict[str, str]]:
    if path is None or not path.exists():
        return []
    entries = json.loads(path.read_text())
    if not isinstance(entries, list) or not all(
        isinstance(e, dict) and "key" in e and "reason" in e for e in entries
    ):
        raise ValueError(
            f"{path}: allowlist must be a list of "
            '{"key": ..., "reason": ...[, "new": <run basename>]} entries'
        )
    return entries


def _allowed(
    entries: List[Dict[str, str]], key: str, new_name: str
) -> Optional[str]:
    for e in entries:
        if e["key"] != key:
            continue
        if "new" in e and e["new"] != new_name:
            continue  # the waiver was pinned to a different run
        return e["reason"]
    return None


def compare(
    old: Dict[str, Any],
    new: Dict[str, Any],
    threshold: float = 0.10,
    keys: Optional[Dict[str, int]] = None,
) -> List[Dict[str, Any]]:
    """Per-key comparison rows; ``verdict`` is ``ok`` / ``improved`` /
    ``regressed`` / ``missing_old`` / ``missing_new``. Regressions beyond
    the threshold are the gate's concern; the rest is the report."""
    rows: List[Dict[str, Any]] = []
    for key, direction in (keys or HEADLINE_KEYS).items():
        a, b = old.get(key), new.get(key)
        if not isinstance(a, (int, float)) or isinstance(a, bool):
            a = None
        if not isinstance(b, (int, float)) or isinstance(b, bool):
            b = None
        if a is None or b is None:
            rows.append({
                "key": key, "old": a, "new": b,
                "verdict": "missing_new" if b is None else "missing_old",
            })
            continue
        # signed change toward "better": positive = improvement
        change = (b - a) / abs(a) if a else 0.0
        better = change * direction
        verdict = "ok"
        if better < -threshold:
            verdict = "regressed"
        elif better > threshold:
            verdict = "improved"
        rows.append({
            "key": key, "old": a, "new": b,
            "delta_pct": round(change * 100, 1),
            "direction": "up" if direction > 0 else "down",
            "verdict": verdict,
        })
    return rows


def run(
    old_path: Path,
    new_path: Path,
    threshold: float = 0.10,
    allowlist_path: Optional[Path] = None,
) -> Dict[str, Any]:
    """The full gate: returns the report dict; ``report["failed"]`` lists
    unallowlisted regressions (nonzero = the gate should redden)."""
    old, old_trunc = extract_record(old_path)
    new, new_trunc = extract_record(new_path)
    entries = load_allowlist(allowlist_path)
    rows = compare(old, new, threshold=threshold)
    failed = []
    for row in rows:
        if row["verdict"] != "regressed":
            continue
        reason = _allowed(entries, row["key"], new_path.name)
        if reason is not None:
            row["verdict"] = "allowlisted"
            row["reason"] = reason
        else:
            failed.append(row["key"])
    return {
        "old": old_path.name,
        "new": new_path.name,
        "threshold_pct": round(threshold * 100, 1),
        "truncated": {
            **({"old": True} if old_trunc else {}),
            **({"new": True} if new_trunc else {}),
        },
        "rows": rows,
        "failed": failed,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_compare", description=__doc__.splitlines()[0]
    )
    ap.add_argument("files", nargs="*", metavar="OLD NEW",
                    help="two bench JSON files (default: the newest two "
                         "BENCH_r0x.json in --dir)")
    ap.add_argument("--dir", default=".", help="where BENCH_r0x.json live")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="regression tolerance as a fraction (default 0.10)")
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                    help="accepted-regression entries (JSON list)")
    ap.add_argument("--report", action="store_true",
                    help="print the comparison but always exit 0 "
                         "(the `make benchmark` non-fatal mode)")
    args = ap.parse_args(argv)

    if len(args.files) == 2:
        old_path, new_path = Path(args.files[0]), Path(args.files[1])
    elif not args.files:
        try:
            files = find_bench_files(Path(args.dir))
        except OSError:
            files = []
        if len(files) < 2:
            print(f"bench_compare: need two BENCH_r0x.json under {args.dir}, "
                  f"found {len(files)}", file=sys.stderr)
            return 2
        old_path, new_path = files[-2], files[-1]
    else:
        ap.print_usage(sys.stderr)
        return 2

    try:
        report = run(
            old_path, new_path,
            threshold=args.threshold,
            allowlist_path=Path(args.allowlist) if args.allowlist else None,
        )
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2

    print(json.dumps(report, indent=2))
    if report["failed"] and not args.report:
        print(
            f"bench_compare: REGRESSION {report['old']} -> {report['new']}: "
            + ", ".join(report["failed"])
            + f" (>{report['threshold_pct']}% worse; allowlist: {args.allowlist})",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
