"""What-if simulator: replay a recorded decision window against a
hypothetical warm-pool policy, offline.

The decision audit ring (docs/decisions.md) already records every
provisioning round — when it happened (``recorded_at``), which
provisioner, and how many pods were considered. That IS the arrival
series the forecaster (karpenter_tpu/forecast) would have seen live. This
tool re-runs that series through the real forecast models and a
discrete-event model of the warm-pool controller's wave/claim/TTL
lifecycle, so an operator can answer "what would the time-to-ready tail
and the speculation bill have looked like under THESE knobs" from a
support bundle, without touching the fleet:

    python -m tools.whatif --decision-dir DIR
    python -m tools.whatif --decision-dir DIR --warm-pool-ttl 300 \
        --seasonal --launch-to-ready-s 120 --node-price-per-h 4.2

Outputs one JSON document: per-provisioner predicted warm-hit rate,
time-to-ready p99 with and without the pool, speculative node-hours and
their $-cost. ``--sweep-ttl`` compares several TTLs in one run.

The same entry points are a library: ``bench.py``'s forecast-storm leg
calls :func:`load_series` + :func:`simulate` over the ring it just
recorded and cross-checks the predicted warm-hit rate against what the
live controller actually measured (the acceptance gate is agreement
within 20%).

``--replay`` additionally re-solves the newest replayable record through
``tools.replay_decision`` first — proving the window itself reproduces
bit-exact before trusting counterfactuals built on it.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from karpenter_tpu.forecast import (
    DEFAULT_BAND_SIGMA,
    DEFAULT_BUCKET_S,
    MODEL_EWMA,
    MODEL_HOLT_WINTERS,
    ShardForecast,
)

# Defaults mirror the live controller's knobs (options.py) so a bare
# `python -m tools.whatif --decision-dir DIR` models the shipped policy.
DEFAULT_TTL_S = 600.0
DEFAULT_MAX_WARM_NODES = 10
DEFAULT_WAVE_INTERVAL_S = 10.0
DEFAULT_LAUNCH_TO_READY_S = 90.0
DEFAULT_BIND_LATENCY_S = 2.0
# GCE a2-highgpu-ish list price; purely illustrative — override it.
DEFAULT_NODE_PRICE_PER_H = 3.67


# -- decision-ring intake ----------------------------------------------------


def load_records(decision_dir: str) -> List[Dict[str, Any]]:
    """Every parseable ``decision-*.json`` in the ring, oldest first
    (lexicographic filename IS time order — the flight-recorder
    discipline). Unreadable files are skipped, not fatal: a pruned ring
    mid-read is normal."""
    try:
        names = sorted(
            n for n in os.listdir(decision_dir)
            if n.startswith("decision-") and n.endswith(".json")
        )
    except OSError:
        return []
    out: List[Dict[str, Any]] = []
    for name in names:
        path = os.path.join(decision_dir, name)
        try:
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if "recorded_at" not in rec:
            # older record shape: fall back to the filename's ms stamp
            try:
                rec["recorded_at"] = int(name.split("-")[1]) / 1e3
            except (IndexError, ValueError):
                continue
        out.append(rec)
    return out


def load_series(
    decision_dir: str, provisioner: Optional[str] = None
) -> Dict[str, List[Tuple[float, float]]]:
    """Per-provisioner arrival series ``[(t, pods), ...]`` from the ring.

    Warm-pool wave records (``state.warm_pool_wave``) are audit entries,
    not demand — they are excluded so a pool that was ALREADY running
    does not feed its own speculation back into the counterfactual."""
    series: Dict[str, List[Tuple[float, float]]] = {}
    for rec in load_records(decision_dir):
        if (rec.get("state") or {}).get("warm_pool_wave"):
            continue
        name = rec.get("provisioner") or ""
        if not name or (provisioner and name != provisioner):
            continue
        pods = float(rec.get("pods_considered") or 0.0)
        series.setdefault(name, []).append((float(rec["recorded_at"]), pods))
    for points in series.values():
        points.sort(key=lambda p: p[0])
    return series


def measured_pods_per_node(records: Iterable[Dict[str, Any]]) -> float:
    """Mean pods-per-node over rounds that placed anything — the same
    unit conversion the live forecaster learns from round spans."""
    ratios = [
        float(r["pods_considered"]) / float(r["nodes"])
        for r in records
        if float(r.get("nodes") or 0) > 0
        and float(r.get("pods_considered") or 0) > 0
        and not (r.get("state") or {}).get("warm_pool_wave")
    ]
    if not ratios:
        return 1.0
    return max(sum(ratios) / len(ratios), 1.0)


# -- the counterfactual ------------------------------------------------------


def _p99(values: Sequence[float]) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(int(math.ceil(len(ordered) * 0.99)) - 1, len(ordered) - 1)
    return ordered[max(idx, 0)]


def simulate(
    series: Sequence[Tuple[float, float]],
    *,
    warm_pool_ttl: float = DEFAULT_TTL_S,
    max_nodes: int = DEFAULT_MAX_WARM_NODES,
    interval_s: float = DEFAULT_WAVE_INTERVAL_S,
    launch_to_ready_s: float = DEFAULT_LAUNCH_TO_READY_S,
    bind_latency_s: float = DEFAULT_BIND_LATENCY_S,
    pods_per_node: float = 1.0,
    model: str = MODEL_EWMA,
    alpha: float = 0.3,
    season_len: int = 24,
    bucket_s: float = DEFAULT_BUCKET_S,
    band_sigma: float = DEFAULT_BAND_SIGMA,
    horizon_s: float = DEFAULT_LAUNCH_TO_READY_S,
    node_price_per_h: float = DEFAULT_NODE_PRICE_PER_H,
) -> Dict[str, Any]:
    """Discrete-event replay of ONE provisioner's arrival series under a
    warm-pool policy.

    The event loop mirrors controllers/warmpool.py and the worker claim
    path exactly: a wave every ``interval_s`` sizes the pool off the
    forecaster's UPPER band over one horizon (ceil(pods/pods_per_node),
    capped at ``max_nodes`` standing); a speculative node becomes
    claimable ``launch_to_ready_s`` after its wave and is TTL-reclaimed
    ``warm_pool_ttl`` after it unless demand lands first. An arriving pod
    claims a ready warm node (time-to-ready = ``bind_latency_s``) when
    one fits, else pays the full cold ``launch_to_ready_s``. The no-pool
    baseline is the same series with every pod cold.

    Returns the prediction panel ``bench.py`` cross-checks against the
    live run: warm-hit rate, both p99s, and the speculation bill."""
    pods_per_node = max(float(pods_per_node), 1.0)
    shard = ShardForecast(
        bucket_s=bucket_s, model=model, alpha=alpha, season_len=season_len
    )
    # each speculative node: [ready_at, expires_at, slots_left]
    warm: List[List[float]] = []
    latencies: List[float] = []
    hits = 0
    total_pods = 0
    launched = 0
    expired = 0
    node_seconds = 0.0  # speculative life: launch -> claim/expiry

    if not series:
        return {
            "pods": 0, "warm_hits": 0, "warm_hit_rate": 0.0,
            "p99_with_pool_s": 0.0, "p99_without_pool_s": 0.0,
            "speculative_launches": 0, "speculative_expired": 0,
            "speculative_node_hours": 0.0, "speculative_cost_usd": 0.0,
        }

    t0 = series[0][0]
    t_end = series[-1][0]
    arrivals = list(series)
    ai = 0
    t = t0
    while t <= t_end + interval_s:
        # arrivals BEFORE this wave tick, in order (the worker's steal
        # runs on every round; waves only add capacity)
        while ai < len(arrivals) and arrivals[ai][0] <= t:
            at, count = arrivals[ai]
            ai += 1
            shard.observe(count, at)
            n = int(count)
            if n <= 0:
                continue
            total_pods += n
            # TTL-expire first, oldest first — the controller's name-sort
            # makes claiming deterministic too
            still: List[List[float]] = []
            for node in warm:
                if node[1] <= at and node[2] > 0:
                    expired += 1
                    node_seconds += node[1] - (node[0] - launch_to_ready_s)
                else:
                    still.append(node)
            warm = still
            # claim ready nodes for this tick's batch: a claimed node
            # serves up to its slot count from THIS batch, then leaves
            # the pool even partially filled — exactly the live steal
            # (the claim patch removes the warm marker, so a node claimed
            # by a small batch is spent capacity)
            pods_left = n
            for node in warm:
                if pods_left <= 0:
                    break
                if node[0] <= at and node[2] > 0:
                    take = min(int(node[2]), pods_left)
                    hits += take
                    pods_left -= take
                    latencies.extend([bind_latency_s] * take)
                    node_seconds += at - (node[0] - launch_to_ready_s)
                    node[2] = 0
            latencies.extend([launch_to_ready_s] * pods_left)
            warm = [x for x in warm if x[2] > 0]
        # the wave: size the pool off the upper band, like _wave does
        point, upper = shard.rate(t, band_sigma=band_sigma)
        want = int(math.ceil((upper * horizon_s) / pods_per_node))
        standing = len(warm)
        deficit = min(want, max_nodes) - standing
        for _ in range(max(deficit, 0)):
            warm.append([
                t + launch_to_ready_s, t + warm_pool_ttl, pods_per_node,
            ])
            launched += 1
        t += interval_s
    # drain: whatever is still standing at the end expires at its TTL
    for node in warm:
        if node[2] > 0:
            expired += 1
            node_seconds += node[1] - (node[0] - launch_to_ready_s)

    hours = node_seconds / 3600.0
    return {
        "pods": total_pods,
        "warm_hits": hits,
        "warm_hit_rate": (hits / total_pods) if total_pods else 0.0,
        "p99_with_pool_s": _p99(latencies),
        "p99_without_pool_s": launch_to_ready_s if total_pods else 0.0,
        "speculative_launches": launched,
        "speculative_expired": expired,
        "speculative_node_hours": round(hours, 4),
        "speculative_cost_usd": round(hours * node_price_per_h, 2),
    }


def whatif(
    decision_dir: str,
    provisioner: Optional[str] = None,
    **params: Any,
) -> Dict[str, Any]:
    """The library entry point: ring directory -> per-provisioner
    counterfactual panels. ``params`` are :func:`simulate` keywords;
    ``pods_per_node`` defaults to the ratio measured FROM the window
    itself (the live forecaster's EWMA does the same job online)."""
    records = load_records(decision_dir)
    series = load_series(decision_dir, provisioner=provisioner)
    if "pods_per_node" not in params:
        params["pods_per_node"] = measured_pods_per_node(records)
    out: Dict[str, Any] = {
        "decision_dir": decision_dir,
        "records": len(records),
        "pods_per_node": params["pods_per_node"],
        "params": {
            k: v for k, v in sorted(params.items()) if k != "pods_per_node"
        },
        "provisioners": {
            name: simulate(points, **params)
            for name, points in sorted(series.items())
        },
    }
    panels = out["provisioners"].values()
    pods = sum(p["pods"] for p in panels)
    hits = sum(p["warm_hits"] for p in panels)
    out["combined"] = {
        "pods": pods,
        "warm_hit_rate": (hits / pods) if pods else 0.0,
        "speculative_cost_usd": round(
            sum(p["speculative_cost_usd"] for p in panels), 2
        ),
    }
    return out


# -- CLI ---------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="whatif",
        description="replay a recorded decision window against a "
        "hypothetical warm-pool policy and print the predicted "
        "time-to-ready / cost panel",
    )
    ap.add_argument("--decision-dir", required=True,
                    help="decision audit ring directory")
    ap.add_argument("--provisioner", default=None,
                    help="limit to one provisioner (default: all)")
    ap.add_argument("--warm-pool-ttl", type=float, default=DEFAULT_TTL_S)
    ap.add_argument("--max-warm-nodes", type=int,
                    default=DEFAULT_MAX_WARM_NODES)
    ap.add_argument("--interval-s", type=float,
                    default=DEFAULT_WAVE_INTERVAL_S,
                    help="warm-pool wave interval")
    ap.add_argument("--launch-to-ready-s", type=float,
                    default=DEFAULT_LAUNCH_TO_READY_S,
                    help="cold launch-to-schedulable latency to model")
    ap.add_argument("--bind-latency-s", type=float,
                    default=DEFAULT_BIND_LATENCY_S,
                    help="warm-claim bind latency to model")
    ap.add_argument("--horizon-s", type=float,
                    default=DEFAULT_LAUNCH_TO_READY_S,
                    help="forecast horizon (live: measured ready p99)")
    ap.add_argument("--pods-per-node", type=float, default=None,
                    help="override the window-measured pods/node ratio")
    ap.add_argument("--ewma-alpha", type=float, default=0.3)
    ap.add_argument("--seasonal", action="store_true",
                    help="use the Holt-Winters seasonal model")
    ap.add_argument("--season-len", type=int, default=24)
    ap.add_argument("--band-sigma", type=float, default=DEFAULT_BAND_SIGMA)
    ap.add_argument("--node-price-per-h", type=float,
                    default=DEFAULT_NODE_PRICE_PER_H)
    ap.add_argument("--sweep-ttl", default="",
                    help="comma-separated TTLs to compare (overrides "
                    "--warm-pool-ttl)")
    ap.add_argument("--replay", action="store_true",
                    help="first re-solve the newest replayable record "
                    "bit-exact (tools.replay_decision)")
    args = ap.parse_args(argv)

    replay_verdict: Optional[Dict[str, Any]] = None
    if args.replay:
        from tools import replay_decision

        path = replay_decision.find_record(args.decision_dir)
        if path:
            try:
                replay_verdict = replay_decision.replay(
                    replay_decision.load_record(path), record_path=path
                )
            except (ValueError, RuntimeError, OSError) as e:
                replay_verdict = {"ok": None, "diff": str(e)}

    params: Dict[str, Any] = dict(
        max_nodes=args.max_warm_nodes,
        interval_s=args.interval_s,
        launch_to_ready_s=args.launch_to_ready_s,
        bind_latency_s=args.bind_latency_s,
        horizon_s=args.horizon_s,
        model=MODEL_HOLT_WINTERS if args.seasonal else MODEL_EWMA,
        alpha=args.ewma_alpha,
        season_len=args.season_len,
        band_sigma=args.band_sigma,
        node_price_per_h=args.node_price_per_h,
    )
    if args.pods_per_node is not None:
        params["pods_per_node"] = args.pods_per_node

    ttls = (
        [float(x) for x in args.sweep_ttl.split(",") if x.strip()]
        if args.sweep_ttl else [args.warm_pool_ttl]
    )
    runs = [
        whatif(args.decision_dir, provisioner=args.provisioner,
               warm_pool_ttl=ttl, **params)
        for ttl in ttls
    ]
    doc: Dict[str, Any] = runs[0] if len(runs) == 1 else {
        "sweep": [
            {"warm_pool_ttl": ttl, **run}
            for ttl, run in zip(ttls, runs)
        ]
    }
    if replay_verdict is not None:
        doc["replay"] = replay_verdict
    print(json.dumps(doc, indent=2, sort_keys=True))
    if not runs[0].get("records"):
        print("whatif: no decision records found", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
