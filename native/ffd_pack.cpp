// Native first-fit packer — the CPU fast path.
//
// Same contract and assignment-exact semantics as the JAX kernels
// (karpenter_tpu/solver/kernel.py pack / pallas_kernel.py): pods arrive
// FFD-sorted and encoded (signature ids, interned hostname ids, fixed-axis
// f32 request vectors); each pod lands on the FIRST open node whose joined
// signature accepts it, whose hostname state is compatible, and where some
// pareto-frontier row still fits the new running total — else it opens a
// node when capacity and the node-table cap allow.
//
// The reference's in-process packer is the Go FFD loop
// (pkg/controllers/provisioning/scheduling/scheduler.go:64-137); this is its
// native equivalent operating on the dense tensor encoding, used when no
// TPU backend is present (and as the sidecar-less fallback).
//
// Build: g++ -O3 -march=native -shared -fPIC -o libffd_pack.so ffd_pack.cpp
// ABI: plain C, called through ctypes (no pybind11 in this toolchain).

#include <cstdint>
#include <cstring>

extern "C" {

// Returns the number of opened nodes. Arrays are caller-allocated:
//   assignment[P] (out), node_sig[n_max] (out), node_host[n_max] (out),
//   node_req[n_max*R] (out, row-major).
int32_t ffd_pack(
    const uint8_t* pod_valid,        // [P]
    const int32_t* pod_open_sig,     // [P]
    const int32_t* pod_core,         // [P]
    const int32_t* pod_host,         // [P] (-1 = unconstrained)
    const uint8_t* pod_host_in_base, // [P]
    const int32_t* pod_open_host,    // [P]
    const float* pod_req,            // [P*R] row-major
    const int32_t* join_table,       // [S*C] row-major
    const float* frontiers,          // [S*F*R] row-major
    const float* daemon,             // [R]
    int32_t P, int32_t R, int32_t S, int32_t C, int32_t F,
    int32_t n_max,
    int32_t* assignment,             // out [P]
    int32_t* node_sig,               // out [n_max]
    int32_t* node_host,              // out [n_max]
    float* node_req                  // out [n_max*R]
) {
    for (int32_t n = 0; n < n_max; ++n) {
        node_sig[n] = -1;
        node_host[n] = -1;
    }
    std::memset(node_req, 0, sizeof(float) * (size_t)n_max * (size_t)R);

    // scratch: candidate running total for the fit test
    float new_req[64];  // R is small (fixed resource axes); guard below
    if (R > 64) return -1;

    int32_t count = 0;
    for (int32_t i = 0; i < P; ++i) {
        assignment[i] = -1;
        if (!pod_valid[i]) continue;
        const float* req = pod_req + (size_t)i * R;
        const int32_t core = pod_core[i];
        const int32_t host = pod_host[i];

        int32_t target = -1;
        int32_t joined_sig = -1;
        // first-fit over open nodes
        for (int32_t n = 0; n < count; ++n) {
            const int32_t sig = node_sig[n];
            if (sig < 0) continue;
            const int32_t j = join_table[(size_t)sig * C + core];
            if (j < 0) continue;
            // hostname join (kernel.py step semantics)
            const int32_t nh = node_host[n];
            const bool ok_host =
                (host < 0) || (nh == -1 && pod_host_in_base[i]) || (nh == host);
            if (!ok_host) continue;
            const float* total = node_req + (size_t)n * R;
            for (int32_t r = 0; r < R; ++r) new_req[r] = total[r] + req[r];
            // ∃ frontier row of the JOINED signature that fits
            bool fits = false;
            const float* fr = frontiers + (size_t)j * F * R;
            for (int32_t f = 0; f < F && !fits; ++f) {
                bool row_ok = true;
                const float* row = fr + (size_t)f * R;
                for (int32_t r = 0; r < R; ++r) {
                    if (new_req[r] > row[r]) { row_ok = false; break; }
                }
                fits = row_ok;
            }
            if (fits) { target = n; joined_sig = j; break; }
        }

        if (target >= 0) {
            float* total = node_req + (size_t)target * R;
            for (int32_t r = 0; r < R; ++r) total[r] += req[r];
            node_sig[target] = joined_sig;
            if (host >= 0) node_host[target] = host;
            assignment[i] = target;
            continue;
        }

        // open a new node when the daemon+pod total fits its signature's
        // frontier and the table has room
        if (count >= n_max) continue;
        const int32_t open_sig = pod_open_sig[i];
        const float* fr = frontiers + (size_t)open_sig * F * R;
        for (int32_t r = 0; r < R; ++r) new_req[r] = daemon[r] + req[r];
        bool open_fits = false;
        for (int32_t f = 0; f < F && !open_fits; ++f) {
            bool row_ok = true;
            const float* row = fr + (size_t)f * R;
            for (int32_t r = 0; r < R; ++r) {
                if (new_req[r] > row[r]) { row_ok = false; break; }
            }
            open_fits = row_ok;
        }
        if (!open_fits) continue;
        node_sig[count] = open_sig;
        node_host[count] = pod_open_host[i];
        float* total = node_req + (size_t)count * R;
        for (int32_t r = 0; r < R; ++r) total[r] = new_req[r];
        assignment[i] = count;
        ++count;
    }
    return count;
}

}  // extern "C"
