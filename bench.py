#!/usr/bin/env python
"""Headline benchmark: pods-scheduled/sec on the TPU batch solver.

Reproduces the reference's scheduler benchmark scenario
(``scheduling_benchmark_test.go``: 400 fake instance types × diverse pod mix)
against the TPU solve path, and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "pods/sec", "vs_baseline": N, ...}

Baseline: the reference enforces ≥250 pods/sec on batches >100 pods
(scheduling_benchmark_test.go:47,151-155); vs_baseline = value / 250.

Run: python bench.py [--pods N] [--iters K] [--grid]
"""

import argparse
import json
import math
import os
import random
import statistics
import sys
import time

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.testing import diverse_pods, make_provisioner

BASELINE_PODS_PER_SEC = 250.0  # reference's enforced CPU floor


class RttProbe:
    """Round-trip floor of the accelerator transport: a trivial dispatch +
    fetch, perturbed per sample so the tunneled backend can't dedupe.
    Under axon this is ~90-115ms of pure tunnel latency that a locally-
    attached chip does not pay; bench reports it so the solve latency can
    be judged against the BASELINE target (<100ms on an attached TPU v5e).

    Samples are taken INTERLEAVED with the benchmark iterations (VERDICT
    methodology fix): the tunnel's latency drifts tens of ms between
    minutes, so a floor measured once before the run can misstate the
    transport the solves actually paid — in either direction. The floor is
    the min over every sample in the run window."""

    def __init__(self):
        import jax
        import numpy as np

        self._x = np.zeros(8, np.float32)
        self._f = jax.jit(lambda a: a + 1)
        jax.device_get(self._f(self._x))  # compile
        self._i = 0
        self.samples = []

    def sample(self, n: int = 1) -> None:
        import jax

        for _ in range(n):
            self._i += 1
            t0 = time.perf_counter()
            jax.device_get(self._f(self._x + self._i * 1e-6))
            self.samples.append(time.perf_counter() - t0)

    @property
    def floor(self) -> float:
        return min(self.samples)


def measure_rtt_floor(samples: int = 5) -> float:
    probe = RttProbe()
    probe.sample(samples)
    return probe.floor


def onchip_parity_check(n_pods: int = 500) -> str:
    """Assignment-exact gates run on the REAL device as part of every bench
    (VERDICT r2 weak #4 / r3 ask #5: CI is CPU-only, so a Mosaic regression
    would otherwise ship with only bench THROUGHPUT noticing). Covers every
    production route: the v1 single solve (pack_best), the fused
    single-dispatch path, the sharded v1 multi-solve, and the v2
    (matmul-gather) kernel on an F>1 shape past the v1 unroll budget.
    Returns a comma-separated list of the routes checked, or raises."""
    import numpy as np

    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.solver import kernel as K
    from karpenter_tpu.solver.pallas_kernel import pack_best, pallas_available

    if not pallas_available():
        return "skipped (no accelerator)"

    def assert_equal(route, got, ref):
        for name in K.PackResult._fields:
            a = np.asarray(getattr(got, name))
            b = np.asarray(getattr(ref, name))
            if not np.array_equal(a, b):
                raise AssertionError(f"on-chip parity FAILED on {route}:{name}")

    catalog = sorted(instance_types(50), key=lambda it: it.effective_price())
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(9)))
    cc = c.clone()
    plan = Topology(Cluster(), rng=random.Random(1)).inject_plan(cc, pods)
    batch = enc.encode(cc, catalog, pods, daemon_overhead(Cluster(), cc), plan=plan)
    n_max = 256
    checked = []

    # 1. v1 single solve (pack_best routes to the Pallas kernel on TPU)
    ref = K.pack(*batch.pack_args(), n_max=n_max)
    assert_equal("v1", pack_best(*batch.pack_args(), n_max=n_max), ref)
    checked.append("v1")

    # 2. fused single-dispatch path (i16 upload + device-resident
    # invariants + on-device typemask) vs the same reference
    import jax

    from karpenter_tpu.solver import fused

    if fused.ids_fit(batch):
        inv = fused.DeviceInvariants()
        join_d, front_d, daemon_d, mask_d, usable_d = inv.get(batch)
        pod_tab, open_by_core, bhh = fused.pack_pod_table(batch)
        uniq = fused.pad_uniq_req(batch.uniq_req)
        buf = jax.device_get(fused.fused_solve(
            pod_tab, open_by_core, bhh, uniq,
            join_d, front_d, daemon_d, mask_d, usable_d,
            n_max=n_max, kernel="pallas",
        ))
        fres, ftypemask = fused.split_fused(
            buf, len(batch.pod_valid), n_max, batch.usable.shape[1],
            batch.usable.shape[0],
        )
        assert_equal("fused", fres, ref)
        # the on-device typemask must match decode's host formula
        node_req = np.asarray(ref.node_req)
        node_sig = np.asarray(ref.node_sig)
        fits = np.all(batch.usable[None, :, :] >= node_req[:, None, :], axis=-1)
        mask_arr = batch.type_mask_matrix()[np.maximum(node_sig, 0)]
        expect = fits & mask_arr & (node_sig >= 0)[:, None]
        if not np.array_equal(ftypemask, expect):
            raise AssertionError("on-chip parity FAILED on fused:typemask")
        checked.append("fused")

    # 3. sharded v1 multi-solve — B sized to the mesh's data axis so the
    # gate works on any rig (1 chip here, but a v4-8 has 4+)
    from karpenter_tpu.parallel.sharding import make_solver_mesh, sharded_multi_solve

    args = batch.pack_args()
    mesh = make_solver_mesh()
    n_b = 2 * mesh.shape["data"]
    stacked = tuple(np.stack([np.asarray(a)] * n_b) for a in args)
    mres, _, mroute = sharded_multi_solve(
        mesh, stacked, np.stack([batch.type_mask_matrix()] * n_b), batch.usable,
        np.array([it.effective_price() for it in catalog], np.float32),
        n_max=n_max,
    )
    route = mroute.get("route")
    if route != "pallas-v1-multi":
        raise AssertionError(f"multi gate took route {route}, not pallas-v1-multi")
    for b in range(n_b):
        got = K.PackResult(*(np.asarray(getattr(mres, f))[b] for f in K.PackResult._fields))
        assert_equal("v1-multi", got, ref)
    checked.append("v1-multi")

    # 3b. fused single-dispatch through the v2 kernel (F>1 tradeoff
    # catalog past the v1 unroll budget — the constraint-diverse route)
    from karpenter_tpu.cloudprovider.fake import instance_types_tradeoff
    from karpenter_tpu.solver.backend import TpuScheduler
    from karpenter_tpu.testing import make_pod

    tcat = sorted(instance_types_tradeoff(16), key=lambda it: it.effective_price())
    tprov = make_provisioner(solver="tpu")
    tc = tprov.spec.constraints
    tc.requirements = tc.requirements.merge(catalog_requirements(tcat))
    rng9 = random.Random(9)
    tpods = sort_pods_ffd([
        make_pod(
            requests={"cpu": f"{rng9.choice([0.25, 0.5, 1])}"},
            node_selector={"team": f"t{i % 64}"},
        )
        for i in range(512)
    ])
    tcc = tc.clone()
    tplan = Topology(Cluster(), rng=random.Random(1)).inject_plan(tcc, tpods)
    tbatch = enc.encode(tcc, tcat, tpods, daemon_overhead(Cluster(), tcc), plan=tplan)
    tsched = TpuScheduler(Cluster())
    route = tsched._fused_route(tbatch, 256)
    if route != "v2":
        raise AssertionError(f"tradeoff batch routed {route}, not fused-v2")
    fres2, _ = tsched._pack_fused_begin(tbatch, 256, "v2")()
    ref2 = K.pack(*tbatch.pack_args(), n_max=256)
    assert_equal("fused-v2", fres2, ref2)
    checked.append("fused-v2")

    # 4. v2 (matmul-gather) kernel on an F>1 shape past the v1 unroll
    # budget — the route constraint-diverse batches take in production
    from karpenter_tpu.solver import pallas_kernel as pk
    from karpenter_tpu.solver.pallas_kernel_v2 import pack_pallas_v2, v2_vmem_ok

    rng = np.random.default_rng(7)
    P2, S2, C2, F2, R2 = 256, 256, 8, 8, 4
    assert S2 * F2 > pk.PALLAS_UNROLL_BUDGET and v2_vmem_ok(S2, 128, C2, F2 * R2)
    synth = (
        np.ones(P2, bool),
        rng.integers(0, S2, P2).astype(np.int32),
        rng.integers(0, C2, P2).astype(np.int32),
        np.full(P2, -1, np.int32),
        np.ones(P2, bool),
        np.full(P2, -1, np.int32),
        rng.uniform(0.1, 1.0, (P2, R2)).astype(np.float32),
        rng.integers(-1, S2, (S2, C2)).astype(np.int32),
        rng.uniform(2.0, 16.0, (S2, F2, R2)).astype(np.float32),
        np.zeros(R2, np.float32),
    )
    assert_equal(
        "v2", pack_pallas_v2(*synth, n_max=128), K.pack(*synth, n_max=128)
    )
    checked.append("v2")
    return ",".join(checked)


def _p99(times):
    return sorted(times)[min(len(times) - 1, max(math.ceil(0.99 * len(times)) - 1, 0))]


def _p90(times):
    return sorted(times)[min(len(times) - 1, max(math.ceil(0.9 * len(times)) - 1, 0))]


def bench_once(
    n_pods: int,
    iters: int,
    solver: str = "tpu",
    breakdown: bool = False,
    packer: str = "auto",
    seed: int = 42,
    wire_telemetry: bool = False,
    record_decisions: str = "",
    delta=None,
):
    """One solve scenario, ``iters`` measured iterations.

    ``wire_telemetry=True`` (VERDICT r4 ask #3) pairs EVERY iteration with
    its own adjacent transport sample, so each device-backed solve subtracts
    its OWN wire time (``*_minus_rtt_each_s``) instead of a window floor or
    median — ending the floor-vs-p50 adjustment ambiguity. Iterations whose
    profile says the pack never crossed the wire (the router chose the
    native packer) are never RTT-adjusted."""
    import os

    from karpenter_tpu.scheduling.oracle import classify_drops

    catalog = instance_types(400)
    provisioner = make_provisioner(solver=solver)
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = diverse_pods(n_pods, random.Random(seed))
    cluster = Cluster()
    # delta=None defers to the KARPENTER_SOLVER_DELTA env twin; the
    # headline legs pass True so the resident-encoding steady state
    # (docs/delta-encoding.md) is what gets measured
    scheduler = Scheduler(cluster, rng=random.Random(1), solver_delta=delta)

    prev_packer = os.environ.get("KARPENTER_PACKER")
    os.environ["KARPENTER_PACKER"] = packer
    try:
        # warmup (compile; under auto a second pass clears the router's
        # two-candidate cold start before any measured iteration)
        nodes = scheduler.solve(provisioner, catalog, pods)
        assert nodes, "benchmark scenario must schedule"
        if packer == "auto":
            scheduler.solve(provisioner, catalog, pods)
        # the runtime's post-warmup GC policy (main.py does the same):
        # collector passes over the warm heap were the host-latency tail
        from karpenter_tpu.utils.gcpolicy import freeze_after_warmup

        freeze_after_warmup()
        # decision-observability overhead leg (docs/decisions.md): record
        # a decision per measured solve into an on-disk ring, exactly as a
        # provisioning round would, and self-account the HOT-PATH cost
        # (attribution + record build + write enqueue; persistence is
        # async by design) — explain_overhead_pct, bar < 1. One warmup
        # record primes the per-signature verdict memos the same way the
        # warmup solves primed XLA: steady state is what's measured.
        decision_log = None
        explain_total = 0.0
        if record_decisions:
            from karpenter_tpu import obs
            from karpenter_tpu.obs import decisions as _dec

            _dec.set_enabled(True)
            decision_log = obs.configure_decisions(record_decisions)
            warm_nodes = scheduler.solve(provisioner, catalog, pods)
            decision_log.record_round(
                "bench", pods, warm_nodes,
                context=scheduler.last_decision_context(), trace_id="",
            )
        # steady-state catalog residency window: the warmup's one
        # unavoidable upload must not dilute the reported hit rate
        from karpenter_tpu.solver import session_stats

        session_stats.reset()
        # fresh trace window: the measured iterations' span trees line up
        # 1:1 with the iteration index (one solver.solve root per solve)
        from karpenter_tpu import obs

        obs.exporter().clear()
        # the online SLO engine over the same measured window: the bench's
        # offline percentile cross-checks the engine's online one (the 5%
        # acceptance bar is the histogram bucket scheme's error bound)
        slo_eng = obs.configure_slo() if obs.enabled() else None

        probe = RttProbe() if breakdown else None
        if probe:
            probe.sample(3)
        times = []
        iter_rtts = []  # wire_telemetry: each iteration's OWN wire sample
        profiles = []
        for it in range(iters):
            t0 = time.perf_counter()
            nodes = scheduler.solve(provisioner, catalog, pods)
            times.append(time.perf_counter() - t0)
            prof = getattr(scheduler._tpu, "last_profile", None)
            profiles.append(dict(prof) if prof else {})
            if decision_log is not None:
                te = time.perf_counter()
                decision_log.record_round(
                    "bench", pods, nodes,
                    context=scheduler.last_decision_context(),
                    trace_id="",
                )
                explain_total += time.perf_counter() - te
            if probe:
                # pair a wire sample only with iterations that actually
                # crossed the wire: a native-backed (routed) iteration has
                # nothing to subtract, and a ~100 ms probe per iteration
                # would be pure bench-time churn
                wired = wire_telemetry and (
                    profiles[-1].get("packer_backend", "device") == "device"
                )
                if wired:
                    probe.sample(1)
                    iter_rtts.append(probe.samples[-1])
                else:
                    if wire_telemetry:
                        iter_rtts.append(None)
                    # interleaved transport sampling, identical density in
                    # every mode: the floor must reflect the tunnel
                    # conditions of THIS run window, not a one-off
                    # measurement before it
                    if it % 10 == 9 or it == iters - 1:
                        probe.sample(2)
    finally:
        if prev_packer is None:
            os.environ.pop("KARPENTER_PACKER", None)
        else:
            os.environ["KARPENTER_PACKER"] = prev_packer
    scheduled = sum(len(n.pods) for n in nodes)
    best = min(times)
    # every drop must be oracle-certified unsatisfiable (scheduling/oracle.py)
    verdict = classify_drops(
        cluster, c, catalog, pods, [p for n in nodes for p in n.pods]
    )
    out = {
        "pods_per_sec": scheduled / best,
        "mean_s": statistics.mean(times),
        "p99_s": _p99(times),
        "nodes": len(nodes),
        "scheduled": scheduled,
        "unschedulable_expected": verdict["dropped"] - len(verdict["unexplained"]),
        "unexplained": len(verdict["unexplained"]),
    }
    if profiles:
        backends = [p.get("packer_backend") for p in profiles]
        if any(backends):
            out["packer_backend"] = max(set(b for b in backends if b),
                                        key=backends.count)
        # resident delta attribution (docs/delta-encoding.md): host share
        # is the per-solve HOST-side cost — sort + inject + encode +
        # decode in whichever variant (delta or full-rebuild) each stage
        # took; delta_hit_rate is the fraction of measured iterations any
        # stage served from resident state. The headline bar is
        # host_share_ms < 10 at the 10k-pod leg in steady state.
        host_keys = ("sort_s", "sort_delta_s", "inject_s", "inject_delta_s",
                     "encode_s", "encode_delta_s", "decode_s",
                     "decode_delta_s")
        shares = [
            sum(p.get(k, 0.0) for k in host_keys) for p in profiles if p
        ]
        if shares:
            out["host_share_ms"] = round(statistics.median(shares) * 1e3, 2)
            out["delta_hit_rate"] = round(
                sum(
                    1 for p in profiles
                    if any(k.endswith("_delta_s") for k in p)
                ) / len(profiles), 4,
            )
    if decision_log is not None:
        solve_total = sum(times)
        out["explain_overhead_pct"] = round(
            explain_total / max(solve_total, 1e-9) * 100, 4
        )
        out["explain_rounds"] = iters
        decision_log.flush(10.0)
        out["decision_records_written"] = decision_log.records_written
    sess = session_stats.snapshot()
    if sess["hit_rate"] is not None:
        # steady-state Pack payloads exclude catalog bytes iff this ≈ 1.0
        out["session_catalog_hit_rate"] = round(sess["hit_rate"], 4)
    if slo_eng is not None:
        # per-objective verdicts from the ONLINE engine — the same code
        # path production serves at /debug/slo, fed by this run's spans
        objectives = slo_eng.snapshot()["objectives"]
        sp = objectives.get("solve_p99")
        if sp is not None and sp["value"] is not None:
            out["slo_solve_p99_s"] = round(sp["value"], 4)
            out["slo_solve_p99_ok"] = bool(sp["ok"])
            # online (log-linear sketch) vs offline (exact sort) agreement
            out["slo_online_offline_delta_pct"] = round(
                abs(sp["value"] - out["p99_s"]) / max(out["p99_s"], 1e-9) * 100,
                2,
            )
        out["slo_burn_rates"] = {
            name: o["burn_rate"]
            for name, o in objectives.items()
            if o["events"]["slow"]
        }
    if obs.enabled():
        # self-time attribution down the worst iteration's span tree — the
        # trace-backed answer to "where did the tail iteration's time go"
        trees = obs.exporter().trees()
        if len(trees) == len(times):
            worst_tree = trees[max(range(len(times)), key=times.__getitem__)]
            out["trace_critical_path_ms"] = obs.critical_path(worst_tree)
    if breakdown and any(profiles):
        rtt = probe.floor
        rtt_p50 = statistics.median(probe.samples)
        out["rtt_samples"] = len(probe.samples)
        out["rtt_p50_ms"] = round(rtt_p50 * 1e3, 1)
        dispatches = max(int(p.get("pack_dispatches", 1)) for p in profiles)
        stages = {
            k: round(statistics.median(p[k] for p in profiles if k in p) * 1e3, 1)
            for k in profiles[0]
            if k.endswith("_s")
        }
        out["breakdown_ms"] = stages
        out["pack_dispatches"] = dispatches
        out["transport_rtt_floor_ms"] = round(rtt * 1e3, 1)
        # per-stage trace of the WORST iteration (the tail diagnosis the
        # aggregate medians hide — VERDICT r4 ask #3)
        worst = max(range(len(times)), key=times.__getitem__)
        wp = profiles[worst]
        out["worst_iter"] = {
            "iter": worst,
            "total_ms": round(times[worst] * 1e3, 1),
            "backend": wp.get("packer_backend"),
            "stages_ms": {k: round(v * 1e3, 1) for k, v in wp.items()
                          if isinstance(v, float) and k.endswith("_s")},
            **({"own_rtt_ms": round(iter_rtts[worst] * 1e3, 1)}
               if worst < len(iter_rtts) and iter_rtts[worst] is not None
               else {}),
        }
        # wire adjustment applies ONLY to iterations that crossed the wire
        wire_iters = [
            i for i, p in enumerate(profiles)
            if p.get("packer_backend", "device") == "device"
        ]
        out["wire_in_path"] = bool(wire_iters)
        if wire_iters:
            wt = [times[i] for i in wire_iters]
            disp = [int(profiles[i].get("pack_dispatches", 1)) for i in wire_iters]
            # what an attached chip would see: the tunnel RTT is pure
            # transport, paid once per kernel dispatch (saturation retries
            # pay it again)
            adj = rtt * dispatches
            out["p99_minus_rtt_s"] = round(max(_p99(wt) - adj, 0.0), 4)
            # p99 over a dozen samples is max(): on a timeshared box a
            # single CPU-contention spike lands there. p90 is the
            # noise-robust tail.
            out["p90_minus_rtt_s"] = round(max(_p90(wt) - adj, 0.0), 4)
            out["mean_minus_rtt_s"] = round(
                max(statistics.mean(wt) - adj, 0.0), 4
            )
            out["mean_minus_rtt_p50_s"] = round(
                max(statistics.mean(wt) - rtt_p50 * dispatches, 0.0), 4
            )
            out["p90_minus_rtt_p50_s"] = round(
                max(_p90(wt) - rtt_p50 * dispatches, 0.0), 4
            )
            if wire_telemetry and iter_rtts:
                # each sample minus its OWN adjacent wire measurement — the
                # canonical adjustment from r5 on (no floor/median choice)
                each = [
                    max(times[i] - iter_rtts[i] * d, 0.0)
                    for i, d in zip(wire_iters, disp)
                    if i < len(iter_rtts) and iter_rtts[i] is not None
                ]
                if each:
                    out["rtt_per_solve_samples"] = len(each)
                    out["p99_minus_rtt_each_s"] = round(_p99(each), 4)
                    out["p90_minus_rtt_each_s"] = round(_p90(each), 4)
                    out["mean_minus_rtt_each_s"] = round(statistics.mean(each), 4)
    return out


def bench_pipelined(n_pods: int, streams: int, iters: int, packer: str = "auto"):
    """Continuous-load throughput: N independent solver streams (one per
    provisioner worker, the production shape) solving back-to-back. Device
    fetches release the GIL, so the tunnel RTT of one stream overlaps other
    streams' host work — throughput is bounded by host encode, not by
    per-solve round-trip latency. Distinct pod mixes per stream keep the
    tunneled backend from deduping byte-identical dispatches."""
    import os
    import threading

    catalog = instance_types(400)
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    from karpenter_tpu.scheduling.oracle import classify_drops

    streams_state = []
    for s in range(streams):
        pods = diverse_pods(n_pods, random.Random(1000 + s))
        sched = Scheduler(Cluster(), rng=random.Random(s))
        streams_state.append((sched, pods))

    prev_packer = os.environ.get("KARPENTER_PACKER")
    os.environ["KARPENTER_PACKER"] = packer
    try:
        # warmup (compile + statics); every stream's drops are
        # oracle-certified once here — iterations re-solve the same pods
        # (VERDICT r4 #7: no uncertified "Failed to schedule" line ships)
        scheduled_per_stream = []
        unexplained = expected_drops = 0
        for sched, pods in streams_state:
            nodes = sched.solve(provisioner, catalog, pods)
            scheduled_per_stream.append(sum(len(n.pods) for n in nodes))
            verdict = classify_drops(
                sched.cluster, c, catalog, pods,
                [p for n in nodes for p in n.pods],
            )
            unexplained += len(verdict["unexplained"])
            expected_drops += verdict["dropped"] - len(verdict["unexplained"])

        # steady-state catalog-residency window (see bench_once)
        from karpenter_tpu.solver import session_stats

        session_stats.reset()
        # fresh trace window for the overlap invariant below
        from karpenter_tpu import obs

        obs.exporter().clear()

        start_gate = threading.Barrier(streams + 1)
        done = []

        def run_stream(idx):
            sched, pods = streams_state[idx]
            start_gate.wait()
            for _ in range(iters):
                sched.solve(provisioner, catalog, pods)

        threads = [
            threading.Thread(target=run_stream, args=(i,), daemon=True)
            for i in range(streams)
        ]
        for t in threads:
            t.start()
        # controller-CPU accounting (VERDICT r4 ask #2): rusage covers every
        # thread of THIS process — exactly the controller's CPU bill. A
        # device-backed solve burns host CPU only on encode/decode/transport
        # while the pack itself runs on the chip; the native pack adds its
        # own host CPU. The delta per solve IS the measured offload.
        import resource

        start_gate.wait()
        ru0 = resource.getrusage(resource.RUSAGE_SELF)
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        ru1 = resource.getrusage(resource.RUSAGE_SELF)

        # the PR-4 double-buffer claim as a CHECKED invariant. The
        # cross-stream pair count is reported for color, but per-stream
        # schedulers cannot detect the regression the claim is about
        # (their solve locks never contend), so the assertion runs on a
        # dedicated probe: ONE scheduler, two concurrent solvers. encode
        # runs under the solve lock and the fetch off it — B's encode can
        # only overlap A's in-flight fetch if solve() really releases the
        # lock before fetching. Asserted only where it is meaningful: not
        # on the native-forced leg (its fetch IS the synchronous pack),
        # and only when the probe's fetches are long enough to overlap.
        overlap_pairs = shared_pairs = None
        if obs.enabled():
            overlap_pairs = obs.overlapping_pairs(obs.exporter().trees())
            sched0, pods_a = streams_state[0]
            pods_b = streams_state[1][1] if streams > 1 else pods_a
            # warm pods_b's shape on the SHARED scheduler first: a compile
            # landing inside the probe runs under the solve lock and
            # legitimately serializes the threads — which would read as a
            # lock regression that isn't there
            sched0.solve(provisioner, catalog, pods_b)
            obs.exporter().clear()
            gate2 = threading.Barrier(2)

            def shared_run(pods_s):
                gate2.wait()
                for _ in range(3):
                    sched0.solve(provisioner, catalog, pods_s)

            threads2 = [
                threading.Thread(target=shared_run, args=(p,), daemon=True)
                for p in (pods_a, pods_b)
            ]
            for t in threads2:
                t.start()
            for t in threads2:
                t.join()
            strees = obs.exporter().trees()
            shared_pairs = obs.overlapping_pairs(strees)
            # MEDIAN fetch gates the assert: on a CPU rig fetches are ~0
            # and nothing can overlap them (a single >1ms outlier is just
            # a compile landing in the probe, during which the other
            # thread legitimately ran to completion alone); a device/wire
            # rig has every steady-state fetch in the milliseconds, and
            # there zero overlap really does mean the lock is held
            # through the fetch
            fetches = [
                s["duration_ms"]
                for t in strees
                for s in obs.spans_named(t, "solve.pack_fetch")
            ]
            if (
                packer != "native"
                and len(fetches) >= 4
                and statistics.median(fetches) >= 1.0
            ):
                assert shared_pairs > 0, (
                    "shared-scheduler probe shows NO encode/fetch overlap — "
                    "the solve lock is held through the fetch again (the "
                    "double-buffered pipeline has regressed to serial)"
                )
    finally:
        if prev_packer is None:
            os.environ.pop("KARPENTER_PACKER", None)
        else:
            os.environ["KARPENTER_PACKER"] = prev_packer
    total_scheduled = sum(scheduled_per_stream) * iters
    cpu_s = (ru1.ru_utime - ru0.ru_utime) + (ru1.ru_stime - ru0.ru_stime)
    n_solves = streams * iters
    out = {
        "streams": streams,
        "iters": iters,
        "scheduled_total": total_scheduled,
        "wall_s": round(wall, 4),
        "pods_per_sec": round(total_scheduled / wall, 1),
        "controller_cpu_seconds_per_solve": round(cpu_s / n_solves, 5),
        "controller_cpu_utilization": round(cpu_s / wall, 3),
        "unschedulable_expected": expected_drops,
        "unexplained": unexplained,
    }
    sess = session_stats.snapshot()
    if sess["hit_rate"] is not None:
        out["session_catalog_hit_rate"] = round(sess["hit_rate"], 4)
    if overlap_pairs is not None:
        out["trace_overlap_pairs"] = overlap_pairs
        out["trace_shared_sched_overlap_pairs"] = shared_pairs
    return out


def bench_stitched(n_pods: int, iters: int):
    """Stitched-attribution leg (docs/telemetry.md): solves through a LIVE
    gRPC sidecar, then re-joins the sidecar's real ``sidecar.pack`` trees
    into their controller ``solver.wire`` parents by the traceparent the v3
    wire carries — the fleet-wide critical path, with the wire's share of
    the worst solve split out (``wire_share_pct``). This is the measured
    attribution ROADMAP item 2 (streaming transport) starts from."""
    import socket

    try:
        import grpc  # noqa: F401
    except Exception as e:  # pragma: no cover - grpc is baked into CI
        raise RuntimeError(f"grpc unavailable: {e}")
    from karpenter_tpu import obs
    from karpenter_tpu.obs import collector as obs_collector
    from karpenter_tpu.solver.service import serve

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    address = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    server = serve(address)
    prev_packer = os.environ.get("KARPENTER_PACKER")
    # pin the device path: the cost router would route these batches to
    # native and the wire would never be exercised (the fleet-storm
    # precedent)
    os.environ["KARPENTER_PACKER"] = "device"
    try:
        catalog = instance_types(400)
        provisioner = make_provisioner(solver="tpu")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = diverse_pods(n_pods, random.Random(7))
        scheduler = Scheduler(
            Cluster(), rng=random.Random(1), solver_service_address=address
        )
        scheduler.solve(provisioner, catalog, pods)  # warm: compile + open
        obs.exporter().clear()
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            scheduler.solve(provisioner, catalog, pods)
            times.append(time.perf_counter() - t0)
        roots, joins = obs_collector.stitch(obs.exporter().trees())
        solves = [r for r in roots if r.get("name") == "solver.solve"]
        stitched = [
            r for r in solves
            if any(s.get("stitched") for s in obs_collector._walk(r))
        ]
        out = {
            "iters": iters,
            "p99_s": _p99(times),
            "solve_trees": len(solves),
            "stitched_joins": joins,
        }
        pool = stitched or solves
        if pool:
            worst = max(pool, key=lambda r: float(r.get("duration_ms") or 0.0))
            legs = obs.critical_path(worst)
            out["fleet_critical_path_ms"] = round(
                sum(leg["self_ms"] for leg in legs), 3
            )
            out["fleet_critical_path"] = legs
            attr = obs_collector.wire_attribution(worst)
            if attr is not None:
                out["wire_attribution"] = attr
                if attr.get("wire_share_pct") is not None:
                    out["wire_share_pct"] = attr["wire_share_pct"]
        return out
    finally:
        if prev_packer is None:
            os.environ.pop("KARPENTER_PACKER", None)
        else:
            os.environ["KARPENTER_PACKER"] = prev_packer
        server.stop(grace=0)


def bench_streamed(n_pods: int, iters: int, coalesce_threads: int = 2):
    """Streamed-transport leg (docs/solver-transport.md § Streaming).

    Against a REAL sidecar subprocess (separate interpreter — an
    in-process server would share the client's GIL and hide exactly the
    overlap the stream exists to exploit), in the SAME run:

    - ``transport_rtt_floor_ms``: the per-solve floor of the unary RPC
      path, measured with 0-deadline probe frames the sidecar sheds
      before dispatch — a round trip of pure transport + parse, the wire
      analog of ``RttProbe``'s trivial ``a+1`` dispatch;
    - ``streamed_rtt_floor_ms``: the same probe over the persistent
      multiplexed stream at credit-window pipeline depth — the
      production shape of the streamed transport (solves multiplex; the
      serial number rides along as ``streamed_rtt_serial_ms``). The
      acceptance bar is ≤ 50% of the unary floor;
    - ``streamed_pods_per_sec`` / ``unary_pods_per_sec``: full scheduler
      solves over each transport;
    - ``streamed_shm``: the zero-copy sub-leg (the arena file is shared
      host-to-host with the subprocess — real colocation) whose
      ``wire_ser_ms``/``wire_deser_ms`` against the unary leg's prove
      the serialize-skip delta;
    - ``stream_coalesced_dispatch_rate``: fraction of streamed solves
      that shared a coalesced device dispatch during the concurrent
      phase, against a second sidecar pinned to the scan (device-route)
      kernel — scraped from ITS /metrics, the production surface.
    """
    import statistics as stats
    import tempfile
    import threading

    import numpy as np

    from karpenter_tpu.solver.service import RemoteSolver, pack_arrays
    from karpenter_tpu.solver.service import N_POD_ARRAYS, _key_array

    shm_dir = tempfile.mkdtemp(prefix="karpenter-shm-")
    prev_packer = os.environ.get("KARPENTER_PACKER")
    os.environ["KARPENTER_PACKER"] = "device"
    sidecar = coalesce_sidecar = None
    try:
        address, health_port, sidecar = _spawn_sidecar(shm_dir=shm_dir)
        catalog = instance_types(400)
        provisioner = make_provisioner(solver="tpu")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = diverse_pods(n_pods, random.Random(7))
        out = {"pods": n_pods, "iters": iters}

        # -- transport floors (0-deadline shed probes, both paths) --------
        from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import encode as enc
        from karpenter_tpu.solver.service import catalog_session_key
        from karpenter_tpu.testing import make_pod

        small_cat = instance_types(4)
        sc = make_provisioner(solver="tpu").spec.constraints
        sc.requirements = sc.requirements.merge(catalog_requirements(small_cat))
        small_pods = sort_pods_ffd(
            [make_pod(requests={"cpu": "0.1"}) for _ in range(4)]
        )
        cl = Cluster()
        Topology(cl).inject(sc, small_pods)
        sb = enc.encode(sc, small_cat, small_pods, daemon_overhead(cl, sc))
        sargs = [np.asarray(a) for a in sb.pack_args()]
        probe = RemoteSolver(address, timeout=30.0, stream=True)
        probe.pack(*sargs, n_max=8)  # open session + establish stream
        deadline = time.time() + 15
        while time.time() < deadline and not (
            probe._stream is not None and probe._stream.up
        ):
            time.sleep(0.02)
        skey = catalog_session_key(*sargs[N_POD_ARRAYS:])
        # record=0 keeps the probes out of the hit-rate stats; the junk
        # pod arrays prove the shed really happens before dispatch (they
        # would crash a solve — the overload storm's deadline-probe trick)
        shed_frame = pack_arrays(
            [np.zeros(4, np.int32), np.asarray([8, 0], np.int32)]
            + [np.zeros(4, np.float32)] * N_POD_ARRAYS
            + [np.asarray([0.0], np.float32)]
        )
        solve_frame = pack_arrays(
            [_key_array(skey), np.asarray([8, 0], np.int32)]
            + sargs[:N_POD_ARRAYS]
        )
        # Both floors use the SAME estimator — the best average over
        # windows of `chunk` consecutive solves — so neither side gets
        # the min-of-single-samples lottery the other doesn't. The unary
        # window is serial future-calls (pack_begin's one-in-flight
        # production shape); the streamed window runs at credit-window
        # pipeline depth (the multiplexed transport's production shape).
        samples, chunk = 200, 25
        unary_ts, stream_ts = [], []
        unary_solve_ts, stream_solve_ts = [], []
        for f in (shed_frame, solve_frame):  # warm both paths
            probe._call(f, timeout=30.0)
            probe._stream.solve(f).result(timeout=30.0)
        for _ in range(samples):
            # production shape on both sides: the unary path dispatches a
            # gRPC future per solve (pack_begin does exactly this)
            t0 = time.perf_counter()
            probe._call.future(shed_frame, timeout=30.0).result()
            unary_ts.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            probe._stream.solve(shed_frame).result(timeout=30.0)
            stream_ts.append(time.perf_counter() - t0)
        for _ in range(20):  # secondary: a real resident-session solve
            t0 = time.perf_counter()
            probe._call.future(solve_frame, timeout=30.0).result()
            unary_solve_ts.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            probe._stream.solve(solve_frame).result(timeout=30.0)
            stream_solve_ts.append(time.perf_counter() - t0)
        depth, piped = 8, 400
        window_ts = []
        inflight = [probe._stream.solve(shed_frame) for _ in range(depth)]
        t0 = time.perf_counter()
        for i in range(piped):
            inflight.pop(0).result(timeout=30.0)
            if i + depth < piped:
                inflight.append(probe._stream.solve(shed_frame))
            if (i + 1) % chunk == 0:
                window_ts.append((time.perf_counter() - t0) / chunk)
                t0 = time.perf_counter()
        while inflight:
            inflight.pop(0).result(timeout=30.0)

        def windowed_floor(ts):
            windows = [
                sum(ts[i:i + chunk]) / chunk
                for i in range(0, len(ts) - chunk + 1, chunk)
            ]
            return min(windows)

        out["rtt_samples"] = samples
        out["transport_rtt_floor_ms"] = round(windowed_floor(unary_ts) * 1e3, 3)
        out["transport_rtt_serial_min_ms"] = round(min(unary_ts) * 1e3, 3)
        out["transport_rtt_p50_ms"] = round(stats.median(unary_ts) * 1e3, 3)
        out["streamed_rtt_floor_ms"] = round(min(window_ts) * 1e3, 3)
        out["streamed_rtt_serial_ms"] = round(min(stream_ts) * 1e3, 3)
        out["streamed_rtt_p50_ms"] = round(stats.median(stream_ts) * 1e3, 3)
        out["streamed_vs_unary_floor"] = round(
            min(window_ts) / max(windowed_floor(unary_ts), 1e-9), 3
        )
        out["unary_solve_rtt_floor_ms"] = round(min(unary_solve_ts) * 1e3, 3)
        out["streamed_solve_rtt_floor_ms"] = round(
            min(stream_solve_ts) * 1e3, 3
        )
        probe.close()

        # -- full scheduler solves over each transport --------------------
        def run_leg(stream: bool, shm: str = "", delta: bool = False):
            sched = Scheduler(
                Cluster(), rng=random.Random(1),
                solver_service_address=address,
                solver_stream=stream, solver_shm_dir=shm,
                solver_delta=delta,
            )
            sched.solve(provisioner, catalog, pods)  # warm + open + establish
            sched.solve(provisioner, catalog, pods)
            times, profiles = [], []
            for _ in range(iters):
                t0 = time.perf_counter()
                nodes = sched.solve(provisioner, catalog, pods)
                times.append(time.perf_counter() - t0)
                prof = getattr(sched._tpu, "last_profile", None)
                profiles.append(dict(prof) if prof else {})
            scheduled = sum(len(n.pods) for n in nodes)
            med = lambda k: round(  # noqa: E731
                stats.median(p.get(k, 0.0) for p in profiles) * 1e3, 3
            )
            host_keys = ("sort_s", "sort_delta_s", "inject_s",
                         "inject_delta_s", "encode_s", "encode_delta_s",
                         "decode_s", "decode_delta_s")
            return {
                "pods_per_sec": round(scheduled / min(times), 1),
                "p99_s": round(_p99(times), 4),
                "wire_ser_ms": med("wire_ser_s"),
                "wire_deser_ms": med("wire_deser_s"),
                "transport": profiles[-1].get("solver_transport", "unary"),
                "host_share_ms": round(stats.median(
                    sum(p.get(k, 0.0) for k in host_keys) for p in profiles
                ) * 1e3, 2),
                "delta_hit_rate": round(sum(
                    1 for p in profiles
                    if any(k.endswith("_delta_s") for k in p)
                ) / max(len(profiles), 1), 4),
            }

        unary_leg = run_leg(stream=False)
        streamed_leg = run_leg(stream=True, delta=True)
        # the shm sub-leg keeps delta OFF: delta frames ride inline by
        # design (the resident base must outlive recycling arena slots),
        # so measuring the arena requires full-pod-set frames
        shm_leg = run_leg(stream=True, shm=shm_dir)
        out["unary_pods_per_sec"] = unary_leg["pods_per_sec"]
        out["unary_wire_ser_ms"] = unary_leg["wire_ser_ms"]
        out["unary_wire_deser_ms"] = unary_leg["wire_deser_ms"]
        out["streamed_pods_per_sec"] = streamed_leg["pods_per_sec"]
        out["streamed_p99_s"] = streamed_leg["p99_s"]
        out["streamed_transport"] = streamed_leg["transport"]
        out["streamed_wire_ser_ms"] = streamed_leg["wire_ser_ms"]
        out["streamed_wire_deser_ms"] = streamed_leg["wire_deser_ms"]
        out["streamed_host_share_ms"] = streamed_leg["host_share_ms"]
        out["streamed_delta_hit_rate"] = streamed_leg["delta_hit_rate"]
        out["streamed_shm"] = shm_leg

        # -- cross-stream coalescing phase --------------------------------
        # a second sidecar pinned to the scan kernel: coalescing only
        # engages on a DEVICE route (vmapping the native host packer would
        # amortize nothing), and `scan` is the same kernel family the real
        # device runs. Counters come off ITS /metrics — the production
        # observability surface.
        # 250ms busy-linger: longer than a scan solve, so in steady state
        # each stream's next solve lands inside a lingering collection —
        # deterministic grouping, and solo/idle dispatches still never
        # pay the window (the busy-aware collector)
        c_address, c_health, coalesce_sidecar = _spawn_sidecar(
            env={"KARPENTER_PACKER": "scan"}, coalesce_window=0.25,
        )
        name = "karpenter_solver_stream_coalesced_solves_total"
        dispatches = "karpenter_solver_stream_coalesced_dispatches_total"
        scheds = [
            Scheduler(
                Cluster(), rng=random.Random(10 + i),
                solver_service_address=c_address, solver_stream=True,
            )
            for i in range(coalesce_threads)
        ]
        for s in scheds:
            s.solve(provisioner, catalog, pods)  # warm + establish
        rounds = max(iters * 2, 10)
        errs = []

        def worker(s, n):
            try:
                for _ in range(n):
                    s.solve(provisioner, catalog, pods)
            except Exception as e:  # pragma: no cover - diagnostic
                errs.append(repr(e))

        def concurrent_rounds(n):
            threads = [
                threading.Thread(target=worker, args=(s, n)) for s in scheds
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        # unmeasured concurrent warm rounds: the scan kernel's single and
        # vmapped-bucket compiles must not eat the measured phase (a phase
        # spent entirely inside XLA compiles never forms a second group)
        concurrent_rounds(2)
        before = _scrape_metric(c_health, name)
        before_d = _scrape_metric(c_health, dispatches)
        t0 = time.perf_counter()
        concurrent_rounds(rounds)
        concurrent_wall = time.perf_counter() - t0
        total_phase = coalesce_threads * rounds
        delta_coalesced = _scrape_metric(c_health, name) - before
        out["concurrent_streams"] = coalesce_threads
        out["concurrent_pods_per_sec"] = round(
            total_phase * n_pods / max(concurrent_wall, 1e-9), 1
        )
        out["stream_coalesced_dispatch_rate"] = round(
            delta_coalesced / max(total_phase, 1), 4
        )
        out["stream_coalesced_dispatches"] = int(
            _scrape_metric(c_health, dispatches) - before_d
        )
        if errs:
            out["concurrent_errors"] = errs[:3]
        return out
    finally:
        if prev_packer is None:
            os.environ.pop("KARPENTER_PACKER", None)
        else:
            os.environ["KARPENTER_PACKER"] = prev_packer
        for proc in (sidecar, coalesce_sidecar):
            if proc is not None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except Exception:
                    proc.kill()
        import shutil

        shutil.rmtree(shm_dir, ignore_errors=True)


def _spawn_sidecar(shm_dir: str = "", env: dict = None, coalesce_window=None):
    """A REAL solver-sidecar subprocess (own interpreter, own GIL — the
    deployed topology); returns (address, health_port, Popen) once its
    warmup solve reports SERVING."""
    import subprocess

    address = f"127.0.0.1:{_stream_free_port()}"
    health_port = _stream_free_port()
    cmd = [
        sys.executable, "-m", "karpenter_tpu.solver.service",
        "--address", address, "--health-port", str(health_port),
        "--profile-hz", "0",
    ]
    if shm_dir:
        cmd += ["--solver-shm-dir", shm_dir]
    if coalesce_window is not None:
        cmd += ["--solver-coalesce-window", str(coalesce_window)]
    child_env = dict(os.environ)
    child_env.pop("KARPENTER_PACKER", None)
    child_env.update(env or {})
    proc = subprocess.Popen(
        cmd, env=child_env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    # readiness over the HTTP probe port (the kubelet surface): a gRPC
    # channel opened before the server binds parks in reconnect backoff
    # and can miss the whole startup window
    import urllib.error
    import urllib.request

    deadline = time.time() + 180
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"sidecar subprocess exited rc={proc.returncode}"
            )
        try:
            status = urllib.request.urlopen(
                f"http://127.0.0.1:{health_port}/readyz", timeout=2
            ).status
            if status == 200:
                return address, health_port, proc
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.25)
    proc.terminate()
    raise RuntimeError("sidecar subprocess never reported SERVING")


def _scrape_metric(health_port: int, name: str) -> float:
    """Sum a (label-less or labeled) metric family off a sidecar's
    /metrics — the production observability surface."""
    import urllib.request

    txt = urllib.request.urlopen(
        f"http://127.0.0.1:{health_port}/metrics", timeout=5
    ).read().decode()
    total = 0.0
    for line in txt.splitlines():
        if line.startswith(name):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                continue
    return total


def _stream_free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def bench_selection_storm(n_pods: int):
    """VERDICT r2 weak #3: drive n pod WATCH EVENTS through the full
    manager → selection → batcher → solve → bind pipeline and report
    end-to-end latency from pod creation to bind. This is the reference's
    10,000-concurrent-reconciles scenario (selection/controller.go:183)
    served by the thread-pool + non-blocking-enqueue architecture."""
    import threading

    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options

    cluster = Cluster()
    rt = build_runtime(Options(), cluster=cluster)
    rt.manager.start()
    try:
        prov = make_provisioner(solver="tpu")
        cluster.create("provisioners", prov)
        deadline = time.time() + 10
        while time.time() < deadline and not rt.provisioning.workers:
            time.sleep(0.02)
        for w in rt.provisioning.workers.values():
            w.batcher.idle_duration = 0.2
            # steady-state measurement: the one-time XLA compile of the
            # batch bucket happens in the worker's warmup, not in the storm
            w.warmed.wait(timeout=120)

        bind_times = {}
        created = {}
        lock = threading.Lock()

        def on_pod(event, pod):
            if event == "MODIFIED" and pod.spec.node_name:
                with lock:
                    if pod.metadata.name in created and pod.metadata.name not in bind_times:
                        bind_times[pod.metadata.name] = time.perf_counter()

        from karpenter_tpu.testing import make_pod

        cluster.watch("pods", on_pod)
        rng = random.Random(5)
        t0 = time.perf_counter()
        for i in range(n_pods):
            name = f"storm-{i}"
            p = make_pod(
                name=name, requests={"cpu": f"{rng.choice([0.25, 0.5, 1])}"}
            )
            with lock:
                created[name] = time.perf_counter()
            cluster.create("pods", p)
        enqueue_wall = time.perf_counter() - t0

        deadline = time.time() + 120
        while time.time() < deadline:
            with lock:
                done = len(bind_times)
            if done >= n_pods:
                break
            time.sleep(0.1)
        wall = time.perf_counter() - t0
        with lock:
            lat = sorted(bind_times[k] - created[k] for k in bind_times)
        bound = len(lat)
        return {
            "pods": n_pods,
            "bound": bound,
            "enqueue_wall_s": round(enqueue_wall, 3),
            "wall_s": round(wall, 3),
            "pods_per_sec": round(bound / wall, 1) if wall else 0.0,
            "bind_latency_p50_s": round(lat[len(lat) // 2], 3) if lat else None,
            "bind_latency_p99_s": round(_p99(lat), 3) if lat else None,
        }
    finally:
        rt.stop()


def bench_diverse(n_pods: int, k_labels: int, iters: int):
    """Constraint-diverse batch (VERDICT r1 weak #5): k distinct selector
    values drive the signature closure up; reports S and which kernel the
    budget routed to (pallas unrolls S×F, so high-S batches take lax.scan)."""
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.solver.pallas_kernel import PALLAS_UNROLL_BUDGET
    from karpenter_tpu.testing import make_pod

    rng = random.Random(11)
    catalog = instance_types(400)
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = [
        make_pod(
            requests={"cpu": f"{rng.choice([0.25, 0.5, 1])}"},
            node_selector={"team": f"t{i % k_labels}"},
        )
        for i in range(n_pods)
    ]
    # measure the actual closure size this batch produces
    cc = c.clone()
    probe = sort_pods_ffd(list(pods))
    Topology(Cluster(), rng=random.Random(1)).inject(cc, probe)
    batch = enc.encode(cc, sorted(catalog, key=lambda it: it.effective_price()),
                       probe, daemon_overhead(Cluster(), cc))
    s, f = len(batch.signatures), batch.frontiers.shape[1]

    scheduler = Scheduler(Cluster(), rng=random.Random(1))
    nodes = scheduler.solve(provisioner, catalog, pods)  # warmup/compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        nodes = scheduler.solve(provisioner, catalog, pods)
        times.append(time.perf_counter() - t0)
    scheduled = sum(len(n.pods) for n in nodes)
    from karpenter_tpu.scheduling.oracle import classify_drops

    verdict = classify_drops(
        scheduler.cluster, c, catalog, pods, [p for n in nodes for p in n.pods]
    )
    return {
        "signatures": s,
        "frontier_width": f,
        "kernel": "pallas" if s * f <= PALLAS_UNROLL_BUDGET else "lax.scan",
        "scheduled": scheduled,
        "pods": n_pods,
        "best_s": round(min(times), 4),
        "mean_s": round(statistics.mean(times), 4),
        "pods_per_sec": round(scheduled / min(times), 1),
        "unschedulable_expected": verdict["dropped"] - len(verdict["unexplained"]),
        "unexplained": len(verdict["unexplained"]),
    }


def bench_consolidation(n_nodes: int, iters: int, solver: str = "tpu"):
    """BASELINE config 5: re-pack of n live nodes in one batched solve."""
    from karpenter_tpu.api import labels as lbl
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
    from karpenter_tpu.controllers.consolidation import ConsolidationController
    from karpenter_tpu.testing import make_pod
    from karpenter_tpu.testing.factories import make_node

    rng = random.Random(7)
    catalog = instance_types(400)
    cluster = Cluster()
    provider = FakeCloudProvider(catalog)
    provisioner = make_provisioner(solver=solver)
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    cluster.create("provisioners", provisioner)
    for i in range(n_nodes):
        node = make_node(
            name=f"live-{i}",
            capacity={"cpu": "16", "memory": "32Gi", "pods": "100"},
            provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: f"fake-it-{rng.randrange(300, 400)}",
                    lbl.TOPOLOGY_ZONE: "test-zone-1", lbl.CAPACITY_TYPE: "on-demand"},
        )
        cluster.create("nodes", node)
        for j in range(rng.randrange(1, 4)):
            cluster.create(
                "pods",
                make_pod(name=f"p-{i}-{j}", requests={"cpu": f"{rng.choice([0.5, 1, 2])}"},
                         node_name=node.metadata.name, unschedulable=False),
            )
    controller = ConsolidationController(cluster, provider)
    plan = controller.plan(provisioner)  # warmup/compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        plan = controller.plan(provisioner)
        times.append(time.perf_counter() - t0)
    placed = sum(len(v.pods) for v in plan.proposed)
    return {
        "nodes_in": n_nodes,
        "nodes_out": len(plan.proposed),
        "pods": len(plan.pods),
        # a consolidation plan must seat every reschedulable pod
        # (ConsolidationPlan.worthwhile enforces this before any evict)
        "repack_placed": placed,
        "repack_drops": len(plan.pods) - placed,
        "savings_frac": round(plan.savings / max(plan.current_price, 1e-9), 3),
        "repack_s": min(times),
        "mean_s": statistics.mean(times),
    }


def bench_interruption_churn(
    n_pods: int = 1000,
    preempt_frac: float = 0.05,
    rounds: int = 5,
):
    """Interruption churn: a steady ``n_pods`` load through the FULL
    runtime (fake provider) while ``preempt_frac`` of the live fleet gets
    a preemption notice each round — the per-minute churn compressed to
    bench time. Reports the two numbers future BENCH rounds track:
    ``interruption_evicted_unready`` (pods evicted with no replacement
    ready — 0 under the fake provider is the done-bar) and
    ``replacement_lead_time_p99_s`` (notice → re-bind on fresh capacity)."""
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options
    from karpenter_tpu.testing.factories import make_pod
    from karpenter_tpu.utils import pod as podutil

    rng = random.Random(17)
    provider = FakeCloudProvider(instance_types(20))
    cluster = Cluster()
    rt = build_runtime(Options(), cluster=cluster, cloud_provider=provider)
    rt.interruption.poll_interval = 0.1  # bench-speed notice latency
    rt.manager.start()
    t_start = time.perf_counter()
    try:
        cluster.create("provisioners", make_provisioner(solver="ffd"))
        deadline = time.time() + 10
        while time.time() < deadline and not rt.provisioning.workers:
            time.sleep(0.02)
        assert rt.provisioning.workers, "provisioner worker never started"
        for w in rt.provisioning.workers.values():
            w.batcher.idle_duration = 0.1
        for i in range(n_pods):
            cluster.create(
                "pods",
                make_pod(
                    name=f"churn-{i}",
                    requests={"cpu": f"{rng.choice([0.1, 0.25, 0.5])}"},
                ),
            )

        def settled(timeout: float) -> bool:
            stop = time.time() + timeout
            while time.time() < stop:
                if not any(podutil.is_provisionable(p) for p in cluster.pods()):
                    return True
                time.sleep(0.1)
            return False

        assert settled(120), "steady-state load never bound"
        preempted_total = 0
        for _ in range(rounds):
            live = [
                n.metadata.name
                for n in cluster.nodes()
                if n.metadata.deletion_timestamp is None
            ]
            victims = rng.sample(live, max(1, int(math.ceil(len(live) * preempt_frac))))
            for name in victims:
                provider.preempt(name, grace_period_seconds=120.0)
            preempted_total += len(victims)
            # the round completes when every victim is drained away AND the
            # replaced pods are bound again
            stop = time.time() + 60
            while time.time() < stop and any(
                cluster.try_get("nodes", v, namespace="") is not None for v in victims
            ):
                time.sleep(0.05)
            assert all(
                cluster.try_get("nodes", v, namespace="") is None for v in victims
            ), "preempted nodes never terminated"
            assert settled(60), "replacement capacity never absorbed the round"
        # let in-flight terminations finish so the drain counters settle
        stop = time.time() + 30
        while time.time() < stop and any(
            n.metadata.deletion_timestamp is not None for n in cluster.nodes()
        ):
            time.sleep(0.1)
        lead = sorted(rt.interruption.lead_times)
        return {
            "pods": n_pods,
            "rounds": rounds,
            "preempt_frac": preempt_frac,
            "nodes_preempted": preempted_total,
            "pods_replaced": len(lead),
            "interruption_evicted_unready": rt.interruption.evicted_unready,
            "replacement_lead_time_p50_s": round(lead[len(lead) // 2], 4) if lead else None,
            "replacement_lead_time_p99_s": round(_p99(lead), 4) if lead else None,
            "notices_handled": rt.interruption.notices_handled,
            "wall_s": round(time.perf_counter() - t_start, 2),
        }
    finally:
        rt.stop()


def bench_chaos(
    n_pods: int = 300,
    error_rate: float = 0.1,
    latency_p95: float = 0.05,
    seed: int = 20260803,
    storm: tuple = (6.0, 9.0),
    preempt: int = 2,
):
    """Chaos leg: the FULL runtime against the simulated provider whose
    control plane misbehaves statistically (testing/chaos.py) — per-call
    error probability, injected latency, an ICE-storm window, plus live
    preemptions mid-run. The resilience layer (retries, breakers, round
    budgets) is what makes this converge; the leg reports the two numbers
    future BENCH rounds track: ``chaos_provision_success_rate`` (bound /
    created pods — the done-bar is 1.0) and ``chaos_launch_p99_s`` (pod
    create → bind under chaos), and asserts no breaker stays open once the
    storm window is over."""
    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
    from karpenter_tpu.interruption.types import PREEMPTION, DisruptionNotice
    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options
    from karpenter_tpu.testing.chaos import ChaosPolicy, ChaosWindow, chaos_wrap
    from karpenter_tpu.testing.factories import make_pod

    api = SimCloudAPI()
    chaos = chaos_wrap(api, ChaosPolicy(
        error_rate=error_rate,
        latency_p95=latency_p95,
        ice_storms=(ChaosWindow(*storm),),
        seed=seed,
    ))
    provider = SimulatedCloudProvider(api=chaos)
    cluster = Cluster()
    rt = build_runtime(Options(), cluster=cluster, cloud_provider=provider)
    rt.interruption.poll_interval = 0.1
    rt.manager.start()
    t_start = time.perf_counter()
    try:
        cluster.create("provisioners", make_provisioner(solver="ffd"))
        deadline = time.time() + 10
        while time.time() < deadline and not rt.provisioning.workers:
            time.sleep(0.02)
        assert rt.provisioning.workers, "provisioner worker never started"
        for w in rt.provisioning.workers.values():
            w.batcher.idle_duration = 0.1
        t0 = time.perf_counter()
        names = []
        for i in range(n_pods):
            name = f"chaos-{i}"
            names.append(name)
            cluster.create("pods", make_pod(name=name, requests={"cpu": "0.25"}))

        # poll for binds, recording each pod's create→bind latency; the ICE
        # storm can sideline every offering for the 45s unavailable-TTL, so
        # the settle allowance covers a full cache expiry plus slack
        bound_at = {}
        settle_deadline = time.time() + 180
        preempted = set()
        while time.time() < settle_deadline:
            alive = [
                p for p in cluster.pods() if p.metadata.deletion_timestamp is None
            ]
            for p in alive:
                if p.spec.node_name and p.metadata.name not in bound_at:
                    bound_at[p.metadata.name] = time.perf_counter() - t0
            # judged on LIVE pod state, not first-bind records: a preempted
            # pod re-enters pending and must re-bind before the leg settles
            all_bound = bool(alive) and all(p.spec.node_name for p in alive)
            # preempt only AFTER the initial workload settled (the churn
            # bench does the same): a notice racing an in-flight bind can
            # evict the pod mid-bind — a pre-existing orchestrator race
            # this leg is not trying to measure
            if preempt and not preempted and all_bound:
                live = [
                    n.metadata.name for n in cluster.nodes()
                    if n.metadata.deletion_timestamp is None
                ]
                for victim in live[:preempt]:
                    preempted.add(victim)
                    api.send_disruption_notice(DisruptionNotice(
                        kind=PREEMPTION, node_name=victim,
                        grace_period_seconds=60.0,
                    ))
                continue
            if all_bound and preempted and all(
                cluster.try_get("nodes", v, namespace="") is None for v in preempted
            ) and chaos.elapsed() > storm[1]:
                break
            time.sleep(0.05)

        # denominator is CREATED pods, not survivors: a pod lost to a
        # deadline eviction must drag the headline below 1.0, never
        # silently drop out of the ratio
        bound = [
            p for p in cluster.pods()
            if p.metadata.deletion_timestamp is None and p.spec.node_name
            and p.metadata.name in set(names)
        ]
        latencies = sorted(bound_at.values())
        breakers_open = []
        breakers = getattr(rt.cloud_provider, "breakers", None)
        if breakers is not None:
            breakers_open = breakers.open_dependencies()
        return {
            "pods": n_pods,
            "error_rate": error_rate,
            "latency_p95_injected_s": latency_p95,
            "ice_storm_s": list(storm),
            "seed": seed,
            "chaos_provision_success_rate": round(len(bound) / max(n_pods, 1), 4),
            "chaos_launch_p99_s": round(_p99(latencies), 4) if latencies else None,
            "chaos_launch_p50_s": round(latencies[len(latencies) // 2], 4) if latencies else None,
            "chaos_injected_failures": chaos.injected_total(),
            "chaos_injected_by_method": dict(sorted(chaos.injected.items())),
            "nodes_preempted": len(preempted),
            "interruption_evicted_unready": rt.interruption.evicted_unready,
            "breakers_open_after_storm": breakers_open,
            "wall_s": round(time.perf_counter() - t_start, 2),
        }
    finally:
        rt.stop()


def bench_fleet_storm(
    n_pods: int = 400,
    n_provisioners: int = 8,
    n_replicas: int = 3,
    pool_size: int = 2,
    lease_duration: float = 2.0,
    renew_interval: float = 0.4,
    kill_replica: bool = True,
    kill_sidecar: bool = True,
    solver: str = "tpu",
):
    """Fleet-scale HA storm (docs/fleet.md): N controller replicas share one
    cluster and one shard-lease file, provisioners partition across them by
    rendezvous placement, and solves route through a consistent-hash pool
    of solver sidecars. Mid-storm a shard OWNER replica is killed (crash —
    its leases expire, survivors rebalance) and a sidecar pool member is
    killed (solves fail over through the ring, NEEDS_CATALOG re-uploads on
    the survivor). The leg reports the acceptance numbers: aggregate
    pods/sec, p99 time-to-bind, duplicate launches (must be 0), and
    rebalance time vs the 2x-lease-duration bar."""
    import tempfile
    import threading

    from karpenter_tpu import metrics as m
    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options
    from karpenter_tpu.testing.chaos import ReplicaChaos, SidecarChaos
    from karpenter_tpu.testing.factories import make_pod
    from karpenter_tpu.api.objects import NodeSelectorRequirement

    t_start = time.perf_counter()
    # pin the device path for the leg: the cost router would (correctly)
    # send these small batches to the native backend, and a storm that
    # never touches the sidecars proves nothing about pool failover
    packer_before = os.environ.get("KARPENTER_PACKER")
    if pool_size and solver == "tpu":
        os.environ["KARPENTER_PACKER"] = "device"
    sidecars = SidecarChaos(n=pool_size) if pool_size else None
    lease_path = tempfile.mktemp(prefix="karpenter-fleet-lease-")
    cluster = Cluster()
    api = SimCloudAPI()
    fleet = ReplicaChaos()
    # duplicate-launch detector: any pod whose nodeName flips from one
    # non-empty value to another was double-provisioned (no preemption in
    # this leg, so there is no legitimate re-bind)
    rebinds = []
    last_node = {}
    bound_at = {}
    t0_box = [0.0]
    watch_mu = threading.Lock()

    def on_pod(event, pod):
        if event == "DELETED" or not pod.spec.node_name:
            return
        with watch_mu:
            prev = last_node.get(pod.metadata.name)
            if prev and prev != pod.spec.node_name:
                rebinds.append((pod.metadata.name, prev, pod.spec.node_name))
            last_node[pod.metadata.name] = pod.spec.node_name
            if pod.metadata.name not in bound_at:
                bound_at[pod.metadata.name] = time.perf_counter() - t0_box[0]

    cluster.watch("pods", on_pod)

    opts_kwargs = dict(
        shard_lease=lease_path,
        shard_lease_duration=lease_duration,
        solver_service_address=sidecars.address_spec if sidecars else "",
    )
    try:
        for i in range(n_replicas):
            rt = build_runtime(
                Options(**opts_kwargs),
                cluster=cluster,
                cloud_provider=SimulatedCloudProvider(api=api),
                shard_identity=f"replica-{i}",
            )
            rt.ownership.renew_interval = renew_interval
            rt.ownership.start()
            rt.manager.start()
            fleet.add(f"replica-{i}", rt)

        for i in range(n_provisioners):
            cluster.create("provisioners", make_provisioner(
                name=f"fleet-{i}", solver=solver,
                requirements=[NodeSelectorRequirement(
                    key="fleet", operator="In", values=[f"fleet-{i}"],
                )],
            ))

        # wait until every shard has exactly one live owner + worker
        deadline = time.time() + 30
        names = [f"fleet-{i}" for i in range(n_provisioners)]
        while time.time() < deadline:
            owners = {
                name: fleet.owner_named(name) for name in names
            }
            workers_ready = all(
                rt is not None and name in rt.provisioning.workers
                for name, (_, rt) in owners.items()
            )
            if workers_ready:
                break
            time.sleep(0.05)
        assert all(fleet.owner_named(n)[0] for n in names), "shards never all owned"
        for rt in fleet.replicas.values():
            for w in rt.provisioning.workers.values():
                w.batcher.idle_duration = 0.1

        shard_counts_before = {
            name: len(shards) for name, shards in fleet.owned_shards().items()
        }

        t0_box[0] = time.perf_counter()
        for i in range(n_pods):
            cluster.create("pods", make_pod(
                name=f"storm-{i}", requests={"cpu": "0.25"},
                node_selector={"fleet": f"fleet-{i % n_provisioners}"},
            ))

        # mid-storm: first kill the session-bearing sidecar member (a cold
        # spare would exercise nothing — wait until a catalog session
        # actually lives somewhere; the warmup compiles delay the first
        # remote solve), then CRASH the owner of shard fleet-0 (leases
        # expire, never released) and time the rebalance.
        rebalance_s = None
        victim_shards = frozenset()
        if kill_sidecar and sidecars:
            deadline = time.time() + 60
            while time.time() < deadline:
                if any(
                    s.solver_service.session_count()
                    for s in sidecars.servers.values()
                ):
                    break
                time.sleep(0.05)
            sidecars.kill(sidecars.busiest())
        if kill_replica:
            time.sleep(0.3)  # let the storm engage
            victim, victim_rt = fleet.owner_named("fleet-0")
            victim_shards = frozenset(victim_rt.ownership.owned())
            t_kill = time.perf_counter()
            fleet.kill(victim)
            deadline = time.time() + lease_duration * 10
            while time.time() < deadline:
                survivors_own = set()
                for rt in fleet.replicas.values():
                    survivors_own |= rt.ownership.owned()
                if victim_shards <= survivors_own:
                    rebalance_s = time.perf_counter() - t_kill
                    break
                time.sleep(0.05)

        # settle: every created pod bound
        deadline = time.time() + 240
        while time.time() < deadline:
            pods = [p for p in cluster.pods() if p.metadata.name.startswith("storm-")]
            if pods and all(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        pods = [p for p in cluster.pods() if p.metadata.name.startswith("storm-")]
        bound = [p for p in pods if p.spec.node_name]
        latencies = sorted(bound_at[p.metadata.name] for p in bound if p.metadata.name in bound_at)
        elapsed = max(latencies) if latencies else float("nan")
        failovers = _sample(m, "karpenter_solver_pool_failovers_total")
        guard_hits = _sample(m, "karpenter_fleet_duplicate_launch_guard_total")
        return {
            "pods": n_pods,
            "provisioners": n_provisioners,
            "replicas": n_replicas,
            "pool_size": pool_size,
            "solver": solver,
            "lease_duration_s": lease_duration,
            "chaos_provision_success_rate": round(len(bound) / max(n_pods, 1), 4),
            "aggregate_pods_per_sec": round(len(bound) / elapsed, 1) if latencies else None,
            "p99_time_to_bind_s": round(_p99(latencies), 4) if latencies else None,
            "p50_time_to_bind_s": round(latencies[len(latencies) // 2], 4) if latencies else None,
            "duplicate_launches": len(rebinds),
            "duplicate_rebinds": rebinds[:5],
            "duplicate_launch_guard_hits": guard_hits,
            "replica_killed": kill_replica,
            "sidecar_killed": bool(kill_sidecar and sidecars),
            "rebalance_s": round(rebalance_s, 3) if rebalance_s is not None else None,
            "rebalance_bar_s": round(2 * lease_duration, 3),
            "rebalance_within_bar": (
                rebalance_s is not None and rebalance_s <= 2 * lease_duration
                if kill_replica else None
            ),
            "shards_per_replica_before_kill": shard_counts_before,
            "shards_per_replica_after": {
                name: len(s) for name, s in fleet.owned_shards().items()
            },
            "pool_failovers_total": failovers,
            "wall_s": round(time.perf_counter() - t_start, 2),
        }
    finally:
        if packer_before is None:
            os.environ.pop("KARPENTER_PACKER", None)
        else:
            os.environ["KARPENTER_PACKER"] = packer_before
        fleet.stop_all()
        if sidecars:
            sidecars.stop_all()
        try:
            os.remove(lease_path)
        except OSError:
            pass


def _forecast_phase(
    label: str,
    schedule,
    n_provisioners: int,
    launch_latency_s: float,
    warm_pool: bool,
    warm_pool_ttl: float,
    max_warm_nodes: int,
    wave_interval: float,
    solver: str,
    in_flash=lambda t: False,
    decision_dir: str = "",
    forecast_bucket_s: float = 1.0,
    forecast_alpha: float = 0.35,
    forecast_horizon_s: float = 8.0,
):
    """One arrival-storm pass — the cold (reactive) and warm (predictive)
    phases of ``bench_forecast_storm`` run the SAME compiled schedule
    through this, differing only in ``warm_pool``."""
    import threading

    from karpenter_tpu import metrics as m
    from karpenter_tpu import obs
    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options
    from karpenter_tpu.testing.factories import make_pod
    from karpenter_tpu.api.objects import NodeSelectorRequirement

    def sample(name):
        return _sample(m, name)

    counters_before = {
        name: sample(name) for name in (
            "karpenter_warmpool_hits_total",
            "karpenter_warmpool_misses_total",
            "karpenter_warmpool_speculative_launches_total",
            "karpenter_warmpool_expired_total",
            "karpenter_fleet_duplicate_launch_guard_total",
        )
    }
    cluster = Cluster()
    api = SimCloudAPI()
    # the cold-launch tax the warm pool exists to hide: every create_fleet
    # pays this before the Node (and therefore any bind) can exist
    api.launch_latency_s = launch_latency_s
    created_ts = {}
    bound_latency = {}
    rebinds = []
    last_node = {}
    watch_mu = threading.Lock()

    def on_pod(event, pod):
        if event == "DELETED" or not pod.spec.node_name:
            return
        with watch_mu:
            prev = last_node.get(pod.metadata.name)
            if prev and prev != pod.spec.node_name:
                rebinds.append((pod.metadata.name, prev, pod.spec.node_name))
            last_node[pod.metadata.name] = pod.spec.node_name
            t0 = created_ts.get(pod.metadata.name)
            if t0 is not None and pod.metadata.name not in bound_latency:
                bound_latency[pod.metadata.name] = time.perf_counter() - t0

    cluster.watch("pods", on_pod)

    engine = None
    if warm_pool:
        # build_runtime wires the controller; the forecaster itself is
        # process-global (run_controller_process installs it in prod)
        engine = obs.configure_forecast(
            bucket_s=forecast_bucket_s, alpha=forecast_alpha,
            default_horizon_s=forecast_horizon_s,
        )
    if decision_dir:
        obs.configure_decisions(decision_dir, write_interval=0.0)
    rt = build_runtime(
        Options(
            default_solver=solver,
            warm_pool=warm_pool,
            warm_pool_ttl=warm_pool_ttl,
            warm_pool_max_nodes=max_warm_nodes,
            gc_interval=1.0,
            # speculative entries live in the journal (the TTL
            # breadcrumb) — the warm pool is inert without one
            launch_journal="memory:",
        ),
        cluster=cluster,
        cloud_provider=SimulatedCloudProvider(api=api),
    )
    # compressed-time knobs: second-scale waves/sweeps instead of the
    # production minute-scale defaults (the leg IS the clock compression)
    if rt.warmpool is not None:
        rt.warmpool.interval = wave_interval
    rt.garbage_collection.gc_interval = 1.0
    rt.garbage_collection.replay_after = 3.0
    try:
        rt.manager.start()
        for i in range(n_provisioners):
            cluster.create("provisioners", make_provisioner(
                name=f"fc-{i}", solver=solver,
                requirements=[NodeSelectorRequirement(
                    key="fc", operator="In", values=[f"fc-{i}"],
                )],
            ))
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(
                f"fc-{i}" in rt.provisioning.workers
                for i in range(n_provisioners)
            ):
                break
            time.sleep(0.05)
        for w in rt.provisioning.workers.values():
            w.batcher.idle_duration = 0.1

        # drive the compiled schedule in real time; flash-crowd ticks get
        # the "flash-" prefix so the spike tail is separable
        start = time.perf_counter()
        n_created = 0
        for tick_i, (t_off, count) in enumerate(schedule):
            delay = t_off - (time.perf_counter() - start)
            if delay > 0:
                time.sleep(delay)
            prefix = "flash" if in_flash(t_off) else "base"
            for j in range(count):
                name = f"{prefix}-{label}-{tick_i}-{j}"
                created_ts[name] = time.perf_counter()
                cluster.create("pods", make_pod(
                    name=name, requests={"cpu": "0.25"},
                    node_selector={"fc": f"fc-{n_created % n_provisioners}"},
                ))
                n_created += 1

        # settle: every pod bound
        deadline = time.time() + 120
        while time.time() < deadline:
            pods = list(cluster.pods())
            if pods and all(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        pods = list(cluster.pods())
        bound = [p for p in pods if p.spec.node_name]

        # epilogue: stop speculating, let the TTL + GC ladder reclaim
        # every standing warm node and drain the journal — the
        # adopted-or-reclaimed acceptance bar
        if rt.warmpool is not None:
            from karpenter_tpu.api import labels as lbl

            rt.warmpool.set_paused(True)
            deadline = time.time() + warm_pool_ttl * 4 + 15
            while time.time() < deadline:
                warm_standing = [
                    n for n in cluster.nodes()
                    if lbl.WARM_POOL_ANNOTATION in n.metadata.annotations
                    and n.metadata.deletion_timestamp is None
                ]
                if not warm_standing and not rt.journal.unresolved():
                    break
                time.sleep(0.2)

        node_names = {n.metadata.name for n in cluster.nodes()}
        provider_ids = {n.spec.provider_id for n in cluster.nodes()}
        live = [i for i in api.list_instances() if i.state != "terminated"]
        leaked = [
            i for i in live
            if i.id not in node_names
            and f"sim:///{i.zone}/{i.id}" not in provider_ids
        ]
        lat = sorted(bound_latency.values())
        spike = sorted(
            v for k, v in bound_latency.items() if k.startswith("flash-")
        )
        counters = {
            name: sample(name) - before
            for name, before in counters_before.items()
        }
        hits = counters["karpenter_warmpool_hits_total"]
        misses = counters["karpenter_warmpool_misses_total"]
        return {
            "phase": label,
            "pods": n_created,
            "bound": len(bound),
            "time_to_ready_p99_s": round(_p99(lat), 4) if lat else None,
            "time_to_ready_p50_s": (
                round(lat[len(lat) // 2], 4) if lat else None
            ),
            "spike_time_to_ready_p99_s": (
                round(_p99(spike), 4) if spike else None
            ),
            "warm_hits": int(hits),
            "warm_misses": int(misses),
            "warm_hit_rate": (
                round(hits / (hits + misses), 4) if (hits + misses) else 0.0
            ),
            "speculative_launches": int(
                counters["karpenter_warmpool_speculative_launches_total"]
            ),
            "speculative_expired": int(
                counters["karpenter_warmpool_expired_total"]
            ),
            "duplicate_launches": len(rebinds),
            "duplicate_launch_guard_hits": counters[
                "karpenter_fleet_duplicate_launch_guard_total"
            ],
            "leaked_instances": len(leaked),
            "unresolved_journal_entries": (
                len(rt.journal.unresolved()) if rt.journal else 0
            ),
        }
    finally:
        rt.stop()
        if engine is not None:
            obs.shutdown_forecast(engine=engine)
        if decision_dir:
            obs.configure_decisions("")


def bench_forecast_storm(
    duration_s: float = 30.0,
    n_provisioners: int = 2,
    launch_latency_s: float = 0.5,
    warm_pool_ttl: float = 8.0,
    max_warm_nodes: int = 12,
    wave_interval: float = 0.5,
    solver: str = "ffd",
    seed: int = 20260807,
):
    """Predictive-provisioning macro leg (docs/forecasting.md): the SAME
    seeded diurnal + flash-crowd storm runs twice over a cloud double
    whose ``create_fleet`` pays a real launch latency — once purely
    reactive (cold), once with the forecaster + speculative warm pool
    (warm). The acceptance numbers: warm spike time-to-ready p99 at least
    2x better than cold, zero leaked instances and duplicate launches,
    every speculative journal entry claimed or TTL-reclaimed, and the
    what-if simulator's predicted warm-hit rate within 20% of measured
    (the counterfactual tool is only trustworthy if it reproduces the
    factual)."""
    import tempfile

    from karpenter_tpu.testing.chaos import ArrivalPattern

    t_start = time.perf_counter()
    pattern = ArrivalPattern(
        base_pods_per_tick=3.0,
        amplitude=0.7,
        period_s=duration_s / 2.0,
        tick_s=1.0,
        flash_at=(duration_s * 0.55, duration_s * 0.8),
        flash_pods=24,
        flash_len_s=3.0,
        seed=seed,
    )
    schedule = pattern.schedule(duration_s)
    decision_dir = tempfile.mkdtemp(prefix="karpenter-forecast-ring-")
    common = dict(
        n_provisioners=n_provisioners,
        launch_latency_s=launch_latency_s,
        warm_pool_ttl=warm_pool_ttl,
        max_warm_nodes=max_warm_nodes,
        wave_interval=wave_interval,
        solver=solver,
        in_flash=pattern.in_flash,
    )
    cold = _forecast_phase("cold", schedule, warm_pool=False, **common)
    warm = _forecast_phase(
        "warm", schedule, warm_pool=True, decision_dir=decision_dir, **common
    )

    # the what-if cross-check: re-simulate the ring the warm phase just
    # recorded under the same policy knobs; its predicted hit rate must
    # land within 20% of what the live controller measured
    from tools.whatif import whatif as run_whatif

    prediction = run_whatif(
        decision_dir,
        warm_pool_ttl=warm_pool_ttl,
        max_nodes=max_warm_nodes,
        interval_s=wave_interval,
        launch_to_ready_s=cold["time_to_ready_p50_s"] or launch_latency_s,
        bind_latency_s=warm["time_to_ready_p50_s"] or 0.05,
        horizon_s=8.0,
        bucket_s=1.0,
        alpha=0.35,
    )
    predicted_rate = prediction["combined"]["warm_hit_rate"]
    measured_rate = warm["warm_hit_rate"]
    whatif_err = (
        abs(predicted_rate - measured_rate) / measured_rate
        if measured_rate else None
    )

    spike_cold = cold["spike_time_to_ready_p99_s"]
    spike_warm = warm["spike_time_to_ready_p99_s"]
    speedup = (
        round(spike_cold / spike_warm, 2)
        if spike_cold and spike_warm else None
    )
    return {
        "duration_s": duration_s,
        "provisioners": n_provisioners,
        "launch_latency_s": launch_latency_s,
        "warm_pool_ttl_s": warm_pool_ttl,
        "seed": seed,
        "scheduled_pods": sum(n for _, n in schedule),
        "cold": cold,
        "warm": warm,
        # headline keys (tools/bench_compare.py HEADLINE_KEYS)
        "time_to_ready_p99_s": warm["time_to_ready_p99_s"],
        "warm_hit_rate": warm["warm_hit_rate"],
        "spike_speedup_warm_vs_cold": speedup,
        "spike_speedup_bar": 2.0,
        "duplicate_launches": (
            cold["duplicate_launches"] + warm["duplicate_launches"]
        ),
        "leaked_instances": (
            cold["leaked_instances"] + warm["leaked_instances"]
        ),
        "unresolved_journal_entries": warm["unresolved_journal_entries"],
        "whatif_predicted_warm_hit_rate": round(predicted_rate, 4),
        "whatif_relative_error": (
            round(whatif_err, 4) if whatif_err is not None else None
        ),
        "whatif_within_20pct": (
            whatif_err <= 0.20 if whatif_err is not None else None
        ),
        "decision_dir": decision_dir,
        "wall_s": round(time.perf_counter() - t_start, 2),
    }


def bench_partition_storm(
    n_pods: int = 240,
    n_provisioners: int = 8,
    n_replicas: int = 3,
    lease_duration: float = 1.5,
    renew_interval: float = 0.3,
    gc_interval: float = 1.0,
):
    """Control-plane partition storm (docs/partition.md): N controller
    replicas, each a real ``ApiCluster`` over HTTP against ONE protocol-
    double apiserver wrapped in ``ApiServerChaos``, shard leases and all.
    Four phases: warm -> a SUB-EXPIRY blackout blip (bar: ZERO shard
    rebalances — the fleet must not read a 10s blip as fleet-wide lease
    loss) -> a sustained 429 brownout (the transport's Retry-After ladder
    keeps provisioning) -> a 2x-lease-duration blackout (bar: every
    replica FENCED, zero cloud mutations while fenced, bounded
    time-to-recover). Throughout: duplicate_launches=0 (watch-rebind
    detector), leaked_instances=0 (journal + GC audit), and provision
    success 1.0 after recovery."""
    import tempfile
    import threading

    from karpenter_tpu import metrics as m
    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
    from karpenter_tpu.kube.apiserver import ApiCluster
    from karpenter_tpu.kube.testserver import TestApiServer
    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options
    from karpenter_tpu.testing.chaos import ApiServerChaos
    from karpenter_tpu.testing.factories import make_pod
    from karpenter_tpu.api.objects import NodeSelectorRequirement

    t_start = time.perf_counter()
    backing = Cluster()
    env = TestApiServer(cluster=backing)
    env.start()
    chaos = ApiServerChaos(seed=20260803)
    api = SimCloudAPI()

    class _MutationRecorder:
        """Cloud-mutation timestamps: the fenced-window bar is judged on
        'zero create_fleet/terminate calls while every replica is fenced'."""

        def __init__(self, delegate):
            self._delegate = delegate
            self.mutations = []  # (perf_counter, method)
            self._mu = threading.Lock()

        def __getattr__(self, name):
            attr = getattr(self._delegate, name)
            if name in ("create_fleet", "terminate_instances") and callable(attr):
                def recorded(*args, **kwargs):
                    with self._mu:
                        self.mutations.append((time.perf_counter(), name))
                    return attr(*args, **kwargs)

                return recorded
            return attr

        def mutation_count(self) -> int:
            with self._mu:
                return len(self.mutations)

    recorder = _MutationRecorder(api)
    journal_path = tempfile.mktemp(prefix="karpenter-partition-journal-")

    # duplicate-launch detector: a pod whose nodeName flips between two
    # non-empty values was double-provisioned (no preemption in this leg)
    rebinds = []
    last_node = {}
    watch_mu = threading.Lock()

    def on_pod(event, pod):
        if event == "DELETED" or not pod.spec.node_name:
            return
        with watch_mu:
            prev = last_node.get(pod.metadata.name)
            if prev and prev != pod.spec.node_name:
                rebinds.append((pod.metadata.name, prev, pod.spec.node_name))
            last_node[pod.metadata.name] = pod.spec.node_name

    backing.watch("pods", on_pod)

    runtimes = []
    created = 0

    def create_pods(prefix: str, n: int) -> list:
        nonlocal created
        names = []
        for i in range(n):
            name = f"{prefix}-{i}"
            names.append(name)
            backing.create("pods", make_pod(
                name=name, requests={"cpu": "0.25"},
                node_selector={"partfleet": f"part-{i % n_provisioners}"},
            ))
        created += n
        return names

    def wait_bound(names: list, timeout: float = 120.0) -> bool:
        deadline = time.time() + timeout
        want = set(names)
        while time.time() < deadline:
            live = {
                p.metadata.name: p for p in backing.pods()
                if p.metadata.name in want
            }
            if len(live) == len(want) and all(
                p.spec.node_name for p in live.values()
            ):
                return True
            time.sleep(0.05)
        return False

    def sample(name):
        return _sample(m, name)

    try:
        for i in range(n_replicas):
            cluster = ApiCluster(env.url)
            # CI-speed retry pacing (the ladder SHAPE is what's under test)
            cluster.transport._backoff_base = 0.01
            cluster.transport._backoff_cap = 0.1
            cluster.watch_backoff_base = 0.1
            cluster.watch_backoff_cap = 2.0
            rt = build_runtime(
                Options(
                    shard_lease="kube:kube-system/karpenter-shard",
                    shard_lease_duration=lease_duration,
                    launch_journal=journal_path,
                    gc_interval=gc_interval,
                    gc_grace_period=max(gc_interval * 6, 8.0),
                    default_solver="ffd",
                ),
                cluster=cluster,
                cloud_provider=SimulatedCloudProvider(api=recorder),
                shard_identity=f"replica-{i}",
            )
            cluster.start()
            assert cluster.wait_for_sync(30), "informer cache never synced"
            rt.ownership.renew_interval = renew_interval
            rt.garbage_collection.replay_after = gc_interval
            rt.ownership.start()
            rt.manager.start()
            runtimes.append(rt)

        names = [f"part-{i}" for i in range(n_provisioners)]
        for name in names:
            backing.create("provisioners", make_provisioner(
                name=name, solver="ffd",
                requirements=[NodeSelectorRequirement(
                    key="partfleet", operator="In", values=[name],
                )],
            ))

        def owner_of(shard):
            for rt in runtimes:
                if rt.ownership.owns(shard):
                    return rt
            return None

        deadline = time.time() + 60
        while time.time() < deadline:
            owners = {name: owner_of(name) for name in names}
            if all(
                rt is not None and name in rt.provisioning.workers
                for name, rt in owners.items()
            ):
                break
            time.sleep(0.05)
        assert all(owner_of(n) is not None for n in names), "shards never all owned"
        for rt in runtimes:
            for w in rt.provisioning.workers.values():
                w.batcher.idle_duration = 0.1

        quarter = max(n_pods // 4, 8)

        # ---- phase 1: warm — the fleet provisions over real HTTP
        assert wait_bound(create_pods("warm", quarter)), "warm phase never bound"

        # ---- phase 2: SUB-EXPIRY blip — the bar is ZERO shard churn
        rebal_before = sample("karpenter_fleet_shard_rebalances_total")
        losses_before = sample("karpenter_fleet_shard_losses_total")
        env.chaos = chaos
        blip = chaos.blackout(lease_duration * 0.5)
        while chaos.in_blackout():
            time.sleep(0.02)
        time.sleep(renew_interval * 3)  # a couple of post-blip renew ticks
        blip_rebalances = (
            sample("karpenter_fleet_shard_rebalances_total") - rebal_before
        )
        blip_losses = sample("karpenter_fleet_shard_losses_total") - losses_before
        assert wait_bound(create_pods("postblip", quarter)), "post-blip pods never bound"

        # ---- phase 3: sustained 429 brownout — Retry-After ladder holds
        throttled_before = sample("karpenter_kube_throttled_total")
        chaos.throttle_rate = 0.4
        chaos.retry_after = 0.05
        brownout_names = create_pods("brownout", quarter)
        time.sleep(2.0)
        chaos.throttle_rate = 0.0
        brownout_throttles = sample("karpenter_kube_throttled_total") - throttled_before
        assert wait_bound(brownout_names), "brownout pods never bound"

        # ---- phase 4: 2x-lease blackout — every replica must FENCE
        def fenced_hits():
            return m.REGISTRY.get_sample_value(
                "karpenter_fleet_duplicate_launch_guard_total",
                {"reason": "fenced"},
            ) or 0.0

        fenced_guard_before = fenced_hits()
        blackout_s = lease_duration * 2.2
        window = chaos.blackout(blackout_s)
        t_blackout = time.perf_counter()
        all_fenced_at = None
        mutations_at_fence = None
        deadline = time.time() + blackout_s
        while time.time() < deadline:
            if all(rt.ownership.fenced() for rt in runtimes):
                all_fenced_at = time.perf_counter() - t_blackout
                mutations_at_fence = recorder.mutation_count()
                break
            time.sleep(0.02)
        while chaos.in_blackout():
            time.sleep(0.02)
        t_recover_start = time.perf_counter()
        fenced_mutations = (
            recorder.mutation_count() - mutations_at_fence
            if mutations_at_fence is not None else None
        )
        # recovery: every shard re-owned, no replica fenced
        recover_s = None
        deadline = time.time() + lease_duration * 20
        while time.time() < deadline:
            if (
                all(owner_of(n) is not None for n in names)
                and not any(rt.ownership.fenced() for rt in runtimes)
            ):
                recover_s = time.perf_counter() - t_recover_start
                break
            time.sleep(0.05)
        assert wait_bound(
            create_pods("recovered", n_pods - created), timeout=180
        ), "post-recovery pods never bound"
        fenced_guard_hits = fenced_hits() - fenced_guard_before

        # ---- settle + audits
        all_names = [p.metadata.name for p in backing.pods()]
        wait_bound(all_names, timeout=60)
        pods = list(backing.pods())
        bound = [p for p in pods if p.spec.node_name]
        journal = runtimes[0].journal
        deadline = time.time() + max(gc_interval * 10, 20)
        while time.time() < deadline and journal.unresolved():
            time.sleep(0.1)
        node_names = {n.metadata.name for n in backing.nodes()}
        provider_ids = {n.spec.provider_id for n in backing.nodes()}
        live = [i for i in api.list_instances() if i.state != "terminated"]
        leaked = [
            i for i in live
            if i.id not in node_names
            and f"sim:///{i.zone}/{i.id}" not in provider_ids
        ]
        token_counts = {}
        for inst in live:
            if inst.launch_token:
                token_counts[inst.launch_token] = (
                    token_counts.get(inst.launch_token, 0) + 1
                )
        dup_tokens = {t: c for t, c in token_counts.items() if c > 1}

        recover_bar_s = lease_duration * 4
        return {
            "pods": created,
            "provisioners": n_provisioners,
            "replicas": n_replicas,
            "lease_duration_s": lease_duration,
            "chaos_provision_success_rate": round(len(bound) / max(created, 1), 4),
            "duplicate_launches": len(rebinds) + len(dup_tokens),
            "duplicate_rebinds": rebinds[:5],
            "leaked_instances": len(leaked),
            "blip_s": round(blip.end - blip.start, 3),
            "blip_rebalances": int(blip_rebalances),
            "blip_shard_losses": int(blip_losses),
            "brownout_throttles": int(brownout_throttles),
            "kube_retries_total": int(sample("karpenter_kube_request_retries_total")),
            "blackout_s": round(window.end - window.start, 3),
            "all_replicas_fenced": all_fenced_at is not None,
            "fenced_within_s": (
                round(all_fenced_at, 3) if all_fenced_at is not None else None
            ),
            "fenced_mutations": fenced_mutations,
            "fenced_guard_hits": int(fenced_guard_hits),
            "recover_s": round(recover_s, 3) if recover_s is not None else None,
            "recover_bar_s": round(recover_bar_s, 3),
            "recovered_within_bar": (
                recover_s is not None and recover_s <= recover_bar_s
            ),
            "events_dropped": int(sample("karpenter_kube_events_dropped_total")),
            "journal_unresolved_after": len(journal.unresolved()),
            "wall_s": round(time.perf_counter() - t_start, 2),
        }
    finally:
        env.chaos = None
        for rt in runtimes:
            rt.stop()
        env.stop()
        try:
            os.remove(journal_path)
        except OSError:
            pass


def bench_corruption_storm(
    n_pods: int = 200,
    pool_size: int = 2,
    corrupt_rate: float = 0.05,
    canary_rate: float = 0.25,
    seed: int = 20260803,
    breaker_cooloff_s: float = 1.5,
):
    """Silent-data-corruption storm (docs/integrity.md): the full runtime
    provisions against a solver sidecar pool whose SERVING member emits
    seeded corrupt frames — one phase per mode (payload bit-flip, frame
    truncation, stale-session replay, NaN injection into the result
    tensors, stale-delta epoch garbling — degrading to a bit flip here
    since this storm runs delta-off; --delta-storm is the delta-on twin)
    at 100% corruption to prove per-mode detection + quarantine
    latency, then a mixed phase at the configured rate. Wire checksums and
    the canary cross-check are ON. Acceptance: corrupt_packs_bound=0 /
    detection_rate=1.0 (no corruption ever reaches a bind — a post-storm
    cluster scan is the judge), quarantine_within_solves <= 5, and
    chaos_provision_success_rate=1.0 via ring failover + the native/FFD
    floor."""
    from karpenter_tpu import metrics as m
    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options
    from karpenter_tpu.solver import integrity
    from karpenter_tpu.testing.chaos import (
        CORRUPTION_MODES,
        ChaosPolicy,
        SidecarChaos,
    )
    from karpenter_tpu.testing.factories import make_pod
    from karpenter_tpu.utils import resources as res

    t_start = time.perf_counter()
    # pin the device path: the cost router would (correctly) route these
    # small batches to native, and a storm that never crosses the wire
    # proves nothing about wire/device corruption defense
    packer_before = os.environ.get("KARPENTER_PACKER")
    os.environ["KARPENTER_PACKER"] = "device"
    integrity.reset()
    sidecars = SidecarChaos(n=pool_size)
    cluster = Cluster()
    rt = build_runtime(
        Options(
            solver_service_address=sidecars.address_spec,
            pack_checksum=True,
            canary_rate=canary_rate,
        ),
        cluster=cluster,
        cloud_provider=SimulatedCloudProvider(api=SimCloudAPI()),
    )
    rt.manager.start()
    created = 0

    def create_pods(prefix: str, n: int) -> list:
        nonlocal created
        names = []
        for i in range(n):
            name = f"{prefix}-{i}"
            names.append(name)
            cluster.create(
                "pods", make_pod(name=name, requests={"cpu": "0.25"})
            )
        created += n
        return names

    def wait_bound(names: list, timeout: float = 120.0) -> None:
        deadline = time.time() + timeout
        want = set(names)
        while time.time() < deadline:
            live = {
                p.metadata.name: p for p in cluster.pods()
                if p.metadata.name in want
            }
            if len(live) == len(want) and all(
                p.spec.node_name for p in live.values()
            ):
                return
            time.sleep(0.05)

    try:
        cluster.create("provisioners", make_provisioner(solver="tpu"))
        deadline = time.time() + 10
        while time.time() < deadline and not rt.provisioning.workers:
            time.sleep(0.02)
        assert rt.provisioning.workers, "provisioner worker never started"
        worker = next(iter(rt.provisioning.workers.values()))
        worker.batcher.idle_duration = 0.1

        # ---- warm: sessions open, compiles done, the ring's serving
        # member identified (consistent-hash: ONE member owns this catalog)
        wait_bound(create_pods("warm", 10))
        victim = sidecars.busiest()
        # shorten the member-breaker cool-off so four quarantine/recovery
        # cycles fit a CI-sized run (test-harness reach-in, not a knob)
        sched = worker.scheduler._tpu
        assert sched is not None, "TPU scheduler never engaged"
        pool = sched._remote_or_init()
        pool._breakers._kwargs["open_seconds"] = breaker_cooloff_s
        for b in pool._breakers._breakers.values():
            b.open_seconds = breaker_cooloff_s
        # restart the serving member behind a chaos proxy (same address —
        # the ring still routes to it); its sessions drop, the client
        # re-opens transparently through NEEDS_CATALOG
        sidecars.restart(victim, policy=ChaosPolicy(seed=seed))
        proxy = sidecars.proxies[victim]
        wait_bound(create_pods("rewarm", 6))

        # ---- one phase per corruption mode at 100% injection
        n_phase = max(n_pods // 8, 10)
        per_mode = {}
        for i, mode in enumerate(CORRUPTION_MODES):
            q0 = integrity.totals().get("quarantines", 0)
            calls0 = proxy.calls_total("solve_bytes")
            injected0 = proxy.corrupted_total()
            proxy.policy = ChaosPolicy(
                corrupt_rate=1.0, corruption_modes=(mode,),
                methods=frozenset({"solve_bytes"}), seed=seed + i,
            )
            names = create_pods(f"storm-{mode}", n_phase)
            quarantine_deadline = time.time() + 60
            calls_at_quarantine = None
            while time.time() < quarantine_deadline:
                if integrity.totals().get("quarantines", 0) > q0:
                    calls_at_quarantine = proxy.calls_total("solve_bytes")
                    break
                time.sleep(0.01)
            # stop corrupting so the phase settles and the member recovers
            # through its half-open probe before the next phase
            proxy.policy = ChaosPolicy(seed=seed)
            wait_bound(names)
            per_mode[mode] = {
                "injected": proxy.corrupted_total() - injected0,
                "quarantined": calls_at_quarantine is not None,
                "quarantine_within_solves": (
                    max(calls_at_quarantine - calls0, 1)
                    if calls_at_quarantine is not None else None
                ),
            }
            time.sleep(breaker_cooloff_s + 0.3)  # half-open re-admission

        # ---- mixed phase at the configured rate, all four modes
        proxy.policy = ChaosPolicy(
            corrupt_rate=max(corrupt_rate, 0.01),
            corruption_modes=CORRUPTION_MODES,
            methods=frozenset({"solve_bytes"}), seed=seed + 99,
        )
        wait_bound(create_pods("mixed", max(n_pods - created, 20)), timeout=180)
        proxy.policy = ChaosPolicy(seed=seed)

        # ---- settle, then judge: did ANY corrupt pack reach a bind?
        all_names = [p.metadata.name for p in cluster.pods()]
        wait_bound(all_names, timeout=60)
        pods = list(cluster.pods())
        bound = [p for p in pods if p.spec.node_name]
        node_names = {n.metadata.name for n in cluster.nodes()}
        anomalies = []
        by_node: dict = {}
        for p in bound:
            reqs = res.requests_for_pods(p)
            if any(not math.isfinite(v) for v in reqs.values()):
                anomalies.append(f"pod {p.metadata.name}: non-finite requests")
            if p.spec.node_name not in node_names:
                anomalies.append(
                    f"pod {p.metadata.name}: bound to missing node "
                    f"{p.spec.node_name}"
                )
            by_node.setdefault(p.spec.node_name, []).append(p)
        for node in cluster.nodes():
            members = by_node.get(node.metadata.name, [])
            if not members or not node.status.allocatable:
                continue
            totals = res.merge(*[res.requests_for_pods(p) for p in members])
            if not res.fits(totals, node.status.allocatable):
                anomalies.append(
                    f"node {node.metadata.name}: oversubscribed "
                    f"({res.to_string(totals)})"
                )
        totals = integrity.totals()
        injected = proxy.corrupted_total()
        corrupt_packs_bound = len(anomalies)
        quarantine_within = [
            m["quarantine_within_solves"] for m in per_mode.values()
            if m["quarantine_within_solves"] is not None
        ]
        return {
            "pods": created,
            "pool_size": pool_size,
            "corrupt_member": victim,
            "corrupt_rate_mixed_phase": max(corrupt_rate, 0.01),
            "canary_rate": canary_rate,
            "pack_checksum": True,
            "seed": seed,
            "injected_corruptions": injected,
            "injected_by_mode": dict(sorted(proxy.corrupted.items())),
            "per_mode": per_mode,
            "corrupt_packs_bound": corrupt_packs_bound,
            "bind_anomalies": anomalies[:5],
            "detection_rate": (
                round((injected - corrupt_packs_bound) / injected, 4)
                if injected else None
            ),
            "quarantine_within_solves": (
                max(quarantine_within) if quarantine_within else None
            ),
            "all_modes_quarantined": all(
                m["quarantined"] for m in per_mode.values()
            ),
            "chaos_provision_success_rate": round(
                len(bound) / max(created, 1), 4
            ),
            "integrity_counters": totals,
            "canary_solves": totals.get("canary_solves", 0),
            "pool_failovers_total": _sample(
                m, "karpenter_solver_pool_failovers_total"
            ),
            "wall_s": round(time.perf_counter() - t_start, 2),
        }
    finally:
        if packer_before is None:
            os.environ.pop("KARPENTER_PACKER", None)
        else:
            os.environ["KARPENTER_PACKER"] = packer_before
        rt.stop()
        sidecars.stop_all()


def bench_delta_storm(
    n_pods: int = 240,
    pool_size: int = 2,
    corrupt_rate: float = 0.5,
    seed: int = 20260807,
):
    """Delta-residency chaos leg (docs/delta-encoding.md): the full
    runtime provisions with resident delta encoding ON against a chaos
    sidecar pool. Three phases: (1) steady pod churn — elide/patch deltas
    flow across the wire; (2) stale_delta injection — checksum-VALID
    requests whose epoch words lie, the wire shape of an out-of-order or
    dropped delta, refused by the sidecar's digest recompute and healed
    by counted full re-establishes; (3) a mid-round sidecar restart —
    empty pod store, the NEEDS_DELTA_BASE/NEEDS_CATALOG ladder re-pins.
    Acceptance: zero stale-tensor binds (the corruption-storm post-run
    cluster scan), delta_epoch_mismatches > 0 with every one healed
    (full re-encodes COUNTED, never silent), provision success rate
    1.0."""
    from karpenter_tpu import metrics as m
    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options
    from karpenter_tpu.solver import integrity
    from karpenter_tpu.testing.chaos import ChaosPolicy, SidecarChaos
    from karpenter_tpu.testing.factories import make_pod
    from karpenter_tpu.utils import resources as res

    t_start = time.perf_counter()
    # pin the device path: these small batches would route native, and a
    # storm that never ships a delta frame proves nothing about the guard
    packer_before = os.environ.get("KARPENTER_PACKER")
    os.environ["KARPENTER_PACKER"] = "device"
    integrity.reset()

    def sample(name: str) -> float:
        return _sample(m, name)

    mm0 = sample("karpenter_solver_delta_epoch_mismatches_total")
    fr0 = sample("karpenter_solver_delta_full_reencodes_total")
    ap0 = sample("karpenter_solver_delta_applied_total")
    sidecars = SidecarChaos(n=pool_size)
    cluster = Cluster()
    rt = build_runtime(
        Options(
            solver_service_address=sidecars.address_spec,
            pack_checksum=True,
            solver_delta=True,
        ),
        cluster=cluster,
        cloud_provider=SimulatedCloudProvider(api=SimCloudAPI()),
    )
    rt.manager.start()
    created = 0

    def create_pods(prefix: str, n: int) -> list:
        nonlocal created
        names = []
        for i in range(n):
            name = f"{prefix}-{i}"
            names.append(name)
            cluster.create(
                "pods", make_pod(name=name, requests={"cpu": "0.25"})
            )
        created += n
        return names

    def wait_bound(names: list, timeout: float = 120.0) -> None:
        deadline = time.time() + timeout
        want = set(names)
        while time.time() < deadline:
            live = {
                p.metadata.name: p for p in cluster.pods()
                if p.metadata.name in want
            }
            if len(live) == len(want) and all(
                p.spec.node_name for p in live.values()
            ):
                return
            time.sleep(0.05)

    try:
        cluster.create("provisioners", make_provisioner(solver="tpu"))
        deadline = time.time() + 10
        while time.time() < deadline and not rt.provisioning.workers:
            time.sleep(0.02)
        assert rt.provisioning.workers, "provisioner worker never started"
        worker = next(iter(rt.provisioning.workers.values()))
        worker.batcher.idle_duration = 0.1

        # ---- phase 1: steady churn — deltas flow, no chaos yet
        n_phase = max(n_pods // 4, 10)
        wait_bound(create_pods("churn-a", n_phase))
        wait_bound(create_pods("churn-b", n_phase))
        applied_steady = sample(
            "karpenter_solver_delta_applied_total"
        ) - ap0
        victim = sidecars.busiest()

        # ---- phase 2: stale_delta injection on the serving member —
        # every refused frame must heal into a counted re-establish, and
        # NO refused (or garbled-but-accepted) frame may produce a bind
        # computed from stale resident tensors
        sidecars.restart(victim, policy=ChaosPolicy(seed=seed))
        proxy = sidecars.proxies[victim]
        wait_bound(create_pods("repin", 6))
        proxy.policy = ChaosPolicy(
            corrupt_rate=corrupt_rate, corruption_modes=("stale_delta",),
            methods=frozenset({"solve_bytes"}), seed=seed + 1,
        )
        wait_bound(create_pods("stale", n_phase), timeout=180)
        proxy.policy = ChaosPolicy(seed=seed)
        injected = proxy.corrupted_total()

        # ---- phase 3: mid-round sidecar restart — resident base AND
        # session store gone; the recovery ladder re-establishes
        sidecars.restart(victim, policy=ChaosPolicy(seed=seed + 2))
        wait_bound(create_pods("restart", max(n_pods - created, 10)),
                   timeout=180)

        # ---- settle, then judge with the corruption-storm bind scan:
        # a stale-tensor bind surfaces as an oversubscribed node or a
        # bind against state that never existed
        all_names = [p.metadata.name for p in cluster.pods()]
        wait_bound(all_names, timeout=60)
        pods = list(cluster.pods())
        bound = [p for p in pods if p.spec.node_name]
        node_names = {n.metadata.name for n in cluster.nodes()}
        anomalies = []
        by_node: dict = {}
        for p in bound:
            reqs = res.requests_for_pods(p)
            if any(not math.isfinite(v) for v in reqs.values()):
                anomalies.append(f"pod {p.metadata.name}: non-finite requests")
            if p.spec.node_name not in node_names:
                anomalies.append(
                    f"pod {p.metadata.name}: bound to missing node "
                    f"{p.spec.node_name}"
                )
            by_node.setdefault(p.spec.node_name, []).append(p)
        for node in cluster.nodes():
            members = by_node.get(node.metadata.name, [])
            if not members or not node.status.allocatable:
                continue
            totals = res.merge(*[res.requests_for_pods(p) for p in members])
            if not res.fits(totals, node.status.allocatable):
                anomalies.append(
                    f"node {node.metadata.name}: oversubscribed "
                    f"({res.to_string(totals)})"
                )
        mismatches = sample(
            "karpenter_solver_delta_epoch_mismatches_total"
        ) - mm0
        reencodes = sample(
            "karpenter_solver_delta_full_reencodes_total"
        ) - fr0
        applied = sample("karpenter_solver_delta_applied_total") - ap0
        return {
            "pods": created,
            "pool_size": pool_size,
            "corrupt_member": victim,
            "stale_delta_rate": corrupt_rate,
            "seed": seed,
            "injected_stale_deltas": injected,
            "delta_applied": int(applied),
            "delta_applied_steady_phase": int(applied_steady),
            "delta_epoch_mismatches": int(mismatches),
            "delta_full_reencodes": int(reencodes),
            "stale_tensor_binds": len(anomalies),
            "bind_anomalies": anomalies[:5],
            "delta_provision_success_rate": round(
                len(bound) / max(created, 1), 4
            ),
            "wall_s": round(time.perf_counter() - t_start, 2),
        }
    finally:
        if packer_before is None:
            os.environ.pop("KARPENTER_PACKER", None)
        else:
            os.environ["KARPENTER_PACKER"] = packer_before
        rt.stop()
        sidecars.stop_all()


def bench_regression_storm(
    n_pods: int = 160,
    pool_size: int = 2,
    latency_step_s: float = 0.05,
    seed: int = 20260807,
):
    """Regression-sentinel storm (docs/observability.md): the full
    runtime provisions identical waves against a sidecar pool while the
    sentinel learns per-(stage, route, shape) baselines online. Phase 1
    (steady): the detector must stay silent — false-positive bar: ZERO
    incidents. Phase 2 (step): every pool member's chaos proxy gains a
    deterministic latency floor, the wire shape of a sustained 2x+
    regression; the sentinel must open exactly ONE correlated incident
    (correlated stages, not a siren) naming a wire/device stage, carrying
    >=1 pinned flight record, >=1 in-window decision id, and the
    profiler's in-window folds. Gate: self-accounted sentinel overhead
    <1% of wall."""
    import shutil
    import tempfile

    from karpenter_tpu import obs
    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options
    from karpenter_tpu.testing.chaos import ChaosPolicy, SidecarChaos
    from karpenter_tpu.testing.factories import make_pod

    t_start = time.perf_counter()
    wire_device_stages = {
        "solver.wire", "solver.solve", "sidecar.pack", "solve.pack_fetch",
    }
    # pin the device path: these small waves would route native and never
    # touch the wire the latency step is injected on
    packer_before = os.environ.get("KARPENTER_PACKER")
    os.environ["KARPENTER_PACKER"] = "device"
    sidecars = SidecarChaos(
        n=pool_size,
        policies={i: ChaosPolicy(seed=seed + i) for i in range(pool_size)},
    )
    flight_dir = tempfile.mkdtemp(prefix="karpenter-sentinel-flight-")
    obs.configure_flight(flight_dir, budget_s=10.0)
    prof = obs.configure_profiler(hz=19.0)
    eng = None
    cluster = Cluster()
    rt = build_runtime(
        Options(solver_service_address=sidecars.address_spec),
        cluster=cluster,
        cloud_provider=SimulatedCloudProvider(api=SimCloudAPI()),
    )
    rt.manager.start()
    created = 0

    def create_wave(prefix: str, n: int) -> list:
        nonlocal created
        names = []
        for i in range(n):
            name = f"{prefix}-{i}"
            names.append(name)
            cluster.create(
                "pods", make_pod(name=name, requests={"cpu": "0.25"})
            )
        created += n
        return names

    def wait_bound(names: list, timeout: float = 120.0) -> None:
        deadline = time.time() + timeout
        want = set(names)
        while time.time() < deadline:
            live = {
                p.metadata.name: p for p in cluster.pods()
                if p.metadata.name in want
            }
            if len(live) == len(want) and all(
                p.spec.node_name for p in live.values()
            ):
                return
            time.sleep(0.05)

    try:
        cluster.create("provisioners", make_provisioner(solver="tpu"))
        deadline = time.time() + 10
        while time.time() < deadline and not rt.provisioning.workers:
            time.sleep(0.02)
        assert rt.provisioning.workers, "provisioner worker never started"
        worker = next(iter(rt.provisioning.workers.values()))
        worker.batcher.idle_duration = 0.1

        # ---- warm the device path BEFORE the sentinel starts learning:
        # the first solve's JIT compile is a seconds-scale outlier that
        # would poison a freshly-minted baseline's variance (threshold
        # balloons past any realistic step) — warming first is the same
        # discipline every other bench leg applies before measuring
        wave_size = 4
        for w in range(3):
            wait_bound(create_wave(f"warm-{w}", wave_size))
        # bench-scale knobs: waves are seconds apart, not minutes — warm
        # in 6 events, judge 4-wide windows, trip on 2 sustained
        # deviations; the 5ms abs floor keeps loopback jitter out of the
        # steady phase while a 50ms injected step clears it by 10x
        eng = obs.configure_sentinel(
            min_events=6, window=4, sustain=2,
            abs_floor_s=0.005, cooldown_s=300.0,
        )

        # ---- phase 1: steady identical waves — baselines warm, and the
        # detector must not trip on its own learning traffic
        steady_waves = max((n_pods // 2) // wave_size, 12)
        for w in range(steady_waves):
            wait_bound(create_wave(f"steady-{w}", wave_size))
        steady_false_positives = eng.incidents.count()
        baselines_learned = eng.baseline_count()

        # ---- phase 2: a sustained latency step on every pool member's
        # wire — retargeting the live proxies (no restart: the step must
        # be pure latency, not a session-loss recovery ladder)
        for proxy in sidecars.proxies.values():
            proxy.policy = ChaosPolicy(
                latency_floor=latency_step_s, seed=seed,
            )
        step_waves = 0
        max_step_waves = max((n_pods // 2) // wave_size, 15)
        for w in range(max_step_waves):
            wait_bound(create_wave(f"step-{w}", wave_size), timeout=180)
            step_waves += 1
            # a few extra waves past first detection let the other
            # deviating stages attach to the SAME correlated incident
            if eng.incidents.count() > 0 and step_waves >= 6:
                break

        incidents = eng.incidents.recent()
        stages: list = []
        flights = decisions = folds = 0
        if incidents:
            rec = incidents[0]
            stages = sorted({s["stage"] for s in rec["stages"]})
            flights = len(rec["flights"])
            decisions = len(rec["decisions"])
            folds = len(rec["profile_top"])
        overhead_pct = eng.overhead_ratio() * 100
        detected = len(incidents) == 1
        attributed = bool(set(stages) & wire_device_stages)
        evidence_ok = flights >= 1 and decisions >= 1 and folds >= 1
        return {
            "pods": created,
            "pool_size": pool_size,
            "latency_step_s": latency_step_s,
            "seed": seed,
            "steady_waves": steady_waves,
            "step_waves": step_waves,
            "baselines_learned": baselines_learned,
            "steady_false_positives": steady_false_positives,
            "incidents_opened": len(incidents),
            "step_detected": detected,
            "incident_stages": stages,
            "step_attributed_wire_device": attributed,
            "incident_flight_records": flights,
            "incident_decision_ids": decisions,
            "incident_profile_folds": folds,
            "incident_evidence_complete": evidence_ok,
            "sentinel_overhead_pct": round(overhead_pct, 4),
            "sentinel_overhead_ok": overhead_pct < 1.0,
            "wall_s": round(time.perf_counter() - t_start, 2),
        }
    finally:
        if packer_before is None:
            os.environ.pop("KARPENTER_PACKER", None)
        else:
            os.environ["KARPENTER_PACKER"] = packer_before
        rt.stop()
        sidecars.stop_all()
        if eng is not None:
            obs.shutdown_sentinel(eng)
        obs.shutdown_profiler(prof)
        shutil.rmtree(flight_dir, ignore_errors=True)


def bench_crash_storm(
    n_pods: int = 200,
    n_provisioners: int = 4,
    n_replicas: int = 3,
    lease_duration: float = 1.5,
    renew_interval: float = 0.3,
    gc_interval: float = 1.0,
    solver: str = "ffd",
):
    """Crash-consistency storm (docs/launch-journal.md): N controller
    replicas share one cluster, one shard-lease file, and one write-ahead
    launch-journal file. Mid-storm one replica is killed BETWEEN the cloud
    create and the Node write (the orphan the GC sweep must ADOPT), then a
    second replica is killed BETWEEN the Node write and the bind (recovery
    must confirm the Node already tracks the instance). The leg reports
    the acceptance numbers: leaked_instances (bar: 0), duplicate_launches
    (bar: 0), adoption latency vs the one-GC-period bar, and
    chaos_provision_success_rate (bar: 1.0)."""
    import tempfile
    import threading

    from karpenter_tpu import metrics as m
    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options
    from karpenter_tpu.testing.chaos import LaunchCrashCluster, ReplicaChaos
    from karpenter_tpu.testing.factories import make_pod
    from karpenter_tpu.api.objects import NodeSelectorRequirement

    t_start = time.perf_counter()
    lease_path = tempfile.mktemp(prefix="karpenter-crash-lease-")
    journal_path = tempfile.mktemp(prefix="karpenter-crash-journal-")
    cluster = Cluster()
    api = SimCloudAPI()
    fleet = ReplicaChaos()
    crash_clusters = {}

    adopted_before = _sample(m, "karpenter_launch_orphans_adopted_total")
    leaked_before = _sample(m, "karpenter_launch_instances_leaked_total")

    # duplicate-launch detector #1: a pod whose nodeName flips between two
    # non-empty values was double-provisioned (no preemption in this leg)
    rebinds = []
    last_node = {}
    watch_mu = threading.Lock()

    def on_pod(event, pod):
        if event == "DELETED" or not pod.spec.node_name:
            return
        with watch_mu:
            prev = last_node.get(pod.metadata.name)
            if prev and prev != pod.spec.node_name:
                rebinds.append((pod.metadata.name, prev, pod.spec.node_name))
            last_node[pod.metadata.name] = pod.spec.node_name

    cluster.watch("pods", on_pod)

    opts = dict(
        shard_lease=lease_path,
        shard_lease_duration=lease_duration,
        launch_journal=journal_path,
        gc_interval=gc_interval,
        gc_grace_period=max(gc_interval * 4, 4.0),
        default_solver=solver,
    )
    try:
        for i in range(n_replicas):
            # each replica launches through its OWN crash proxy over the
            # shared cluster, so the scenario can kill exactly one mid-write
            proxy = LaunchCrashCluster(cluster)
            crash_clusters[f"replica-{i}"] = proxy
            rt = build_runtime(
                Options(**opts),
                cluster=proxy,
                cloud_provider=SimulatedCloudProvider(api=api),
                shard_identity=f"replica-{i}",
            )
            rt.ownership.renew_interval = renew_interval
            # the adoption bar is measured against eligibility: an entry
            # must age replay_after before the sweep touches it
            rt.garbage_collection.replay_after = gc_interval
            rt.ownership.start()
            rt.manager.start()
            fleet.add(f"replica-{i}", rt)

        names = [f"crash-{i}" for i in range(n_provisioners)]
        for name in names:
            cluster.create("provisioners", make_provisioner(
                name=name, solver=solver,
                requirements=[NodeSelectorRequirement(
                    key="crashfleet", operator="In", values=[name],
                )],
            ))

        deadline = time.time() + 30
        while time.time() < deadline:
            owners = {name: fleet.owner_named(name) for name in names}
            if all(
                rt is not None and name in rt.provisioning.workers
                for name, (_, rt) in owners.items()
            ):
                break
            time.sleep(0.05)
        assert all(fleet.owner_named(n)[0] for n in names), "shards never all owned"
        for rt in fleet.replicas.values():
            for w in rt.provisioning.workers.values():
                w.batcher.idle_duration = 0.1

        instances_before = len(api.list_instances())

        def crash_phase(point: str, shard: str, first_pod: int, count: int):
            """Arm ``point`` on the owner of ``shard``, drive pods at it,
            kill the owner the moment the crash fires. Returns the kill
            timestamp (perf_counter) and the victim's crash proxy."""
            victim = None
            deadline = time.time() + lease_duration * 10
            while time.time() < deadline:
                victim, _ = fleet.owner_named(shard)
                if victim is not None:
                    break
                time.sleep(0.05)  # a prior phase's rebalance still settling
            assert victim is not None, f"no live owner for {shard}"
            proxy = crash_clusters[victim]
            proxy.arm(point)
            for i in range(first_pod, first_pod + count):
                cluster.create("pods", make_pod(
                    name=f"storm-{i}", requests={"cpu": "0.25"},
                    node_selector={
                        "crashfleet": f"crash-{i % n_provisioners}",
                    },
                ))
            if not proxy.crashed.wait(timeout=60):
                raise AssertionError(
                    f"crash point {point} never fired on {victim}"
                )
            t_kill = time.perf_counter()
            fleet.kill(victim)
            return t_kill, proxy

        half = n_pods // 2
        # phase 1: die between the cloud create and the Node write — the
        # instance exists, tokened and journaled, and nothing tracks it
        t_kill_1, proxy_1 = crash_phase("before_node_write", "crash-0", 0, half)
        # the orphan, identified by the interrupted write itself (the node
        # is named after its instance): scanning the provider for "newest
        # untracked instance" would race a survivor's healthy in-flight
        # launch and could measure an ordinary Node write as the adoption
        orphan_id = proxy_1.crash_nodes.get("before_node_write")

        # wait for a survivor's GC sweep to adopt it (Node written)
        adoption_s = None
        if orphan_id:
            deadline = time.time() + max(gc_interval * 10, 30)
            while time.time() < deadline:
                if cluster.try_get("nodes", orphan_id, namespace="") is not None:
                    adoption_s = time.perf_counter() - t_kill_1
                    break
                time.sleep(0.05)

        # phase 2: die between the Node write and the bind — the Node
        # already tracks the instance; recovery resolves, pods re-enter
        crash_phase("after_node_write", "crash-1", half, n_pods - half)

        # settle: every storm pod bound by the survivors
        deadline = time.time() + 240
        while time.time() < deadline:
            pods = [p for p in cluster.pods() if p.metadata.name.startswith("storm-")]
            if len(pods) == n_pods and all(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        pods = [p for p in cluster.pods() if p.metadata.name.startswith("storm-")]
        bound = [p for p in pods if p.spec.node_name]

        # let the journal drain (replays resolve confirmed entries)
        journal = fleet.replicas[next(iter(fleet.replicas))].journal
        deadline = time.time() + max(gc_interval * 10, 30)
        while time.time() < deadline and journal.unresolved():
            time.sleep(0.1)

        # leak audit: every live instance must be tracked by a Node
        node_names = {n.metadata.name for n in cluster.nodes()}
        provider_ids = {n.spec.provider_id for n in cluster.nodes()}
        live = [i for i in api.list_instances() if i.state != "terminated"]
        leaked = [
            i for i in live
            if i.id not in node_names
            and f"sim:///{i.zone}/{i.id}" not in provider_ids
        ]
        # duplicate-launch detector #2: one launch token, one instance
        token_counts = {}
        for inst in live:
            if inst.launch_token:
                token_counts[inst.launch_token] = (
                    token_counts.get(inst.launch_token, 0) + 1
                )
        dup_tokens = {t: c for t, c in token_counts.items() if c > 1}

        adoption_bar_s = gc_interval * 2  # age-in (replay_after) + one sweep
        return {
            "pods": n_pods,
            "provisioners": n_provisioners,
            "replicas": n_replicas,
            "solver": solver,
            "lease_duration_s": lease_duration,
            "gc_interval_s": gc_interval,
            "chaos_provision_success_rate": round(len(bound) / max(n_pods, 1), 4),
            "crashes_fired": {
                name: dict(proxy.crashes)
                for name, proxy in crash_clusters.items() if proxy.crashes
            },
            "leaked_instances": len(leaked),
            "duplicate_launches": len(rebinds) + len(dup_tokens),
            "duplicate_rebinds": rebinds[:5],
            "duplicate_tokens": list(dup_tokens)[:5],
            "orphans_adopted": int(
                _sample(m, "karpenter_launch_orphans_adopted_total") - adopted_before
            ),
            "leaks_terminated": int(
                _sample(m, "karpenter_launch_instances_leaked_total") - leaked_before
            ),
            "adoption_s": round(adoption_s, 3) if adoption_s is not None else None,
            "adoption_bar_s": round(adoption_bar_s + 1.0, 3),
            "adopted_within_gc_period": (
                adoption_s is not None and adoption_s <= adoption_bar_s + 1.0
            ),
            "journal_unresolved_after": len(journal.unresolved()),
            "instances_launched": len(api.list_instances()) - instances_before,
            "wall_s": round(time.perf_counter() - t_start, 2),
        }
    finally:
        fleet.stop_all()
        for path in (lease_path, journal_path):
            try:
                os.remove(path)
            except OSError:
                pass


def _sample(m, name: str) -> float:
    """Sum a metric family's samples from the process registry."""
    total = 0.0
    for metric in m.REGISTRY.collect():
        for s in metric.samples:
            if s.name == name:
                total += s.value
    return total


def bench_consolidation_storm(
    n_pods: int = 48,
    n_provisioners: int = 2,
    n_replicas: int = 3,
    lease_duration: float = 1.5,
    renew_interval: float = 0.3,
    gc_interval: float = 1.0,
    replay_after: float = 12.0,
    budget: str = "2",
    wave_size: int = 3,
    error_rate: float = 0.05,
    seed: int = 20260807,
    solver: str = "ffd",
):
    """Disruption-safe consolidation storm (docs/consolidation.md): N
    replicas over one cluster run budgeted, journaled re-pack waves at
    ~70% utilization while pods churn, the cloud API injects seeded
    errors, and one replica is killed MID-WAVE (first victim cordoned,
    nothing else done — the exact window the journal entry exists for).
    Bars: zero evicted-unready pods, zero budget violations (never more
    than ``budget`` concurrently-disrupted nodes per provisioner), zero
    leaked/duplicate instances, the crashed wave replayed by a survivor
    (victim un-cordoned, entry resolved), and every surviving pod bound
    at the end. Reports the headline pair: consolidation_nodes_reclaimed
    and consolidation_cost_delta_usd (negative = cheaper cluster)."""
    import tempfile
    import threading

    from karpenter_tpu import metrics as m
    from karpenter_tpu.api import labels as lbl
    from karpenter_tpu.api.objects import (
        NodeSelectorRequirement,
        OwnerReference,
        PodCondition,
    )
    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
    from karpenter_tpu.interruption.types import DisruptionNotice
    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options
    from karpenter_tpu.testing.chaos import (
        ChaosPolicy,
        LaunchCrash,
        ReplicaChaos,
        chaos_wrap,
    )
    from karpenter_tpu.testing.factories import make_pod

    t_start = time.perf_counter()
    lease_path = tempfile.mktemp(prefix="karpenter-cons-lease-")
    journal_path = tempfile.mktemp(prefix="karpenter-cons-journal-")
    cluster = Cluster()
    api = SimCloudAPI()
    # the replicas see the misbehaving control plane; the audit below reads
    # the RAW double, so injected describe errors can't fake a leak
    proxy = chaos_wrap(api, ChaosPolicy(error_rate=error_rate, seed=seed))
    fleet = ReplicaChaos()
    budget_allowed = int(budget)

    evicted_before = _sample(m, "karpenter_consolidation_evicted_unready_total")
    blocked_before = _sample(m, "karpenter_consolidation_budget_blocked_total")
    waves_before = _sample(m, "karpenter_consolidation_waves_total")
    reclaimed_before = _sample(m, "karpenter_consolidation_reclaimed_nodes_total")

    opts = dict(
        shard_lease=lease_path,
        shard_lease_duration=lease_duration,
        launch_journal=journal_path,
        gc_interval=gc_interval,
        gc_grace_period=max(gc_interval * 4, 4.0),
        default_solver=solver,
        consolidation_wave_size=wave_size,
        consolidation_budget=budget,
    )
    names = [f"cons-{i}" for i in range(n_provisioners)]
    owner_ref = OwnerReference(api_version="apps/v1", kind="ReplicaSet", name="storm-rs")
    churn_stop = threading.Event()
    churn_failures = []

    # no kubelet in this substrate: a background "kubelet" marks launched
    # nodes Ready, because candidacy (and the done-bar itself) is defined
    # over READY capacity only
    kubelet_stop = threading.Event()

    def kubelet():
        while not kubelet_stop.is_set():
            for node in cluster.nodes():
                if not any(
                    c.type == "Ready" and c.status == "True"
                    for c in node.status.conditions
                ):
                    node.status.conditions.append(
                        PodCondition(type="Ready", status="True")
                    )
            time.sleep(0.05)

    def enqueue_all():
        for rt in list(fleet.replicas.values()):
            for name in names:
                try:
                    rt.manager.enqueue("consolidation", name)
                except Exception:
                    pass  # a replica mid-kill

    try:
        for i in range(n_replicas):
            rt = build_runtime(
                Options(**opts),
                cluster=cluster,
                cloud_provider=SimulatedCloudProvider(api=proxy),
                consolidation_enabled=True,
                shard_identity=f"replica-{i}",
            )
            rt.ownership.renew_interval = renew_interval
            rt.garbage_collection.replay_after = replay_after
            # the shared store is in-memory, but the storm exercises the
            # REAL (apiserver) migration mode: taint→replace→drain per
            # victim, workload controllers notionally recreating
            rt.consolidation.migration = "evict"
            rt.ownership.start()
            rt.manager.start()
            fleet.add(f"replica-{i}", rt)

        threading.Thread(target=kubelet, daemon=True).start()

        for name in names:
            cluster.create("provisioners", make_provisioner(
                name=name, solver=solver,
                requirements=[
                    NodeSelectorRequirement(
                        key="consfleet", operator="In", values=[name],
                    ),
                    # pin the fleet to one small shape so the storm builds
                    # a MANY-node world (4 pods per gp-2x) — re-packing one
                    # huge node would trivialize budgets and wave pacing
                    NodeSelectorRequirement(
                        key=lbl.INSTANCE_TYPE, operator="In",
                        values=["sim.gp-2x"],
                    ),
                ],
            ))

        deadline = time.time() + 30
        while time.time() < deadline:
            owners = {name: fleet.owner_named(name) for name in names}
            if all(
                rt is not None and name in rt.provisioning.workers
                for name, (_, rt) in owners.items()
            ):
                break
            time.sleep(0.05)
        assert all(fleet.owner_named(n)[0] for n in names), "shards never all owned"
        for rt in fleet.replicas.values():
            for w in rt.provisioning.workers.values():
                w.batcher.idle_duration = 0.1

        # phase A: build the running world — 4 pods per gp-2x with a
        # sliver of headroom left, so churn pods SEAT on live capacity
        # instead of minting one-pod nodes (which would turn the churn
        # into a perpetual empty-node consolidation treadmill)
        for i in range(n_pods):
            cluster.create("pods", make_pod(
                name=f"cons-pod-{i}", requests={"cpu": "0.4"},
                node_selector={"consfleet": names[i % n_provisioners]},
                owner=owner_ref,
            ))
        deadline = time.time() + 120
        while time.time() < deadline:
            pods = [p for p in cluster.pods() if p.metadata.name.startswith("cons-pod-")]
            if len(pods) == n_pods and all(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        assert all(
            p.spec.node_name for p in cluster.pods()
            if p.metadata.name.startswith("cons-pod-")
        ), "storm pods never all bound"

        # phase B: fragment to ~70% utilization — every third pod leaves,
        # stranding capacity the re-pack exists to hand back
        for i in range(0, n_pods, 3):
            cluster.delete("pods", f"cons-pod-{i}", namespace="default")
        survivors = {
            p.metadata.name for p in cluster.pods()
            if p.metadata.name.startswith("cons-pod-")
        }
        price_by_type = {
            it.name: it.effective_price()
            for it in SimulatedCloudProvider(api=api).get_instance_types(None)
        }

        def cluster_price():
            return sum(
                price_by_type.get(n.metadata.labels.get(lbl.INSTANCE_TYPE, ""), 0.0)
                for n in cluster.nodes()
            )

        nodes_before_storm = len(cluster.nodes())
        price_before_storm = cluster_price()

        # phase C: kill the owner of cons-0 MID-WAVE — after the wave is
        # journaled and its first victim cordoned, before anything drains
        class _CrashAfterCordon:
            """Orchestrator proxy: the first consolidate() cordons the
            victim (the wave's first real write), then dies like a SIGKILL
            — a BaseException, so the worker thread is gone, not requeued."""

            def __init__(self, real):
                self.real = real
                self.fired = threading.Event()
                self.crash_node = ""

            def consolidate(self, node, decision_id="", on_release=None):
                if not self.fired.is_set():
                    self.real._taint_and_cordon(node, DisruptionNotice(
                        kind="consolidation", node_name=node.metadata.name,
                        grace_period_seconds=0.0,
                    ))
                    self.crash_node = node.metadata.name
                    self.fired.set()
                    raise LaunchCrash(
                        f"simulated crash mid-consolidation-wave "
                        f"({node.metadata.name})"
                    )
                return self.real.consolidate(
                    node, decision_id=decision_id, on_release=on_release
                )

            def __getattr__(self, name):
                return getattr(self.real, name)

        victim_name, victim_rt = fleet.owner_named(names[0])
        assert victim_rt is not None
        crasher = _CrashAfterCordon(victim_rt.consolidation.orchestrator)
        victim_rt.consolidation.orchestrator = crasher
        victim_rt.manager.enqueue("consolidation", names[0])
        if not crasher.fired.wait(timeout=60):
            raise AssertionError("mid-wave crash never fired")
        t_kill = time.perf_counter()
        fleet.kill(victim_name)

        # a survivor's GC must replay the crashed wave: entry resolved,
        # the cordoned victim un-cordoned (its pods never moved)
        replay_s = None
        deadline = time.time() + max(replay_after * 5, 45)
        while time.time() < deadline:
            replays = sum(
                rt.garbage_collection.consolidation_waves_replayed
                for rt in fleet.replicas.values()
            )
            node = cluster.try_get("nodes", crasher.crash_node, namespace="")
            if replays >= 1 and node is not None and not node.spec.unschedulable:
                replay_s = time.perf_counter() - t_kill
                break
            time.sleep(0.1)
        waves_replayed = sum(
            rt.garbage_collection.consolidation_waves_replayed
            for rt in fleet.replicas.values()
        )

        # wait for the dead replica's shards to re-home before driving waves
        deadline = time.time() + lease_duration * 20
        while time.time() < deadline:
            if all(fleet.owner_named(n)[0] for n in names):
                break
            time.sleep(0.05)

        # phase D: budgeted waves under churn + seeded cloud errors.
        # the budget watcher samples the observable the budget bounds:
        # concurrently-disrupted (consolidation-tainted) nodes per
        # provisioner, across every settling wave
        max_tainted = {name: 0 for name in names}
        violations = []
        watcher_stop = threading.Event()

        def watch_budget():
            while not watcher_stop.is_set():
                tainted = {name: 0 for name in names}
                for node in cluster.nodes():
                    prov = node.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL, "")
                    if prov in tainted and any(
                        t.key == lbl.INTERRUPTION_TAINT_KEY
                        and t.value == "consolidation"
                        for t in node.spec.taints
                    ):
                        tainted[prov] += 1
                for name, count in tainted.items():
                    if count > max_tainted[name]:
                        max_tainted[name] = count
                    if count > budget_allowed:
                        violations.append((name, count))
                time.sleep(0.03)

        def churn():
            i = 0
            while not churn_stop.is_set():
                name = f"churn-{i}"
                try:
                    cluster.create("pods", make_pod(
                        name=name, requests={"cpu": "0.15"},
                        node_selector={"consfleet": names[i % n_provisioners]},
                        owner=owner_ref,
                    ))
                    time.sleep(0.2)
                    cluster.delete("pods", name, namespace="default")
                except Exception:
                    churn_failures.append(name)
                time.sleep(0.1)
                i += 1

        watcher = threading.Thread(target=watch_budget, daemon=True)
        churner = threading.Thread(target=churn, daemon=True)
        watcher.start()
        churner.start()

        # drive waves through a fixed churn window (churn keeps perturbing
        # the optimum, so the controller never "finishes" while it runs —
        # that standing pressure is the storm), then stop the churn and
        # keep driving until the re-pack genuinely dries up: node count
        # stable, every surviving pod re-seated, every wave's journal
        # entry resolved
        journal = fleet.replicas[next(iter(fleet.replicas))].journal
        deadline = time.time() + 45
        while time.time() < deadline:
            enqueue_all()
            time.sleep(0.5)
        churn_stop.set()
        churner.join(timeout=10)
        deadline = time.time() + 90
        last_nodes = len(cluster.nodes())
        stable_since = time.time()
        while time.time() < deadline:
            enqueue_all()
            count = len(cluster.nodes())
            if count != last_nodes:
                last_nodes = count
                stable_since = time.time()
            bound = all(
                p.spec.node_name for p in cluster.pods()
                if p.metadata.name in survivors
            )
            if (
                time.time() - stable_since > 10
                and bound
                and not journal.unresolved()
            ):
                break
            time.sleep(0.5)
        for p in list(cluster.pods()):
            if p.metadata.name.startswith("churn-"):
                try:
                    cluster.delete("pods", p.metadata.name, namespace="default")
                except Exception:
                    pass
        # one more pass so a wave mid-settle when the loop broke resolves
        # and every displaced survivor re-seats
        deadline = time.time() + 60
        while time.time() < deadline:
            if not journal.unresolved() and all(
                p.spec.node_name for p in cluster.pods()
                if p.metadata.name in survivors
            ):
                break
            enqueue_all()
            time.sleep(0.25)
        watcher_stop.set()
        watcher.join(timeout=5)

        # audits (all against the RAW cloud double)
        pods = [p for p in cluster.pods() if p.metadata.name in survivors]
        bound = [p for p in pods if p.spec.node_name]
        node_names = {n.metadata.name for n in cluster.nodes()}
        provider_ids = {n.spec.provider_id for n in cluster.nodes()}
        live = [i for i in api.list_instances() if i.state != "terminated"]
        leaked = [
            i for i in live
            if i.id not in node_names
            and f"sim:///{i.zone}/{i.id}" not in provider_ids
        ]
        token_counts = {}
        for inst in live:
            if inst.launch_token:
                token_counts[inst.launch_token] = (
                    token_counts.get(inst.launch_token, 0) + 1
                )
        dup_tokens = {t: c for t, c in token_counts.items() if c > 1}

        nodes_after = len(cluster.nodes())
        price_after = cluster_price()
        return {
            "pods": n_pods,
            "provisioners": n_provisioners,
            "replicas": n_replicas,
            "solver": solver,
            "budget": budget,
            "wave_size": wave_size,
            "error_rate": error_rate,
            "chaos_injected": proxy.injected_total(),
            "consolidation_success_rate": round(
                len(bound) / max(len(survivors), 1), 4
            ),
            "evicted_unready": int(
                _sample(m, "karpenter_consolidation_evicted_unready_total")
                - evicted_before
            ),
            "budget_violations": len(violations),
            "budget_blocked": int(
                _sample(m, "karpenter_consolidation_budget_blocked_total")
                - blocked_before
            ),
            "max_concurrent_disruptions": max_tainted,
            "waves_executed": int(
                _sample(m, "karpenter_consolidation_waves_total") - waves_before
            ),
            "waves_replayed": int(waves_replayed),
            "replay_s": round(replay_s, 3) if replay_s is not None else None,
            "leaked_instances": len(leaked),
            "duplicate_launches": len(dup_tokens),
            "journal_unresolved_after": len(journal.unresolved()),
            "nodes_before": nodes_before_storm,
            "nodes_after": nodes_after,
            # headline = NET fleet shrink; the gross counter also tallies
            # retire->relaunch cycles where churn re-perturbed the optimum
            "consolidation_nodes_reclaimed": max(
                nodes_before_storm - nodes_after, 0
            ),
            "nodes_retired_gross": int(
                _sample(m, "karpenter_consolidation_reclaimed_nodes_total")
                - reclaimed_before
            ),
            "consolidation_cost_delta_usd": round(
                price_after - price_before_storm, 4
            ),
            "churn_failures": len(churn_failures),
            "wall_s": round(time.perf_counter() - t_start, 2),
        }
    finally:
        churn_stop.set()
        kubelet_stop.set()
        fleet.stop_all()
        for path in (lease_path, journal_path):
            try:
                os.remove(path)
            except OSError:
                pass


def bench_overload_storm(
    n_pods: int = 300,
    overload_factor: float = 5.0,
    n_provisioners: int = 4,
    batcher_depth: int = 10,
    max_inflight: int = 1,
    queue_depth: int = 2,
    sidecar_floor_s: float = 0.2,
    calibration_pods: int = 60,
    stream: bool = False,
):
    """Overload-control proof (docs/overload.md): drive ≥``overload_factor``×
    the measured single-rate capacity at a chaos-slowed sidecar with tiny
    admission caps and a bounded batcher, with a high/default/low pod
    priority mix. The system must DECIDE what to drop: queue depths stay at
    their caps, sheds land on the lowest priority class first, every
    highest-priority pod still binds, goodput holds ≥80% of single-rate
    capacity, zero deadline-expired solves reach device dispatch, and no
    real circuit breaker trips on pure overload."""
    import threading

    import numpy as np

    from karpenter_tpu import metrics as m
    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
    from karpenter_tpu.main import build_runtime
    from karpenter_tpu.options import Options
    from karpenter_tpu.solver.service import (
        N_POD_ARRAYS,
        STATUS_DEADLINE_EXCEEDED,
        SolverService,
        pack_arrays,
        serve,
        unpack_arrays,
    )
    from karpenter_tpu.testing.chaos import ChaosPolicy, SidecarChaos, chaos_wrap
    from karpenter_tpu.testing.factories import make_pod
    from karpenter_tpu.api.objects import NodeSelectorRequirement

    t_start = time.perf_counter()
    # pin the device path: the cost router would (correctly) route these
    # small batches to native and the admission gate would never see load
    packer_before = os.environ.get("KARPENTER_PACKER")
    os.environ["KARPENTER_PACKER"] = "device"
    # stream-storm mode (docs/solver-transport.md § Streaming): the same
    # ≥5x overload leg over the streamed transport — the excess must be
    # absorbed by flow-control credits and streamed STATUS_OVERLOADED
    # soft backoff, never by gRPC deadline errors (which would book REAL
    # breaker failures; breaker_trips_on_overload=0 is the proof either
    # transport must keep)
    stream_before = os.environ.get("KARPENTER_SOLVER_STREAM")
    if stream:
        os.environ["KARPENTER_SOLVER_STREAM"] = "true"

    service = SolverService(
        max_inflight=max_inflight, queue_depth=queue_depth,
        overload_retry_after=0.2,
    )
    wrapped = chaos_wrap(
        service, ChaosPolicy(error_rate=0.0, latency_floor=sidecar_floor_s)
    )
    address = f"127.0.0.1:{SidecarChaos._free_port()}"
    server = serve(address, max_workers=8, service=wrapped)

    cluster = Cluster()
    bound_at = {}
    t0_box = [0.0]
    watch_mu = threading.Lock()

    def on_pod(event, pod):
        if event == "DELETED" or not pod.spec.node_name:
            return
        with watch_mu:
            bound_at.setdefault(
                pod.metadata.name, time.perf_counter() - t0_box[0]
            )

    cluster.watch("pods", on_pod)
    rt = build_runtime(
        Options(solver_service_address=address),
        cluster=cluster,
        cloud_provider=SimulatedCloudProvider(api=SimCloudAPI()),
    )
    shed_by_priority: dict = {}
    shed_by_reason: dict = {}
    shed_mu = threading.Lock()
    trips_before = _sample(m, "karpenter_solver_breaker_trips_total")
    try:
        rt.manager.start()
        for i in range(n_provisioners):
            cluster.create("provisioners", make_provisioner(
                name=f"ols-{i}", solver="tpu",
                requirements=[NodeSelectorRequirement(
                    key="ols", operator="In", values=[f"ols-{i}"],
                )],
            ))
        deadline = time.time() + 30
        while time.time() < deadline:
            if len(rt.provisioning.workers) == n_provisioners:
                break
            time.sleep(0.05)
        # let the solver warmups land so the calibration measures capacity,
        # not compile time (an artificially low capacity would soften the
        # goodput bar)
        deadline = time.time() + 90
        while time.time() < deadline:
            if all(w.warmed.is_set() for w in rt.provisioning.list_workers()):
                break
            time.sleep(0.1)
        for w in rt.provisioning.list_workers():
            w.batcher.idle_duration = 0.1
            w.batcher.max_depth = batcher_depth
            # shed audit: record every dropped pod's priority class + reason
            # on top of the worker's own hook (which clears pending state
            # and emits the Warning event)
            orig = w.batcher._on_shed

            def on_shed(item, reason, _orig=orig):
                from karpenter_tpu.utils.pod import priority_of

                with shed_mu:
                    shed_by_priority[priority_of(item)] = (
                        shed_by_priority.get(priority_of(item), 0) + 1
                    )
                    shed_by_reason[reason] = shed_by_reason.get(reason, 0) + 1
                if _orig is not None:
                    _orig(item, reason)

            w.batcher._on_shed = on_shed

        def make_storm_pod(i: int, prefix: str):
            # 10% high / 70% default / 20% low: the mix the shed ordering
            # is judged against
            r = i % 10
            pclass = (
                "high-priority" if r == 0
                else "low-priority" if r >= 8
                else ""
            )
            return make_pod(
                name=f"{prefix}-{i}", requests={"cpu": "0.25"},
                node_selector={"ols": f"ols-{i % n_provisioners}"},
                priority_class_name=pclass,
            )

        # -- phase 1: single-rate capacity ----------------------------------
        # Two steps, because a pure burst mostly binds in ONE batcher round
        # and measures burst-absorption, not sustained rate — on a fast
        # machine that inflates "capacity" past what any multi-round drain
        # can match and the goodput bar becomes unmeetable. Step 1 bursts
        # to get a rate estimate; step 2 re-measures PACED at that 1x rate
        # (the same offered-load shape as the storm), and THAT drain is the
        # capacity the >=0.8 goodput bar is judged against.
        t0_box[0] = time.perf_counter()
        for i in range(calibration_pods):
            cluster.create("pods", make_storm_pod(i, "cal"))
        deadline = time.time() + 120
        while time.time() < deadline:
            cal = [p for p in cluster.pods() if p.metadata.name.startswith("cal-")]
            if cal and all(p.spec.node_name for p in cal):
                break
            time.sleep(0.05)
        cal_latencies = [
            v for k, v in bound_at.items() if k.startswith("cal-")
        ]
        burst_capacity = (
            len(cal_latencies) / max(max(cal_latencies, default=1.0), 1e-6)
        )
        t0_box[0] = time.perf_counter()
        for i in range(calibration_pods):
            cluster.create("pods", make_storm_pod(i, "calp"))
            target = (i + 1) / max(burst_capacity, 1e-6)
            lag = target - (time.perf_counter() - t0_box[0])
            if lag > 0:
                time.sleep(lag)
        deadline = time.time() + 120
        while time.time() < deadline:
            cal = [
                p for p in cluster.pods()
                if p.metadata.name.startswith("calp-")
            ]
            if cal and all(p.spec.node_name for p in cal):
                break
            time.sleep(0.05)
        paced_latencies = [
            v for k, v in bound_at.items() if k.startswith("calp-")
        ]
        capacity = (
            len(paced_latencies)
            / max(max(paced_latencies, default=1.0), 1e-6)
        )

        # -- phase 2: the storm at overload_factor x capacity ----------------
        rate = max(capacity * overload_factor, 20.0)
        shed_batcher_before = _sample(m, "karpenter_batcher_shed_total")
        t0_box[0] = time.perf_counter()
        for i in range(n_pods):
            cluster.create("pods", make_storm_pod(i, "storm"))
            target = (i + 1) / rate
            lag = target - (time.perf_counter() - t0_box[0])
            if lag > 0:
                time.sleep(lag)
        offered_window = time.perf_counter() - t0_box[0]
        # settle: shed pods re-enter via selection's requeue, and every
        # HIGH-priority pod must bind (shed ordering protects them). Wait
        # for the whole storm to drain (bounded) so goodput and p99 cover
        # sustained overload, not just the first burst.
        deadline = time.time() + 180
        while time.time() < deadline:
            storm = [
                p for p in cluster.pods()
                if p.metadata.name.startswith("storm-")
            ]
            if storm and all(p.spec.node_name for p in storm):
                break
            time.sleep(0.1)
        high = [
            p for p in cluster.pods()
            if p.metadata.name.startswith("storm-")
            and p.spec.priority_class_name == "high-priority"
        ]
        high_bound = sum(1 for p in high if p.spec.node_name)

        # -- phase 3: deadline-shed probe ------------------------------------
        # an already-expired propagated budget must shed BEFORE device
        # dispatch: junk pod arrays prove the gate runs first (they would
        # crash the solve if it ever got that far). Wait for the sidecar to
        # quiesce so late storm solves can't blur the dispatch delta.
        deadline = time.time() + 60
        while time.time() < deadline and service.admission.depth():
            time.sleep(0.05)
        time.sleep(2 * sidecar_floor_s)
        dispatches_before = service.dispatches
        deadline_probes = 8
        for _ in range(deadline_probes):
            arrays = (
                [np.zeros(4, np.int32), np.asarray([64, 1], np.int32)]
                + [np.zeros(4, np.float32)] * N_POD_ARRAYS
                + [np.asarray([0.0], np.float32)]  # 0s of budget left
            )
            resp = service.solve_bytes(pack_arrays(arrays))
            status = int(unpack_arrays(resp)[0].reshape(-1)[0])
            assert status == STATUS_DEADLINE_EXCEEDED, status
        deadline_expired_dispatches = (
            service.dispatches - dispatches_before
        )

        storm = [p for p in cluster.pods() if p.metadata.name.startswith("storm-")]
        bound_total = sum(1 for p in storm if p.spec.node_name)
        accepted = sorted(
            v for k, v in bound_at.items() if k.startswith("storm-")
        )
        # goodput under SUSTAINED overload: everything the system bound
        # over the storm-to-drain span — the bar is >=80% of the single-
        # rate capacity, i.e. overload costs at most a fifth of throughput
        goodput = bound_total / max(accepted[-1] if accepted else offered_window, 1e-6)
        shed_batcher = _sample(m, "karpenter_batcher_shed_total") - shed_batcher_before
        trips = _sample(m, "karpenter_solver_breaker_trips_total") - trips_before
        batcher_peaks = [
            w.batcher.max_depth_seen for w in rt.provisioning.list_workers()
        ]
        return {
            "pods": n_pods,
            "overload_factor": overload_factor,
            "provisioners": n_provisioners,
            "capacity_pods_per_sec": round(capacity, 1),
            "burst_capacity_pods_per_sec": round(burst_capacity, 1),
            "offered_rate_pods_per_sec": round(rate, 1),
            "offered_window_s": round(offered_window, 2),
            "goodput_pods_per_sec": round(goodput, 1),
            "goodput_fraction_of_capacity": round(goodput / max(capacity, 1e-6), 3),
            "accepted_p99_bind_s": round(_p99(accepted), 3) if accepted else None,
            "bound_total": bound_total,
            "high_priority_success_rate": round(
                high_bound / max(len(high), 1), 4
            ),
            "batcher_shed_total": int(shed_batcher),
            "shed_by_priority": {str(k): v for k, v in sorted(shed_by_priority.items())},
            "shed_by_reason": dict(sorted(shed_by_reason.items())),
            "sidecar_shed": dict(service.shed),
            "sidecar_dispatches": service.dispatches,
            "deadline_sheds": deadline_probes,
            "deadline_expired_dispatches": int(deadline_expired_dispatches),
            "batcher_depth_cap": batcher_depth,
            "batcher_max_depth_seen": max(batcher_peaks, default=0),
            "batcher_depth_bounded": max(batcher_peaks, default=0) <= batcher_depth,
            "admission_depth_cap": max_inflight + queue_depth,
            "admission_max_depth_seen": service.admission.max_depth_seen,
            "admission_depth_bounded": (
                service.admission.max_depth_seen <= max_inflight + queue_depth
            ),
            "breaker_trips_on_overload": int(trips),
            **(
                {
                    # streamed-transport proof keys: the storm actually
                    # rode the stream, and the excess was absorbed by
                    # credits / streamed soft backoff (breaker trips and
                    # deadline-expired dispatches above must both be 0 —
                    # a gRPC deadline error would have tripped a breaker)
                    "stream_transport": True,
                    "stream_solves": int(
                        service.stream_stats["stream_solves"]
                    ),
                    "stream_coalesced_solves": int(
                        service.stream_stats["coalesced_solves"]
                    ),
                    "stream_credit_stalls": int(_sample(
                        m, "karpenter_solver_stream_credit_stalls_total"
                    )),
                    "stream_breaks": int(_sample(
                        m, "karpenter_solver_stream_breaks_total"
                    )),
                }
                if stream else {}
            ),
            "wall_s": round(time.perf_counter() - t_start, 2),
        }
    finally:
        if packer_before is None:
            os.environ.pop("KARPENTER_PACKER", None)
        else:
            os.environ["KARPENTER_PACKER"] = packer_before
        if stream:
            if stream_before is None:
                os.environ.pop("KARPENTER_SOLVER_STREAM", None)
            else:
                os.environ["KARPENTER_SOLVER_STREAM"] = stream_before
        rt.stop()
        server.stop(grace=0)


def bench_multi_provisioner(n_provisioners: int, n_pods: int, iters: int):
    """BASELINE config 4: many provisioners' batches solved concurrently —
    stacked on the batch axis and sharded over the device mesh
    (parallel/sharding.py). Also runs the SAME encoded batches through the
    native CPU packer sequentially (VERDICT r3 ask #4: apples-to-apples),
    with the device inputs kept resident across iterations (the production
    shape: invariants cached on device; a locally-attached chip pays PCIe,
    not this rig's ~30MB/s tunnel)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from karpenter_tpu.parallel.sharding import make_solver_mesh, sharded_multi_solve
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc

    catalog = sorted(instance_types(400), key=lambda it: it.effective_price())
    batches = []
    batch_meta = []  # (constraints, pods) per batch, for oracle certification
    for b in range(n_provisioners):
        provisioner = make_provisioner(name=f"prov-{b}")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(100 + b)))
        cc = c.clone()
        Topology(Cluster(), rng=random.Random(b)).inject(cc, pods)
        daemon = daemon_overhead(Cluster(), cc)
        batches.append(enc.encode(cc, catalog, pods, daemon))
        # PRE-injection pods for oracle certification: inject() writes the
        # chosen zone/hostname into pod selectors, and the oracle reasons
        # about the empty plan — same seed + deterministic sort makes
        # index i of this copy the same pod as assignment column i
        batch_meta.append((c, sort_pods_ffd(diverse_pods(n_pods, random.Random(100 + b)))))
    # all batches share the same shapes (same pod count bucket + catalog)
    arrays = tuple(
        np.stack([np.asarray(getattr(b, f)) for b in batches])
        for f in ("pod_valid", "pod_open_sig", "pod_core", "pod_host",
                  "pod_host_in_base", "pod_open_host", "pod_req",
                  "join_table", "frontiers", "daemon")
    )
    sig_type_mask = np.stack([b.type_mask_matrix() for b in batches])
    prices = np.array([it.effective_price() for it in catalog], np.float32)
    mesh = make_solver_mesh()
    n_max = max(256, len(batches[0].pod_valid) // 4)
    n_real = batches[0].n_pods

    # device-resident inputs: invariants uploaded once; the per-iteration
    # perturbation of the PADDED pod rows (the tunneled backend dedupes
    # byte-identical dispatches; padding rows cannot affect the packing)
    # happens ON DEVICE from an 8-byte epsilon
    pad_mask = np.zeros(arrays[6].shape, np.float32)
    pad_mask[:, n_real:, :] = 1.0
    sh3 = NamedSharding(mesh, PS("data", None, None))
    base_req = jax.device_put(arrays[6], sh3)
    mask_dev = jax.device_put(pad_mask, sh3)
    perturb = jax.jit(lambda base, m, eps: base + m * eps)
    placed = list(arrays)

    def run(epsilon: float):
        placed[6] = perturb(base_req, mask_dev, epsilon)
        result, cheapest, _ = sharded_multi_solve(
            mesh, tuple(placed), sig_type_mask, batches[0].usable, prices, n_max=n_max
        )
        # a real fetch forces execution — under the tunneled backend,
        # block_until_ready alone does not
        jax.device_get((result.n_nodes, cheapest[:, 0]))
        return result

    result = run(0.0)  # warmup/compile
    specs = [PS("data")] * 6 + [None, PS("data", None, None),
                                PS("data", None, None, None), PS("data", None)]
    for i, s in enumerate(specs):
        if i != 6:
            placed[i] = jax.device_put(arrays[i], NamedSharding(mesh, s))
    run(0.0)
    # interleaved transport sampling, like bench_once: the adjusted
    # figures must reflect this run window's tunnel, not a one-off probe
    probe = RttProbe()
    probe.sample(3)
    times = []
    for it in range(iters):
        t0 = time.perf_counter()
        result = run((it + 1) * 1e-7)
        times.append(time.perf_counter() - t0)
        probe.sample(1)
    rtt = probe.floor
    best = min(times)
    assignment_np = np.asarray(result.assignment)
    scheduled = int((assignment_np[:, :n_real] >= 0).sum())

    # oracle-certify every batch's drops: assignment index i is pods[i]
    # (encode preserves the FFD sort order) — VERDICT r4 #7
    from karpenter_tpu.scheduling.oracle import classify_drops

    unexplained = expected_drops = 0
    for b, (bc, bpods) in enumerate(batch_meta):
        placed = [p for i, p in enumerate(bpods) if assignment_np[b, i] >= 0]
        verdict = classify_drops(Cluster(), bc, catalog, bpods, placed)
        unexplained += len(verdict["unexplained"])
        expected_drops += verdict["dropped"] - len(verdict["unexplained"])

    out = {
        "provisioners": n_provisioners,
        "pods_per_batch": n_pods,
        "scheduled_total": scheduled,
        "unschedulable_expected": expected_drops,
        "unexplained": unexplained,
        "solve_s": best,
        "pods_per_sec": scheduled / best,
        "solve_minus_rtt_s": round(max(best - rtt, 1e-9), 4),
        "pods_per_sec_minus_rtt": round(scheduled / max(best - rtt, 1e-9), 1),
        "mesh": dict(mesh.shape),
    }
    # identical workload through the native CPU packer, sequentially (one
    # core in this rig; ctypes releases the GIL but there is nothing to
    # overlap with)
    from karpenter_tpu.solver.native import native_available, pack_native

    if native_available(wait=120):
        cpu_times = []
        cpu_scheduled = 0
        for _ in range(max(2, iters // 2)):
            t0 = time.perf_counter()
            cpu_scheduled = 0
            for b in batches:
                r = pack_native(*b.pack_args(), n_max=n_max)
                cpu_scheduled += int((np.asarray(r.assignment)[: b.n_pods] >= 0).sum())
            cpu_times.append(time.perf_counter() - t0)
        cpu_best = min(cpu_times)
        out["multi_cpu_solve_s"] = round(cpu_best, 5)
        out["multi_cpu_pods_per_sec"] = round(cpu_scheduled / cpu_best, 1)
        out["multi_tpu_pods_per_sec"] = out["pods_per_sec_minus_rtt"]
        # The honest read (VERDICT r3 ask #4): the batch axis amortizes on
        # the TPU (throughput scales ~4x from B=8 to B=64 at equal latency
        # class) but first-fit-decreasing is a sequential dependence chain
        # with no matmul content — the cache-resident native packer runs at
        # ~70ns/pod and stays ahead at every B reachable on one chip; vmap
        # over a Pallas grid serializes lanes, so multi-chip 'data' sharding
        # (n_devices x this rate), not lane count, is the TPU scaling axis.
        out["multi_tpu_wins"] = out["multi_tpu_pods_per_sec"] > out["multi_cpu_pods_per_sec"]
    return out


def _config_scenario(config: int):
    """(catalog, provisioner, pods, label) for BASELINE configs 1-3 —
    shared by bench_config and the router-parity axis."""
    from karpenter_tpu.api import labels as lbl
    from karpenter_tpu.api.objects import (
        LabelSelector,
        PodAffinityTerm,
        Taint,
        Toleration,
    )
    from karpenter_tpu.testing import make_pod, zone_spread

    if config == 1:
        # Single Provisioner, 100 pods, cpu+mem only (FFD baseline)
        catalog = instance_types(50)
        provisioner = make_provisioner(solver="ffd")
        pods = [
            make_pod(requests={"cpu": "0.5", "memory": "512Mi"}) for _ in range(100)
        ]
        label = "config-1: 100 pods cpu+mem, ffd"
    elif config == 2:
        # nodeSelector + taint/toleration filter, 1k pods × 50 types
        catalog = instance_types(50)
        provisioner = make_provisioner(
            solver="tpu", taints=[Taint(key="dedicated", value="team", effect="NoSchedule")]
        )
        rng = random.Random(2)
        pods = [
            make_pod(
                requests={"cpu": f"{rng.choice([0.25, 0.5, 1])}"},
                node_selector={lbl.TOPOLOGY_ZONE: rng.choice(
                    ["test-zone-1", "test-zone-2", "test-zone-3"])},
                tolerations=[Toleration(key="dedicated", value="team")],
            )
            for _ in range(1000)
        ]
        label = "config-2: 1k pods x 50 types, selectors+taints, tpu"
    elif config == 3:
        # podAffinity/antiAffinity + topologySpread across 3 AZs
        rng = random.Random(3)
        catalog = instance_types(50)
        provisioner = make_provisioner(solver="tpu")
        pods = []
        for i in range(333):
            sel = {"app": f"g{i % 5}"}
            pods.append(make_pod(labels=sel, requests={"cpu": "0.5"},
                                 pod_requirements=[PodAffinityTerm(
                                     label_selector=LabelSelector(match_labels=sel),
                                     topology_key=lbl.TOPOLOGY_ZONE)]))
            pods.append(make_pod(labels=sel, requests={"cpu": "0.5"},
                                 pod_anti_requirements=[PodAffinityTerm(
                                     label_selector=LabelSelector(match_labels={"app": f"solo{i}"}),
                                     topology_key=lbl.TOPOLOGY_ZONE)]))
            pods.append(make_pod(labels=sel, requests={"cpu": "0.5"},
                                 topology=[zone_spread(max_skew=1, labels=sel)]))
        label = "config-3: affinity/anti-affinity + zone spread, tpu"
    else:
        raise SystemExit(f"no scenario for config {config}")
    return catalog, provisioner, pods, label


def bench_config(config: int, iters: int):
    """Run one of BASELINE.json's five configs and emit its JSON line."""
    if config in (1, 2, 3):
        catalog, provisioner, pods, label = _config_scenario(config)
    elif config == 4:
        # Multi-Provisioner sharding, 10k pods × 400 types
        r = bench_multi_provisioner(8, 1250, iters)
        return {
            "metric": "BASELINE config-4: multi-provisioner 10k pods x 400 types",
            "value": round(r["pods_per_sec"], 1),
            "unit": "pods/sec",
            "vs_baseline": round(r["pods_per_sec"] / BASELINE_PODS_PER_SEC, 2),
            **{k: v for k, v in r.items() if k != "pods_per_sec"},
        }
    elif config == 5:
        r = bench_consolidation(1000, iters, "tpu")
        return {
            "metric": "BASELINE config-5: consolidation re-pack of 1k nodes",
            "value": round(r["repack_s"] * 1e3, 1),
            "unit": "ms/re-pack",
            "vs_baseline": round((r["pods"] / max(r["repack_s"], 1e-9)) / BASELINE_PODS_PER_SEC, 2),
            **{k: v for k, v in r.items() if k != "repack_s"},
        }
    else:
        raise SystemExit(f"unknown config {config}")

    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    cluster = Cluster()
    scheduler = Scheduler(cluster, rng=random.Random(1))
    nodes = scheduler.solve(provisioner, catalog, pods)  # warmup
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        nodes = scheduler.solve(provisioner, catalog, pods)
        times.append(time.perf_counter() - t0)
    best = min(times)
    scheduled = sum(len(n.pods) for n in nodes)
    # every published figure carries oracle certification (VERDICT r4 #7)
    from karpenter_tpu.scheduling.oracle import classify_drops

    verdict = classify_drops(
        cluster, c, catalog, pods, [p for n in nodes for p in n.pods]
    )
    return {
        "metric": f"BASELINE {label}",
        "value": round(scheduled / best, 1),
        "unit": "pods/sec",
        "vs_baseline": round((scheduled / best) / BASELINE_PODS_PER_SEC, 2),
        "scheduled": scheduled,
        "pods": len(pods),
        "nodes": len(nodes),
        "best_s": round(best, 4),
        "unschedulable_expected": verdict["dropped"] - len(verdict["unexplained"]),
        "unexplained": len(verdict["unexplained"]),
    }


def bench_affinity_dense(n_pods: int, iters: int, frac: float = 0.5):
    """VERDICT r5 ask #1b: the affinity-dense regime — the workload that
    maximizes the topology pre-assignment pass (pairwise pod-affinity
    turned into group-domain assignment) relative to the pack. Head-to-head
    end-to-end through the device path vs the native packer on the
    IDENTICAL scenario, interleaved and order-rotated like the parity axis,
    with the inject/pack stage medians that show where the time actually
    lives (docs/affinity-regime.md is the written analysis)."""
    import os

    from karpenter_tpu.scheduling.oracle import classify_drops
    from karpenter_tpu.testing import affinity_dense_pods

    catalog = instance_types(400)
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = affinity_dense_pods(n_pods, random.Random(77), frac=frac)
    cluster = Cluster()
    scheduler = Scheduler(cluster, rng=random.Random(1))

    forces = (("device", "fused"), ("native", "native"))
    prev = os.environ.get("KARPENTER_PACKER")
    times = {label: [] for label, _ in forces}
    stages = {label: [] for label, _ in forces}
    nodes = []
    try:
        for label, env in forces:  # per-backend warmup (compile)
            os.environ["KARPENTER_PACKER"] = env
            scheduler.solve(provisioner, catalog, pods)
        from karpenter_tpu.utils.gcpolicy import freeze_after_warmup

        freeze_after_warmup()
        for rnd in range(max(3, iters)):
            order = [forces[(rnd + k) % len(forces)] for k in range(len(forces))]
            for label, env in order:
                os.environ["KARPENTER_PACKER"] = env
                t0 = time.perf_counter()
                nodes = scheduler.solve(provisioner, catalog, pods)
                times[label].append(time.perf_counter() - t0)
                stages[label].append(dict(scheduler._tpu.last_profile))
    finally:
        if prev is None:
            os.environ.pop("KARPENTER_PACKER", None)
        else:
            os.environ["KARPENTER_PACKER"] = prev
    scheduled = sum(len(n.pods) for n in nodes)
    verdict = classify_drops(
        cluster, c, catalog, pods, [p for n in nodes for p in n.pods]
    )
    out = {
        "pods": n_pods,
        "affinity_frac": frac,
        "scheduled": scheduled,
        "unschedulable_expected": verdict["dropped"] - len(verdict["unexplained"]),
        "unexplained": len(verdict["unexplained"]),
    }
    for label, _ in forces:
        best = min(times[label])
        out[f"{label}_pods_per_sec"] = round(scheduled / best, 1)
        out[f"{label}_best_s"] = round(best, 4)
        med = {
            k: round(statistics.median(p[k] for p in stages[label] if k in p) * 1e3, 1)
            for k in stages[label][0]
            if k.endswith("_s")
        }
        out[f"{label}_stages_ms"] = med
    out["tpu_wins"] = out["device_pods_per_sec"] > out["native_pods_per_sec"]
    return out


def _parity_scenario(cfg: int):
    """One BASELINE config as a reusable pass closure: build the scenario
    ONCE, return ``run() -> scheduled_count`` driven under whatever
    KARPENTER_PACKER is in force. Sharing the scenario lets the parity axis
    interleave backends pass-by-pass so ambient load noise (this is a
    1-core box) hits every backend equally."""
    if cfg in (2, 3):
        catalog, provisioner, pods, _ = _config_scenario(cfg)
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        scheduler = Scheduler(Cluster(), rng=random.Random(1))

        def run():
            return sum(
                len(n.pods) for n in scheduler.solve(provisioner, catalog, pods)
            )

        return run
    if cfg == 4:
        # production shape of the multi-provisioner config: 8 workers, each
        # solving its own 1250-pod batch via TpuScheduler (the path the
        # router governs; the sharded-mesh kernel is the multi-chip axis,
        # benched separately by bench_multi_provisioner)
        catalog = instance_types(400)
        setups = []
        for b in range(8):
            prov = make_provisioner(name=f"prov-{b}", solver="tpu")
            c = prov.spec.constraints
            c.requirements = c.requirements.merge(catalog_requirements(catalog))
            pods = diverse_pods(1250, random.Random(100 + b))
            setups.append((prov, Scheduler(Cluster(), rng=random.Random(b)), pods))

        def run():
            return sum(
                sum(len(n.pods) for n in sched.solve(prov, catalog, pods))
                for prov, sched, pods in setups
            )

        return run
    if cfg != 5:
        raise SystemExit(f"no parity scenario for config {cfg}")
    # consolidation re-pack of 1k nodes
    from karpenter_tpu.api import labels as lbl
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
    from karpenter_tpu.controllers.consolidation import ConsolidationController
    from karpenter_tpu.testing import make_pod
    from karpenter_tpu.testing.factories import make_node

    rng = random.Random(7)
    catalog = instance_types(400)
    cluster = Cluster()
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    cluster.create("provisioners", provisioner)
    for i in range(1000):
        node = make_node(
            name=f"live-{i}", capacity={"cpu": "16", "memory": "32Gi", "pods": "100"},
            provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: f"fake-it-{rng.randrange(300, 400)}",
                    lbl.TOPOLOGY_ZONE: "test-zone-1", lbl.CAPACITY_TYPE: "on-demand"},
        )
        cluster.create("nodes", node)
        for j in range(rng.randrange(1, 4)):
            cluster.create(
                "pods",
                make_pod(name=f"p-{i}-{j}", requests={"cpu": f"{rng.choice([0.5, 1, 2])}"},
                         node_name=node.metadata.name, unschedulable=False),
            )
    controller = ConsolidationController(cluster, FakeCloudProvider(catalog))

    def run():
        return len(controller.plan(provisioner).pods)

    return run


def bench_router_parity(iters: int, emit=print):
    """VERDICT r5 ask #1a done-bar: ``auto`` (the measured-cost router,
    solver/router.py) must match the best forced backend within 10% on
    every BASELINE config — the product is never slower than its own CPU
    path. ``device`` is forced via KARPENTER_PACKER=fused (the r4 platform-
    preferring behavior). Backends share one scenario and run INTERLEAVED
    pass-by-pass, so ambient load lands on all of them equally; config 1
    is the FFD solver (no packer in play)."""
    import os

    forces = (("auto", "auto"), ("native", "native"), ("device", "fused"))
    rows = []
    for cfg in (1, 2, 3, 4, 5):
        row = {"config": cfg}
        if cfg == 1:
            r = bench_config(1, max(2, iters))
            row.update({
                "auto_pods_per_sec": r["value"],
                "note": "ffd solver: no packer in play",
                "auto_vs_best": 1.0, "within_10pct": True,
            })
            rows.append(row)
            if emit:
                emit(json.dumps({"metric": "router-parity config-1",
                                 **{k: v for k, v in row.items() if k != "config"}}))
            continue
        try:
            run = _parity_scenario(cfg)
            prev = os.environ.get("KARPENTER_PACKER")
            times = {label: [] for label, _ in forces}
            reps = {}
            scheduled = 0
            try:
                for label, env in forces:  # per-backend warmup (compile,
                    os.environ["KARPENTER_PACKER"] = env  # router cold start)
                    run()
                    if label == "auto":
                        run()  # second pass: past the 2-candidate cold start
                    t0 = time.perf_counter()
                    run()
                    est = time.perf_counter() - t0
                    if est < 0.05:
                        # cheap pass: one GC spike in the single estimate
                        # would mis-size reps — take the min of two
                        t0 = time.perf_counter()
                        run()
                        est = min(est, time.perf_counter() - t0)
                    # a timed unit must be >=100 ms: a 2-3 ms solve cannot
                    # hold a 10% bound against timer/GC noise on a shared
                    # 1-core box, so cheap backends amortize over reps
                    reps[label] = max(1, min(128, int(0.10 / max(est, 1e-4)) + 1))
                # gen-2 GC passes over the warm heap are 100-200 ms spikes
                # that land on random units (the consolidation scenario
                # allocates a 1k-node shadow cluster per pass) — same
                # post-warmup policy as bench_once and the runtime
                from karpenter_tpu.utils.gcpolicy import freeze_after_warmup

                freeze_after_warmup()
                for rnd in range(max(6, iters)):
                    # auto and native run back-to-back (their comparison is
                    # the one the 10% bar judges — adjacent units see the
                    # same ambient load), alternating which goes first; the
                    # heavyweight device unit always runs last so its
                    # cache/GC hangover lands on next round's leader, which
                    # alternates between the two
                    pair = [forces[0], forces[1]]
                    if rnd % 2:
                        pair.reverse()
                    order = pair + [forces[2]]
                    for label, env in order:
                        os.environ["KARPENTER_PACKER"] = env
                        t0 = time.perf_counter()
                        for _ in range(reps[label]):
                            scheduled = run()
                        times[label].append(
                            (time.perf_counter() - t0) / reps[label]
                        )
            finally:
                if prev is None:
                    os.environ.pop("KARPENTER_PACKER", None)
                else:
                    os.environ["KARPENTER_PACKER"] = prev
            perf = {label: scheduled / min(ts) for label, ts in times.items()}
            for label, v in perf.items():
                row[f"{label}_pods_per_sec"] = round(v, 1)
            best_forced = max(v for k, v in perf.items() if k != "auto")
            row["auto_vs_best"] = round(perf["auto"] / best_forced, 3)
            row["within_10pct"] = bool(perf["auto"] >= 0.9 * best_forced)
        except Exception as e:
            row["error"] = str(e)[:120]
        rows.append(row)
        if emit:
            emit(json.dumps({"metric": f"router-parity config-{cfg}",
                             **{k: v for k, v in row.items() if k != "config"}}))
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=10000)
    # 50+ iterations: a p99/p90 judged on a dozen samples is max(), and a
    # single CPU-contention spike lands there (VERDICT r3 weak #4)
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--solver", default="tpu", choices=["tpu", "ffd"])
    ap.add_argument("--grid", action="store_true", help="run the reference's full batch grid")
    ap.add_argument("--consolidation", type=int, metavar="N_NODES", default=0,
                    help="bench the consolidation re-pack of N live nodes instead")
    ap.add_argument("--multi", type=int, metavar="N_PROVISIONERS", default=0,
                    help="bench N provisioners' batches solved concurrently on the mesh")
    ap.add_argument("--diverse", type=int, metavar="K_LABELS", default=0,
                    help="bench a constraint-diverse batch with K distinct selector values")
    ap.add_argument("--selection-storm", type=int, metavar="N_PODS", default=0,
                    help="drive N pod watch events through manager->selection->"
                         "batcher->solve->bind and report end-to-end latency")
    ap.add_argument("--interruption-churn", type=int, metavar="N_PODS", default=0,
                    help="steady N-pod load with 5%% of nodes preempted per "
                         "round; reports interruption_evicted_unready and "
                         "replacement_lead_time_p99_s")
    ap.add_argument("--chaos", type=int, metavar="N_PODS", default=0,
                    help="provision N pods through the full runtime while the "
                         "simulated control plane misbehaves (10%% errors, "
                         "50ms p95 injected latency, an ICE-storm window, live "
                         "preemptions); reports chaos_provision_success_rate "
                         "and chaos_launch_p99_s")
    ap.add_argument("--chaos-error-rate", type=float, default=0.1)
    ap.add_argument("--chaos-latency-p95", type=float, default=0.05)
    ap.add_argument("--chaos-seed", type=int, default=20260803)
    ap.add_argument("--fleet-storm", type=int, metavar="N_PODS", default=0,
                    help="multi-tenant HA storm: provisioners sharded across "
                         "controller replicas over a solver sidecar pool, "
                         "with a mid-storm replica crash + sidecar kill; "
                         "reports aggregate pods/sec, p99 time-to-bind, "
                         "duplicate_launches (bar: 0) and rebalance_s "
                         "(bar: 2x lease duration)")
    ap.add_argument("--forecast-storm", type=float, metavar="DURATION_S",
                    default=0,
                    help="predictive-provisioning storm "
                         "(docs/forecasting.md): the same seeded diurnal "
                         "+ flash-crowd arrival schedule run cold "
                         "(reactive) then warm (forecast-driven "
                         "speculative pool) over a latency-bearing cloud "
                         "double; reports warm_hit_rate, warm-vs-cold "
                         "spike time-to-ready p99 (bar: 2x), "
                         "leaked_instances/duplicate_launches (bar: 0), "
                         "and the what-if simulator cross-check "
                         "(bar: within 20%%)")
    ap.add_argument("--forecast-launch-latency", type=float, default=0.5,
                    help="simulated create_fleet latency the warm pool "
                         "must hide (seconds)")
    ap.add_argument("--forecast-seed", type=int, default=20260807)
    # None = each storm's own default (fleet: 8, crash: 4) — a real default
    # here would be indistinguishable from an explicit request for it
    ap.add_argument("--fleet-provisioners", type=int, default=None)
    ap.add_argument("--fleet-replicas", type=int, default=3)
    ap.add_argument("--fleet-pool", type=int, default=2)
    ap.add_argument("--partition-storm", type=int, metavar="N_PODS", default=0,
                    help="control-plane partition storm (docs/partition.md): "
                    "replicas over a chaos-wrapped apiserver — sub-expiry "
                    "blip (zero shard churn), 429 brownout, and a 2x-lease "
                    "blackout (every replica fenced, zero cloud mutations "
                    "while fenced, bounded recovery)")
    ap.add_argument("--partition-lease-duration", type=float, default=1.5)
    ap.add_argument("--crash-storm", type=int, metavar="N_PODS", default=0,
                    help="crash-consistency storm: a replica is killed "
                         "between the cloud create and the Node write, a "
                         "second between the Node write and the bind; "
                         "reports leaked_instances (bar: 0), "
                         "duplicate_launches (bar: 0), adoption latency vs "
                         "the one-GC-period bar, and "
                         "chaos_provision_success_rate (bar: 1.0)")
    ap.add_argument("--consolidation-storm", type=int, metavar="N_PODS",
                    default=0,
                    help="disruption-safe consolidation storm "
                         "(docs/consolidation.md): replicas run budgeted, "
                         "journaled re-pack waves at ~70%% utilization with "
                         "pod churn, seeded cloud errors, and a mid-wave "
                         "replica kill; bars: zero evicted-unready pods, "
                         "zero budget violations, zero leaked/duplicate "
                         "instances, crashed wave replayed; reports "
                         "consolidation_nodes_reclaimed and "
                         "consolidation_cost_delta_usd")
    ap.add_argument("--consolidation-budget", default="2",
                    help="per-provisioner disruption budget for "
                         "--consolidation-storm (count or percent)")
    ap.add_argument("--corruption-storm", type=int, metavar="N_PODS", default=0,
                    help="silent-data-corruption storm: the serving sidecar "
                         "pool member emits seeded corrupt frames (payload "
                         "bit-flip, frame truncation, stale-session replay, "
                         "NaN injection), one 100%%-injection phase per mode "
                         "+ a mixed phase, with wire checksums and the "
                         "native canary cross-check ON; reports "
                         "corrupt_packs_bound (bar: 0), detection_rate "
                         "(bar: 1.0), quarantine_within_solves (bar: <=5) "
                         "and chaos_provision_success_rate (bar: 1.0)")
    ap.add_argument("--corrupt-rate", type=float, default=0.05,
                    help="mixed-phase corruption probability for "
                         "--corruption-storm (per-mode phases always run "
                         "at 1.0)")
    ap.add_argument("--canary-rate", type=float, default=0.25,
                    help="canary cross-check fraction for --corruption-storm")
    ap.add_argument("--overload-storm", type=int, metavar="N_PODS", default=0,
                    help="overload-control storm: >=5x the measured "
                         "single-rate capacity at a chaos-slowed sidecar "
                         "with tiny admission caps and a bounded batcher "
                         "(high/default/low priority mix); reports goodput "
                         "vs capacity (bar: >=0.8), shed counts by "
                         "priority, accepted-work p99, max queue depths vs "
                         "caps, deadline_expired_dispatches (bar: 0), "
                         "high_priority_success_rate (bar: 1.0), and "
                         "breaker_trips_on_overload (bar: 0)")
    ap.add_argument("--streamed", type=int, metavar="N_PODS", default=0,
                    help="streamed-transport leg (docs/solver-transport.md "
                         "§ Streaming): unary vs streamed RTT floors against "
                         "one live sidecar, full-scheduler throughput over "
                         "both transports, the zero-copy shm sub-leg, and "
                         "the cross-stream coalescing rate")
    ap.add_argument("--overload-stream", action="store_true",
                    help="run the overload storm over the STREAMED "
                         "transport: credits + streamed soft backoff must "
                         "absorb the ≥5x excess with zero breaker trips "
                         "and zero gRPC deadline errors")
    ap.add_argument("--overload-factor", type=float, default=5.0,
                    help="offered-load multiple of measured capacity for "
                         "--overload-storm")
    ap.add_argument("--config", type=int, default=0, metavar="1..5",
                    help="run one of BASELINE.json's five configs")
    ap.add_argument("--all-configs", action="store_true",
                    help="run all five BASELINE configs (one JSON line each)")
    ap.add_argument("--router-parity", action="store_true",
                    help="auto (cost-routed) vs best forced backend on the five "
                         "BASELINE configs (VERDICT r5 #1a done-bar)")
    ap.add_argument("--affinity-dense", type=int, metavar="N_PODS", default=0,
                    help="head-to-head device vs native on the affinity-dense "
                         "regime (VERDICT r5 #1b)")
    ap.add_argument("--profile", metavar="OUT", default="",
                    help="write cProfile stats for one solve (the pprof-harness analog, "
                         "reference: scheduling_benchmark_test.go:76-108)")
    ap.add_argument("--no-trace", action="store_true",
                    help="disable span tracing entirely — the overhead "
                         "acceptance bar compares a traced run's native leg "
                         "against this mode (within 3%%)")
    ap.add_argument("--profile-hz", type=float, default=0.0,
                    help="run the stdlib sampling profiler (obs/profiler.py) "
                         "for the whole bench at this rate; the record line "
                         "gains profiler_overhead_pct (<1 bar) + top frames")
    ap.add_argument("--profile-overhead-check", action="store_true",
                    help="CI gate: run the headline leg with and without the "
                         "sampling profiler, report both, exit 1 if the "
                         "profiler's self-accounted overhead is >=1%%")
    ap.add_argument("--no-solver-delta", action="store_true",
                    help="disable resident delta encoding on the headline/"
                         "device legs (docs/delta-encoding.md) — the "
                         "host_share_ms comparison point: full sort/inject/"
                         "encode rebuild every solve")
    ap.add_argument("--delta-storm", type=int, metavar="N_PODS", default=0,
                    help="delta-residency chaos leg (docs/delta-encoding.md):"
                         " the full runtime with --solver-delta against a "
                         "chaos sidecar pool injecting stale_delta frames "
                         "(checksum-valid, epoch words lying — the wire "
                         "shape of out-of-order/dropped deltas) plus a "
                         "mid-round sidecar restart; acceptance: zero "
                         "stale-tensor binds, epoch-mismatch full "
                         "re-encodes counted, provision success rate 1.0")
    ap.add_argument("--regression-storm", type=int, metavar="N_PODS",
                    default=0,
                    help="regression-sentinel storm (docs/observability.md):"
                         " steady identical waves against a sidecar pool "
                         "(bar: ZERO false-positive incidents), then a "
                         "sustained injected wire latency step; the "
                         "sentinel must open exactly one correlated "
                         "incident naming a wire/device stage with >=1 "
                         "flight record, >=1 decision id and profiler "
                         "folds attached, at <1%% self-accounted overhead")
    ap.add_argument("--sentinel-overhead-check", action="store_true",
                    help="CI gate: run the headline leg with and without "
                         "the regression sentinel hooked; report both, "
                         "exit 1 if the sentinel's self-accounted overhead "
                         "is >=1%%")
    ap.add_argument("--no-explain", action="store_true",
                    help="disable the decision observability plane for this "
                         "run — the explain-overhead acceptance bar compares "
                         "the headline leg's explain_overhead_pct (attribution "
                         "+ record write, <1%%) against this mode")
    ap.add_argument("--explain-overhead-check", action="store_true",
                    help="CI gate: run the headline leg with per-round "
                         "decision records + attribution and again with "
                         "--no-explain; report both, exit 1 if the "
                         "self-accounted explain overhead is >=1%%")
    args = ap.parse_args()

    from karpenter_tpu import obs

    if args.no_trace:
        obs.set_enabled(False)
    if args.no_explain:
        from karpenter_tpu.obs import decisions as _dec

        _dec.set_enabled(False)

    if args.explain_overhead_check:
        # with-vs-without comparison, the profiler-gate discipline: the
        # throughput delta is reported for humans (noisy on shared CI
        # boxes), the GATE is the self-accounted hot-path share — the
        # attribution + record build + write enqueue measured per round
        # against the solve time it rode alongside
        import tempfile

        from karpenter_tpu.obs import decisions as _dec

        iters = max(args.iters, 4)
        _dec.set_enabled(False)
        base = bench_once(args.pods, iters, args.solver)
        _dec.set_enabled(True)
        with tempfile.TemporaryDirectory() as ddir:
            withx = bench_once(
                args.pods, iters, args.solver, record_decisions=ddir
            )
        overhead_pct = withx.get("explain_overhead_pct", 0.0)
        ok = overhead_pct < 1.0
        print(json.dumps({
            "metric": f"explain overhead ({args.pods} pods, per-round "
                      "decision records + attribution)",
            "value": round(overhead_pct, 4),
            "unit": "% of solve time spent on the decision hot path",
            "explain_overhead_pct": round(overhead_pct, 4),
            "explain_overhead_ok": ok,
            "decision_records_written": withx.get("decision_records_written"),
            "pods_per_sec_off": round(base["pods_per_sec"], 1),
            "pods_per_sec_on": round(withx["pods_per_sec"], 1),
            "throughput_delta_pct": round(
                (base["pods_per_sec"] - withx["pods_per_sec"])
                / base["pods_per_sec"] * 100, 2,
            ),
        }))
        sys.exit(0 if ok else 1)

    if args.sentinel_overhead_check:
        # with-vs-without comparison, the profiler-gate discipline: the
        # throughput delta is reported for humans (noisy on shared CI
        # boxes), the GATE is the sentinel's self-accounted busy/wall
        # ratio — the per-span probe + detector arithmetic + periodic
        # baseline save, measured from inside the hook
        iters = max(args.iters, 4)
        base = bench_once(args.pods, iters, args.solver)
        eng = obs.configure_sentinel(min_events=8)
        withs = bench_once(args.pods, iters, args.solver)
        overhead_pct = eng.overhead_ratio() * 100
        baselines = eng.baseline_count()
        obs.shutdown_sentinel(eng)
        ok = overhead_pct < 1.0
        print(json.dumps({
            "metric": f"sentinel overhead ({args.pods} pods, online "
                      "baselines + change-point detection per span)",
            "value": round(overhead_pct, 4),
            "unit": "% sentinel busy/wall",
            "sentinel_overhead_pct": round(overhead_pct, 4),
            "sentinel_overhead_ok": ok,
            "sentinel_baselines": baselines,
            "pods_per_sec_off": round(base["pods_per_sec"], 1),
            "pods_per_sec_on": round(withs["pods_per_sec"], 1),
            "throughput_delta_pct": round(
                (base["pods_per_sec"] - withs["pods_per_sec"])
                / base["pods_per_sec"] * 100, 2,
            ),
        }))
        sys.exit(0 if ok else 1)

    if args.profile_overhead_check:
        # with-vs-without comparison: the throughput delta is reported for
        # humans (noisy on shared CI boxes), the GATE is the profiler's
        # self-accounted busy/wall ratio — deterministic, and what the
        # karpenter_telemetry_profile_overhead_ratio gauge publishes
        iters = max(args.iters, 4)
        base = bench_once(args.pods, iters, args.solver)
        prof = obs.configure_profiler(hz=args.profile_hz or 19.0)
        withp = bench_once(args.pods, iters, args.solver)
        overhead_pct = prof.overhead_ratio() * 100
        samples = prof.snapshot(top_n=3)
        obs.shutdown_profiler(prof)
        ok = overhead_pct < 1.0
        print(json.dumps({
            "metric": f"profiler overhead ({args.pods} pods, {samples['hz']}Hz)",
            "value": round(overhead_pct, 4),
            "unit": "% sampler busy/wall",
            "profiler_overhead_pct": round(overhead_pct, 4),
            "profiler_overhead_ok": ok,
            "profile_samples": samples["samples"],
            "profile_top": samples["top"],
            "pods_per_sec_off": round(base["pods_per_sec"], 1),
            "pods_per_sec_on": round(withp["pods_per_sec"], 1),
            "throughput_delta_pct": round(
                (base["pods_per_sec"] - withp["pods_per_sec"])
                / base["pods_per_sec"] * 100, 2,
            ),
        }))
        sys.exit(0 if ok else 1)

    profiler = (
        obs.configure_profiler(hz=args.profile_hz) if args.profile_hz > 0 else None
    )

    if args.profile:
        import cProfile

        catalog = instance_types(400)
        provisioner = make_provisioner(solver=args.solver)
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = diverse_pods(args.pods, random.Random(42))
        scheduler = Scheduler(Cluster(), rng=random.Random(1))
        scheduler.solve(provisioner, catalog, pods)  # warm
        cProfile.runctx(
            "scheduler.solve(provisioner, catalog, pods)",
            globals(), locals(), filename=args.profile,
        )
        print(f"# wrote cProfile stats to {args.profile} "
              f"(inspect: python -m pstats {args.profile})", file=sys.stderr)
        return

    if args.all_configs:
        for cfg in (1, 2, 3, 4, 5):
            print(json.dumps(bench_config(cfg, max(args.iters, 2))))
        return
    if args.affinity_dense:
        r = bench_affinity_dense(args.affinity_dense, max(args.iters, 3))
        print(json.dumps({
            "metric": f"affinity-dense head-to-head ({args.affinity_dense} pods, "
                      f"{int(r['affinity_frac'] * 100)}% affinity)",
            "value": r["device_pods_per_sec"],
            "unit": "pods/sec (device path)",
            "vs_baseline": round(r["device_pods_per_sec"] / BASELINE_PODS_PER_SEC, 2),
            **{k: v for k, v in r.items() if k != "device_pods_per_sec"},
        }))
        return
    if args.router_parity:
        rows = bench_router_parity(max(args.iters, 2))
        ratios = [r["auto_vs_best"] for r in rows if "auto_vs_best" in r]
        ok = bool(ratios) and all(
            r.get("within_10pct", False) for r in rows if "auto_vs_best" in r
        )
        print(json.dumps({
            "metric": "router-parity (auto vs best forced backend, 5 BASELINE configs)",
            "value": round(min(ratios), 3) if ratios else 0.0,
            "unit": "worst auto/best ratio",
            "vs_baseline": 1.0,
            "router_parity_ok": ok,
        }))
        return
    if args.config:
        print(json.dumps(bench_config(args.config, max(args.iters, 2))))
        return

    if args.fleet_storm:
        r = bench_fleet_storm(
            args.fleet_storm,
            n_provisioners=args.fleet_provisioners or 8,
            n_replicas=args.fleet_replicas,
            pool_size=args.fleet_pool,
            solver=args.solver,
        )
        ok = (
            r["chaos_provision_success_rate"] == 1.0
            and r["duplicate_launches"] == 0
            and (r["rebalance_within_bar"] in (True, None))
        )
        print(json.dumps({
            "metric": (
                f"fleet-storm ({r['provisioners']} provisioners x "
                f"{r['replicas']} replicas x {r['pool_size']}-member pool, "
                "replica+sidecar kill)"
            ),
            "value": r["aggregate_pods_per_sec"],
            "unit": "aggregate pods/sec",
            "fleet_ok": ok,
            **{k: v for k, v in r.items() if k != "aggregate_pods_per_sec"},
        }))
        return

    if args.forecast_storm:
        r = bench_forecast_storm(
            duration_s=args.forecast_storm,
            n_provisioners=args.fleet_provisioners or 2,
            launch_latency_s=args.forecast_launch_latency,
            # host path: the leg measures launch economics, not packing
            # throughput — device compiles would only add settle noise
            solver="ffd",
            seed=args.forecast_seed,
        )
        ok = (
            r["duplicate_launches"] == 0
            and r["leaked_instances"] == 0
            and r["unresolved_journal_entries"] == 0
            and (r["spike_speedup_warm_vs_cold"] or 0) >= r["spike_speedup_bar"]
            and r["whatif_within_20pct"] in (True, None)
        )
        print(json.dumps({
            "metric": (
                f"forecast-storm ({r['duration_s']}s diurnal + flash "
                f"crowds, {r['launch_latency_s']}s launch latency, "
                "cold vs warm)"
            ),
            "value": r["warm_hit_rate"],
            "unit": "warm hit rate",
            "forecast_ok": ok,
            **{k: v for k, v in r.items() if k != "warm_hit_rate"},
        }))
        return

    if args.partition_storm:
        r = bench_partition_storm(
            args.partition_storm,
            n_provisioners=args.fleet_provisioners or 8,
            n_replicas=args.fleet_replicas,
            lease_duration=args.partition_lease_duration,
        )
        ok = (
            r["chaos_provision_success_rate"] == 1.0
            and r["duplicate_launches"] == 0
            and r["leaked_instances"] == 0
            and r["blip_rebalances"] == 0
            and r["blip_shard_losses"] == 0
            and r["all_replicas_fenced"]
            and r["fenced_mutations"] == 0
            and r["recovered_within_bar"]
        )
        print(json.dumps({
            "metric": (
                f"partition-storm ({r['provisioners']} provisioners x "
                f"{r['replicas']} replicas, blip + 429 brownout + "
                f"{r['blackout_s']}s blackout)"
            ),
            "value": r["chaos_provision_success_rate"],
            "unit": "provision success rate",
            "partition_ok": ok,
            **{k: v for k, v in r.items() if k != "chaos_provision_success_rate"},
        }))
        return

    if args.corruption_storm:
        r = bench_corruption_storm(
            args.corruption_storm,
            pool_size=args.fleet_pool,
            corrupt_rate=args.corrupt_rate,
            canary_rate=args.canary_rate,
            seed=args.chaos_seed,
        )
        ok = (
            r["corrupt_packs_bound"] == 0
            and r["detection_rate"] == 1.0
            and r["all_modes_quarantined"]
            and (r["quarantine_within_solves"] or 99) <= 5
            and r["chaos_provision_success_rate"] == 1.0
        )
        print(json.dumps({
            "metric": (
                f"corruption-storm ({r['pods']} pods, "
                f"{r['pool_size']}-member pool, "
                f"{len(r['per_mode'])} corruption modes, "
                "checksums + canary on)"
            ),
            "value": r["detection_rate"],
            "unit": "corruption detection rate (corrupt packs never bind)",
            "integrity_ok": ok,
            **{k: v for k, v in r.items() if k != "detection_rate"},
            "detection_rate": r["detection_rate"],
        }))
        return

    if args.delta_storm:
        r = bench_delta_storm(
            args.delta_storm,
            pool_size=args.fleet_pool,
            seed=args.chaos_seed,
        )
        ok = (
            r["stale_tensor_binds"] == 0
            and r["delta_provision_success_rate"] == 1.0
            # the refusals were COUNTED, not silent: chaos injected stale
            # epochs, so mismatches and their healing re-encodes must show
            and (r["injected_stale_deltas"] == 0
                 or (r["delta_epoch_mismatches"] > 0
                     and r["delta_full_reencodes"] > 0))
            # and the steady phase actually rode the delta path
            and r["delta_applied_steady_phase"] > 0
        )
        print(json.dumps({
            "metric": (
                f"delta-storm ({r['pods']} pods, "
                f"{r['pool_size']}-member pool, stale_delta injection + "
                "mid-round sidecar restart, resident delta encoding on)"
            ),
            "value": r["stale_tensor_binds"],
            "unit": "stale-tensor binds (bar: 0)",
            "delta_ok": ok,
            **{k: v for k, v in r.items() if k != "stale_tensor_binds"},
            "stale_tensor_binds": r["stale_tensor_binds"],
        }))
        return

    if args.regression_storm:
        r = bench_regression_storm(
            args.regression_storm,
            pool_size=args.fleet_pool,
            seed=args.chaos_seed,
        )
        ok = (
            r["steady_false_positives"] == 0
            and r["step_detected"]
            and r["step_attributed_wire_device"]
            and r["incident_evidence_complete"]
            and r["sentinel_overhead_ok"]
        )
        print(json.dumps({
            "metric": (
                f"regression-storm ({r['pods']} pods, "
                f"{r['pool_size']}-member pool, "
                f"{r['latency_step_s'] * 1e3:.0f}ms injected wire step)"
            ),
            "value": r["steady_false_positives"],
            "unit": "steady-phase false-positive incidents (bar: 0)",
            "sentinel_ok": ok,
            **{k: v for k, v in r.items() if k != "steady_false_positives"},
            "steady_false_positives": r["steady_false_positives"],
        }))
        sys.exit(0 if ok else 1)

    if args.overload_storm:
        r = bench_overload_storm(
            args.overload_storm, overload_factor=args.overload_factor,
            stream=args.overload_stream,
        )
        ok = (
            r["goodput_fraction_of_capacity"] >= 0.8
            and r["deadline_expired_dispatches"] == 0
            and r["batcher_depth_bounded"]
            and r["admission_depth_bounded"]
            and r["high_priority_success_rate"] == 1.0
            and r["breaker_trips_on_overload"] == 0
        )
        if args.overload_stream:
            # the stream-storm bar: the storm must actually have ridden
            # the stream (not silently fallen back to unary forever)
            ok = ok and r.get("stream_solves", 0) > 0
        print(json.dumps({
            "metric": (
                f"overload-storm ({r['pods']} pods at "
                f"{r['overload_factor']}x capacity, bounded batcher + "
                "sidecar admission + deadline sheds"
                + (", STREAMED transport" if args.overload_stream else "")
                + ")"
            ),
            "value": r["goodput_fraction_of_capacity"],
            "unit": "goodput fraction of single-rate capacity",
            "overload_ok": ok,
            **{k: v for k, v in r.items()
               if k != "goodput_fraction_of_capacity"},
            "goodput_fraction_of_capacity": r["goodput_fraction_of_capacity"],
        }))
        return

    if args.streamed:
        r = bench_streamed(args.streamed, iters=max(args.iters // 5, 4))
        ok = (
            r["streamed_rtt_floor_ms"]
            <= 0.5 * r["transport_rtt_floor_ms"]
            # the coalescer must actually have engaged during the
            # concurrent phase — a zero rate means the feature regressed
            and r["stream_coalesced_dispatch_rate"] > 0.0
            and "concurrent_errors" not in r
        )
        print(json.dumps({
            "metric": (
                f"streamed-transport ({r['pods']} pods, persistent "
                "multiplexed stream + shm arena + dispatch coalescing)"
            ),
            "value": r["streamed_pods_per_sec"],
            "unit": "pods/sec over the streamed transport",
            "streamed_ok": ok,
            **{k: v for k, v in r.items() if k != "streamed_pods_per_sec"},
            "streamed_pods_per_sec": r["streamed_pods_per_sec"],
        }))
        return

    if args.crash_storm:
        r = bench_crash_storm(
            args.crash_storm,
            n_provisioners=args.fleet_provisioners or 4,
            n_replicas=args.fleet_replicas,
            solver=args.solver,
        )
        ok = (
            r["chaos_provision_success_rate"] == 1.0
            and r["leaked_instances"] == 0
            and r["duplicate_launches"] == 0
            and r["adopted_within_gc_period"]
        )
        print(json.dumps({
            "metric": (
                f"crash-storm ({r['pods']} pods, {r['replicas']} replicas, "
                "kill mid-create + kill mid-bind)"
            ),
            "value": r["chaos_provision_success_rate"],
            "unit": "provision success rate with zero leaks",
            "crash_ok": ok,
            **{k: v for k, v in r.items()
               if k != "chaos_provision_success_rate"},
            "chaos_provision_success_rate": r["chaos_provision_success_rate"],
        }))
        return

    if args.consolidation_storm:
        r = bench_consolidation_storm(
            args.consolidation_storm,
            n_provisioners=args.fleet_provisioners or 2,
            n_replicas=args.fleet_replicas,
            budget=args.consolidation_budget,
            seed=args.chaos_seed,
            solver=args.solver,
        )
        ok = (
            r["consolidation_success_rate"] == 1.0
            and r["evicted_unready"] == 0
            and r["budget_violations"] == 0
            and r["leaked_instances"] == 0
            and r["duplicate_launches"] == 0
            and r["waves_replayed"] >= 1
            and r["consolidation_nodes_reclaimed"] > 0
        )
        print(json.dumps({
            "metric": (
                f"consolidation-storm ({r['pods']} pods, {r['replicas']} "
                f"replicas, budget {r['budget']}, mid-wave kill + "
                f"{int(r['error_rate'] * 100)}% cloud errors)"
            ),
            "value": r["consolidation_nodes_reclaimed"],
            "unit": "nodes reclaimed with zero unsafe evictions",
            "consolidation_ok": ok,
            **{k: v for k, v in r.items()
               if k != "consolidation_nodes_reclaimed"},
            "consolidation_nodes_reclaimed": r["consolidation_nodes_reclaimed"],
        }))
        return

    if args.chaos:
        r = bench_chaos(
            args.chaos,
            error_rate=args.chaos_error_rate,
            latency_p95=args.chaos_latency_p95,
            seed=args.chaos_seed,
        )
        ok = (
            r["chaos_provision_success_rate"] == 1.0
            and not r["breakers_open_after_storm"]
            and r["interruption_evicted_unready"] == 0
        )
        print(
            json.dumps(
                {
                    "metric": f"chaos provisioning ({args.chaos} pods, "
                              f"{int(args.chaos_error_rate * 100)}% API errors, "
                              f"{int(args.chaos_latency_p95 * 1000)}ms p95 injected)",
                    "value": r["chaos_provision_success_rate"],
                    "unit": "provision success rate under chaos",
                    "vs_baseline": 1.0 if ok else 0.0,
                    **{k: v for k, v in r.items()
                       if k != "chaos_provision_success_rate"},
                    "chaos_provision_success_rate": r["chaos_provision_success_rate"],
                }
            )
        )
        return

    if args.interruption_churn:
        r = bench_interruption_churn(args.interruption_churn)
        print(
            json.dumps(
                {
                    "metric": f"interruption churn ({args.interruption_churn} pods, "
                              f"{int(r['preempt_frac'] * 100)}% of nodes preempted "
                              f"x {r['rounds']} rounds)",
                    "value": r["interruption_evicted_unready"],
                    "unit": "pods evicted without replacement ready",
                    "vs_baseline": 1.0 if r["interruption_evicted_unready"] == 0 else 0.0,
                    **{k: v for k, v in r.items() if k != "interruption_evicted_unready"},
                    "interruption_evicted_unready": r["interruption_evicted_unready"],
                }
            )
        )
        return

    if args.selection_storm:
        r = bench_selection_storm(args.selection_storm)
        print(
            json.dumps(
                {
                    "metric": f"selection storm ({args.selection_storm} pod events end-to-end)",
                    "value": r["pods_per_sec"],
                    "unit": "pods bound/sec",
                    "vs_baseline": round(r["pods_per_sec"] / BASELINE_PODS_PER_SEC, 2),
                    **{k: v for k, v in r.items() if k != "pods_per_sec"},
                }
            )
        )
        return

    if args.diverse:
        r = bench_diverse(args.pods, args.diverse, max(args.iters, 2))
        print(
            json.dumps(
                {
                    "metric": f"constraint-diverse solve ({args.pods} pods, {args.diverse} selector values)",
                    "value": r["pods_per_sec"],
                    "unit": "pods/sec",
                    "vs_baseline": round(r["pods_per_sec"] / BASELINE_PODS_PER_SEC, 2),
                    **{k: v for k, v in r.items() if k != "pods_per_sec"},
                }
            )
        )
        return

    if args.multi:
        r = bench_multi_provisioner(args.multi, args.pods, max(args.iters, 2))
        print(
            json.dumps(
                {
                    "metric": f"multi-provisioner sharded solve ({args.multi} x {args.pods} pods)",
                    "value": round(r["pods_per_sec"], 1),
                    "unit": "pods/sec",
                    "vs_baseline": round(r["pods_per_sec"] / BASELINE_PODS_PER_SEC, 2),
                    **{k: v for k, v in r.items() if k != "pods_per_sec"},
                }
            )
        )
        return

    if args.consolidation:
        r = bench_consolidation(args.consolidation, args.iters, args.solver)
        print(
            json.dumps(
                {
                    "metric": f"consolidation re-pack ({args.consolidation} nodes, {args.solver} solver)",
                    "value": round(r["repack_s"] * 1e3, 1),
                    "unit": "ms/re-pack",
                    "vs_baseline": round((r["pods"] / max(r["repack_s"], 1e-9)) / BASELINE_PODS_PER_SEC, 2),
                    **{k: v for k, v in r.items() if k != "repack_s"},
                }
            )
        )
        return

    if args.grid:
        for n in [1, 50, 100, 500, 1000, 2000, 5000]:
            r = bench_once(n, max(args.iters, 2), args.solver)
            print(
                f"# {n:>5} pods × 400 types: {r['pods_per_sec']:>10,.0f} pods/sec "
                f"({r['nodes']} nodes, mean {r['mean_s'] * 1e3:.1f}ms)",
                file=sys.stderr,
            )

    # THE HEADLINE IS THE PRODUCT: `auto`, cost-routed (solver/router.py).
    # With a TPU attached, the router sends these shapes wherever measured
    # cost says — the device-forced leg below keeps the on-chip path's own
    # latency story measured with per-solve wire telemetry.
    bench_t0 = time.monotonic()
    # optional legs stop starting once this much wall time is spent, so the
    # record line always lands even if the harness caps the run (override
    # with BENCH_BUDGET_S)
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1200"))
    # the decision plane rides the headline leg in its production shape
    # (per-round records into an on-disk ring) unless --no-explain: the
    # record line carries its self-accounted explain_overhead_pct (<1 bar)
    import tempfile as _tempfile

    _explain_ctx = (
        _tempfile.TemporaryDirectory() if not args.no_explain else None
    )
    try:
        r = bench_once(
            args.pods, args.iters, args.solver,
            breakdown=args.solver == "tpu", wire_telemetry=args.solver == "tpu",
            record_decisions=_explain_ctx.name if _explain_ctx else "",
            delta=not args.no_solver_delta,
        )
    finally:
        if _explain_ctx is not None:
            _explain_ctx.cleanup()
    line = {
        "metric": f"pods-scheduled/sec ({args.pods} pods x 400 instance types, {args.solver} solver, cost-routed)",
        "value": round(r["pods_per_sec"], 1),
        "unit": "pods/sec",
        "vs_baseline": round(r["pods_per_sec"] / BASELINE_PODS_PER_SEC, 2),
        "nodes": r["nodes"],
        "scheduled_pods": r["scheduled"],
        "mean_solve_s": round(r["mean_s"], 4),
        "p99_solve_s": round(r["p99_s"], 4),
        "unschedulable_expected": r["unschedulable_expected"],
        "unexplained": r["unexplained"],
    }
    line["trace_enabled"] = obs.enabled()
    from karpenter_tpu.obs import decisions as _dec_mod

    line["explain_enabled"] = _dec_mod.enabled()
    for k in ("explain_overhead_pct", "explain_rounds",
              "decision_records_written"):
        if k in r:
            line[k] = r[k]
    if profiler is not None:
        # the always-on profiler's cost over the measured headline leg —
        # self-accounted busy/wall, the <1% acceptance bar
        psnap = profiler.snapshot(top_n=3)
        line["profiler_overhead_pct"] = round(psnap["overhead_ratio"] * 100, 4)
        line["profile_samples"] = psnap["samples"]
        line["profile_top"] = psnap["top"]
    for k in ("packer_backend", "wire_in_path", "breakdown_ms", "worst_iter",
              "host_share_ms", "delta_hit_rate",
              "trace_critical_path_ms",
              "slo_solve_p99_ok", "slo_solve_p99_s",
              "slo_online_offline_delta_pct", "slo_burn_rates",
              "transport_rtt_floor_ms", "rtt_samples", "rtt_p50_ms",
              "rtt_per_solve_samples", "p99_minus_rtt_each_s",
              "p90_minus_rtt_each_s", "mean_minus_rtt_each_s",
              "p99_minus_rtt_s", "p90_minus_rtt_s", "mean_minus_rtt_s",
              "mean_minus_rtt_p50_s", "p90_minus_rtt_p50_s"):
        if k in r:
            line[k] = r[k]
    if args.solver == "tpu":
        # a PROVISIONAL record line before the optional legs: the driver
        # parses the LAST JSON line of output, so if the harness caps the
        # run mid-leg the headline capture still exists
        print(json.dumps({**line, "provisional": True}), flush=True)

        def budget_left() -> bool:
            return time.monotonic() - bench_t0 < budget_s

        # on-device kernel parity gates every bench run (CI is CPU-only)
        line["onchip_parity"] = onchip_parity_check()
        def skip(leg: str) -> None:
            line.setdefault("skipped_legs", []).append(leg)

        # the device path's own latency story, measured with PER-SOLVE wire
        # telemetry (each sample subtracts its own adjacent transport
        # measurement — VERDICT r4 ask #3)
        try:
            dev = bench_once(
                args.pods, max(2, args.iters // 2), "tpu",
                breakdown=True, packer="fused", wire_telemetry=True,
                delta=not args.no_solver_delta,
            )
            for k in ("pods_per_sec", "mean_s", "p99_s",
                      "host_share_ms", "delta_hit_rate",
                      "rtt_per_solve_samples", "mean_minus_rtt_each_s",
                      "p90_minus_rtt_each_s", "p99_minus_rtt_each_s",
                      "worst_iter", "trace_critical_path_ms",
                      "slo_solve_p99_ok", "slo_solve_p99_s",
                      "slo_online_offline_delta_pct", "slo_burn_rates"):
                if k in dev:
                    line[f"device_{k}"] = (
                        round(dev[k], 4) if isinstance(dev[k], float) else dev[k]
                    )
            if "session_catalog_hit_rate" in dev:
                # the device_pipelined leg refines this with its own window
                line["session_catalog_hit_rate"] = dev["session_catalog_hit_rate"]
        except Exception as e:
            line["device_error"] = str(e)[:120]
        # apples-to-apples: the same scenario through the native C++ packer
        try:
            cpu = bench_once(args.pods, max(2, args.iters // 2), "tpu", packer="native")
            line["cpu_native_pods_per_sec"] = round(cpu["pods_per_sec"], 1)
            line["cpu_native_p99_s"] = round(cpu["p99_s"], 4)
        except Exception as e:
            line["cpu_native_error"] = str(e)[:120]
        # stitched-attribution leg (docs/telemetry.md): a live gRPC sidecar,
        # the sidecar's real sidecar.pack trees re-joined into their
        # solver.wire parents — the fleet-wide critical path the streaming-
        # transport work (ROADMAP item 2) will be judged against
        if not budget_left():
            skip("stitched")
        else:
            try:
                st = bench_stitched(min(args.pods, 2000), 4)
                line["stitched_joins"] = st["stitched_joins"]
                if "fleet_critical_path_ms" in st:
                    line["fleet_critical_path_ms"] = st["fleet_critical_path_ms"]
                    line["fleet_critical_path"] = st["fleet_critical_path"]
                if "wire_share_pct" in st:
                    line["wire_share_pct"] = st["wire_share_pct"]
                    line["stitched_wire_attribution"] = st["wire_attribution"]
            except Exception as e:
                line["stitched_error"] = str(e)[:120]
        print(json.dumps({**line, "provisional": True}), flush=True)
        # continuous-load pipelined throughput in all three modes, each
        # with controller-CPU accounting: host CPU-seconds per solve is the
        # measured offload claim (VERDICT r4 ask #2)
        pipe = bench_pipelined(args.pods, streams=3, iters=max(2, args.iters // 2))
        line["pipelined_pods_per_sec"] = pipe["pods_per_sec"]
        line["pipelined_streams"] = pipe["streams"]
        if "trace_overlap_pairs" in pipe:
            # nonzero = the encode(i+1)/solve(i) overlap invariant held
            line["pipelined_trace_overlap_pairs"] = pipe["trace_overlap_pairs"]
        line["pipelined_unschedulable_expected"] = pipe["unschedulable_expected"]
        line["pipelined_unexplained"] = pipe["unexplained"]
        cpu_per_solve = {"auto": pipe["controller_cpu_seconds_per_solve"]}
        cpu_util = {"auto": pipe["controller_cpu_utilization"]}
        try:
            dev_pipe = bench_pipelined(
                args.pods, streams=3, iters=max(2, args.iters // 2), packer="fused"
            )
            line["device_pipelined_pods_per_sec"] = dev_pipe["pods_per_sec"]
            cpu_per_solve["device"] = dev_pipe["controller_cpu_seconds_per_solve"]
            cpu_util["device"] = dev_pipe["controller_cpu_utilization"]
            if "session_catalog_hit_rate" in dev_pipe:
                # steady-state session residency on the device-forced
                # continuous-load leg — the ≥0.95 acceptance bar
                line["session_catalog_hit_rate"] = dev_pipe["session_catalog_hit_rate"]
        except Exception as e:
            line["device_pipelined_error"] = str(e)[:120]
        try:
            cpu_pipe = bench_pipelined(
                args.pods, streams=3, iters=max(2, args.iters // 2), packer="native"
            )
            line["cpu_native_pipelined_pods_per_sec"] = cpu_pipe["pods_per_sec"]
            cpu_per_solve["native"] = cpu_pipe["controller_cpu_seconds_per_solve"]
            cpu_util["native"] = cpu_pipe["controller_cpu_utilization"]
            line["tpu_vs_cpu_pipelined"] = round(
                pipe["pods_per_sec"] / cpu_pipe["pods_per_sec"], 3
            )
        except Exception as e:
            line["cpu_native_pipelined_error"] = str(e)[:120]
        line["controller_cpu_seconds_per_solve"] = cpu_per_solve
        line["controller_cpu_utilization"] = cpu_util
        if "device" in cpu_per_solve and "native" in cpu_per_solve:
            # the offload claim, quantified: host CPU the device path frees
            # per solve vs the native pack (negative = it COSTS host CPU)
            line["controller_cpu_offload_per_solve_s"] = round(
                cpu_per_solve["native"] - cpu_per_solve["device"], 5
            )
        if "cpu_native_pods_per_sec" in line:
            line["tpu_pipelined_vs_cpu_native"] = round(
                pipe["pods_per_sec"] / line["cpu_native_pods_per_sec"], 3
            )
        print(json.dumps({**line, "provisional": True}), flush=True)
        # batched multi-solve, TPU vs CPU on identical workloads
        # (VERDICT r3 ask #4)
        if not budget_left():
            skip("multi")
        else:
            try:
                m = bench_multi_provisioner(32, 1250, 4)
                line["multi_b"] = m["provisioners"]
                line["multi_tpu_pods_per_sec"] = m.get("multi_tpu_pods_per_sec")
                line["multi_tpu_raw_pods_per_sec"] = round(m["pods_per_sec"], 1)
                line["multi_cpu_pods_per_sec"] = m.get("multi_cpu_pods_per_sec")
                line["multi_tpu_wins"] = m.get("multi_tpu_wins")
                line["multi_unschedulable_expected"] = m["unschedulable_expected"]
                line["multi_unexplained"] = m["unexplained"]
            except Exception as e:
                line["multi_error"] = str(e)[:120]
        print(json.dumps({**line, "provisional": True}), flush=True)
        # the r5 #1a done-bar rides the default line: auto (cost-routed)
        # within 10% of the best forced backend on all five BASELINE configs
        if not budget_left():
            skip("router_parity")
        else:
            try:
                rp = bench_router_parity(2, emit=None)
                ratios = {
                    f"config{r['config']}": r["auto_vs_best"]
                    for r in rp if "auto_vs_best" in r
                }
                line["router_parity"] = ratios
                line["router_parity_ok"] = bool(ratios) and all(
                    r.get("within_10pct", False) for r in rp if "auto_vs_best" in r
                )
            except Exception as e:
                line["router_parity_error"] = str(e)[:120]
        print(json.dumps({**line, "provisional": True}), flush=True)
        # the r5 #1b axis: the affinity-dense regime, head-to-head on
        # identical work (docs/affinity-regime.md is the analysis)
        if not budget_left():
            skip("affinity_dense")
        else:
            try:
                ad = bench_affinity_dense(args.pods, 3)
                line["affinity_dense"] = {
                    "device_pods_per_sec": ad["device_pods_per_sec"],
                    "native_pods_per_sec": ad["native_pods_per_sec"],
                    "tpu_wins": ad["tpu_wins"],
                    "device_inject_ms": ad["device_stages_ms"].get("inject_s"),
                    "native_inject_ms": ad["native_stages_ms"].get("inject_s"),
                    "device_pack_fetch_ms": ad["device_stages_ms"].get("pack_fetch_s"),
                    "native_pack_fetch_ms": ad["native_stages_ms"].get("pack_fetch_s"),
                    "unexplained": ad["unexplained"],
                }
            except Exception as e:
                line["affinity_dense_error"] = str(e)[:120]
        print(json.dumps({**line, "provisional": True}), flush=True)
        # LAST leg: the DEDICATED on-chip suite (incl. the S=128 stress
        # tests the CPU suite skips) in a subprocess, so on-chip CI is an
        # every-round artifact, not a scheduled workflow nobody triggers
        # (VERDICT r4 missing #3). It gets its own EXTENDED allowance —
        # being the priority artifact, it must not be the first casualty
        # of a tight budget.
        if time.monotonic() - bench_t0 > budget_s + 300:
            skip("onchip_suite")
        else:
            import subprocess

            try:
                proc = subprocess.run(
                    [sys.executable, "-m", "pytest",
                     "tests/test_pallas_kernel.py", "tests/test_fused_solve.py",
                     "-q", "--no-header", "-p", "no:cacheprovider"],
                    env={**os.environ, "KARPENTER_TEST_TPU": "1"},
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                    capture_output=True, text=True, timeout=600,
                )
                tail = (proc.stdout or proc.stderr).strip().splitlines()
                line["onchip_suite"] = tail[-1].strip()[:160] if tail else "no output"
                line["onchip_suite_ok"] = proc.returncode == 0
            except Exception as e:
                line["onchip_suite"] = f"error: {e}"[:120]
                line["onchip_suite_ok"] = False
    print(json.dumps(line))


if __name__ == "__main__":
    main()
