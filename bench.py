#!/usr/bin/env python
"""Headline benchmark: pods-scheduled/sec on the TPU batch solver.

Reproduces the reference's scheduler benchmark scenario
(``scheduling_benchmark_test.go``: 400 fake instance types × diverse pod mix)
against the TPU solve path, and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "pods/sec", "vs_baseline": N, ...}

Baseline: the reference enforces ≥250 pods/sec on batches >100 pods
(scheduling_benchmark_test.go:47,151-155); vs_baseline = value / 250.

Run: python bench.py [--pods N] [--iters K] [--grid]
"""

import argparse
import json
import math
import random
import statistics
import sys
import time

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.testing import diverse_pods, make_provisioner

BASELINE_PODS_PER_SEC = 250.0  # reference's enforced CPU floor


def bench_once(n_pods: int, iters: int, solver: str = "tpu"):
    catalog = instance_types(400)
    provisioner = make_provisioner(solver=solver)
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = diverse_pods(n_pods, random.Random(42))
    scheduler = Scheduler(Cluster(), rng=random.Random(1))

    # warmup (compile)
    nodes = scheduler.solve(provisioner, catalog, pods)
    assert nodes, "benchmark scenario must schedule"

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        nodes = scheduler.solve(provisioner, catalog, pods)
        times.append(time.perf_counter() - t0)
    scheduled = sum(len(n.pods) for n in nodes)
    best = min(times)
    return {
        "pods_per_sec": scheduled / best,
        "mean_s": statistics.mean(times),
        "p99_s": sorted(times)[min(len(times) - 1, max(math.ceil(0.99 * len(times)) - 1, 0))],
        "nodes": len(nodes),
        "scheduled": scheduled,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=2000)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--solver", default="tpu", choices=["tpu", "ffd"])
    ap.add_argument("--grid", action="store_true", help="run the reference's full batch grid")
    args = ap.parse_args()

    if args.grid:
        for n in [1, 50, 100, 500, 1000, 2000, 5000]:
            r = bench_once(n, max(args.iters, 2), args.solver)
            print(
                f"# {n:>5} pods × 400 types: {r['pods_per_sec']:>10,.0f} pods/sec "
                f"({r['nodes']} nodes, mean {r['mean_s'] * 1e3:.1f}ms)",
                file=sys.stderr,
            )

    r = bench_once(args.pods, args.iters, args.solver)
    print(
        json.dumps(
            {
                "metric": f"pods-scheduled/sec ({args.pods} pods x 400 instance types, {args.solver} solver)",
                "value": round(r["pods_per_sec"], 1),
                "unit": "pods/sec",
                "vs_baseline": round(r["pods_per_sec"] / BASELINE_PODS_PER_SEC, 2),
                "nodes": r["nodes"],
                "scheduled_pods": r["scheduled"],
                "mean_solve_s": round(r["mean_s"], 4),
                "p99_solve_s": round(r["p99_s"], 4),
            }
        )
    )


if __name__ == "__main__":
    main()
