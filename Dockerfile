# Multi-stage image for all three entrypoints — controller, webhook, and
# TPU solver sidecar — selected by command (deploy/*.yaml set it)
# (reference: the ko-built controller/webhook images, Makefile:22-42).

# Stage 1: compile the native CPU packer
FROM python:3.12-slim AS build
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /src/native
COPY native/ffd_pack.cpp .
RUN g++ -O3 -shared -fPIC -o libffd_pack.so ffd_pack.cpp

# Stage 1.5: static analysis gate — karplint is stdlib-only, so the bare
# slim image (no jax, no prometheus) can run the full rule set: the rule
# corpus must fire, the tree must be clean. A dirty tree fails the build
# before the runtime stage ever assembles.
FROM python:3.12-slim AS analyze
WORKDIR /app
COPY tools/ tools/
COPY karpenter_tpu/ karpenter_tpu/
COPY docs/metrics.md docs/metrics.md
COPY tests/karplint_fixtures/ tests/karplint_fixtures/
RUN python -m tools.karplint --selftest tests/karplint_fixtures \
    && python -m tools.karplint karpenter_tpu \
    && touch /analyze.ok

# Stage 2: runtime
FROM python:3.12-slim
# jax[tpu] pulls libtpu for real chips; CPU-only environments still work
# (JAX_PLATFORMS=cpu). grpcio serves the solver transport; cryptography
# self-manages the webhook serving cert.
RUN pip install --no-cache-dir \
    "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    grpcio prometheus-client cryptography numpy \
    || pip install --no-cache-dir jax grpcio prometheus-client cryptography numpy
WORKDIR /app
COPY karpenter_tpu/ karpenter_tpu/
# the ctypes loader resolves <root>/native/libffd_pack.so relative to the
# package (solver/native.py); ship source + prebuilt so no g++ is needed
COPY native/ffd_pack.cpp native/
COPY --from=build /src/native/libffd_pack.so native/
# the analyze stage gates the image: this COPY forces it to run (and pass)
COPY --from=analyze /analyze.ok /tmp/analyze.ok
ENV PYTHONPATH=/app
ENV PYTHONUNBUFFERED=1
USER 65532:65532
# default: the controller; webhook/solver Deployments override command
CMD ["python", "-m", "karpenter_tpu.main"]
