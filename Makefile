# Developer workflow — the reference's Makefile targets, adapted
# (reference: Makefile:22-42 dev/ci/test/battletest/benchmark/deflake).

PY ?= python
TESTFLAGS ?= -q

dev: analyze test  ## everything a presubmit needs

test:  ## unit + integration suites (tier-1: slow soak/chaos legs excluded)
	$(PY) -m pytest tests/ -x -m 'not slow' $(TESTFLAGS)

analyze:  ## karplint gate: prove every rule fires on the corpus, then require a clean tree
	$(PY) -m tools.karplint --selftest tests/karplint_fixtures
	$(PY) -m tools.karplint karpenter_tpu

analyze-baseline:  ## regenerate tools/karplint/baseline.json (P0 findings are never baselined)
	$(PY) -m tools.karplint --write-baseline karpenter_tpu

lint: analyze  ## ruff + mypy + karplint; ruff/mypy skip with a notice when not installed
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
		$(PY) -m ruff check karpenter_tpu tools bench.py; \
	else echo "lint: ruff not installed, skipping (pip install ruff)"; fi
	@if $(PY) -m mypy --version >/dev/null 2>&1; then \
		$(PY) -m mypy karpenter_tpu; \
	else echo "lint: mypy not installed, skipping (pip install mypy)"; fi

battletest:  ## full suite without fail-fast + duration report (the -race analog)
	$(PY) -m pytest tests/ $(TESTFLAGS) --durations=10

deflake:  ## run the suite 10x to shake out flakes (reference: Makefile:38-39)
	for i in 1 2 3 4 5 6 7 8 9 10; do \
		$(PY) -m pytest tests/ -x -q || exit 1; \
	done

benchmark:  ## headline solve benchmark (prints one JSON line) + trajectory report
	$(PY) bench.py
	-$(PY) -m tools.bench_compare --report

bench-compare:  ## regression gate over the checked-in BENCH_r0x trajectory (CI runs this)
	$(PY) -m tools.bench_compare

benchmark-notrace:  ## tracing-overhead comparison run (acceptance bar: native leg within 3%)
	$(PY) bench.py --no-trace

profile-smoke:  ## profiler-overhead gate: headline leg with and without the sampling profiler (<1% self-accounted bar)
	$(PY) bench.py --profile-overhead-check --pods 2000 --iters 6 --solver ffd

explain-smoke:  ## explain-overhead gate: per-round decision records + attribution vs --no-explain (<1% self-accounted bar)
	$(PY) bench.py --explain-overhead-check --pods 4000 --iters 6

benchmark-grid:  ## the reference's full batch grid
	$(PY) bench.py --grid

benchmark-consolidation:  ## BASELINE config 5: 1k-node re-pack
	$(PY) bench.py --consolidation 1000

benchmark-storm:  ## 10k pod watch events through the full pipeline
	$(PY) bench.py --selection-storm 10000

benchmark-multi:  ## BASELINE config 4: concurrent provisioner batches on the mesh
	$(PY) bench.py --multi 8 --pods 1250

benchmark-router-parity:  ## auto (cost-routed) vs best forced backend, 5 BASELINE configs
	$(PY) bench.py --router-parity

benchmark-affinity-dense:  ## device vs native head-to-head on the 50%-affinity regime
	$(PY) bench.py --affinity-dense 10000

chaos:  ## seeded chaos suite + the bench chaos leg (success-rate done-bar: 1.0)
	$(PY) -m pytest tests/test_chaos.py tests/test_resilience.py -q $(TESTFLAGS)
	$(PY) bench.py --chaos 300

fleet-chaos:  ## fleet HA proof: shard/pool suites + the replica+sidecar-kill storm leg
	$(PY) -m pytest tests/test_fleet.py tests/test_fleet_pool.py -q -m 'not slow' $(TESTFLAGS)
	$(PY) bench.py --fleet-storm 120 --solver tpu

crash-chaos:  ## crash-consistency proof: journal/GC suites + the kill-mid-create storm leg
	$(PY) -m pytest tests/test_launch_journal.py -q -m 'not slow' $(TESTFLAGS)
	$(PY) bench.py --crash-storm 200 --solver ffd

overload-chaos:  ## overload-control proof: shed/brownout suites + the >=5x offered-load storm leg
	$(PY) -m pytest tests/test_overload.py -q -m 'not slow' $(TESTFLAGS)
	$(PY) bench.py --overload-storm 300

benchmark-streamed:  ## streamed-transport leg: unary vs streamed RTT floors, shm sub-leg, coalescing rate
	$(PY) bench.py --streamed 2000 --iters 20

stream-chaos:  ## streamed-transport proof: stream lifecycle suite + the >=5x overload storm OVER the stream
	$(PY) -m pytest tests/test_solver_stream.py -q -m 'not slow' $(TESTFLAGS)
	$(PY) bench.py --overload-storm 300 --overload-stream

corruption-chaos:  ## pack-integrity proof: checksum/canary/quarantine suites + the 5-mode corruption storm leg
	$(PY) -m pytest tests/test_integrity.py tests/test_serde_fuzz.py -q -m 'not slow' $(TESTFLAGS)
	$(PY) bench.py --corruption-storm 200

delta-chaos:  ## resident-delta proof: parity/epoch-guard/residency suites + the stale_delta + restart storm leg
	$(PY) -m pytest tests/test_delta.py tests/test_serde_fuzz.py -q -m 'not slow' $(TESTFLAGS)
	$(PY) bench.py --delta-storm 240

partition-chaos:  ## control-plane partition proof: transport/fencing suites + the apiserver blip/brownout/blackout storm leg
	$(PY) -m pytest tests/test_partition.py -q -m 'not slow' $(TESTFLAGS)
	$(PY) bench.py --partition-storm 240

consolidation-chaos:  ## disruption-safe consolidation proof: budget/repack/wave suites + the mid-wave-kill re-pack storm leg
	$(PY) -m pytest tests/test_consolidation.py tests/test_disruption_budget.py -q -m 'not slow' $(TESTFLAGS)
	$(PY) bench.py --consolidation-storm 48 --solver ffd

FORECAST_STORM_S ?= 30
forecast-chaos:  ## predictive-provisioning proof: forecast/warm-pool/what-if suites + the diurnal+flash storm leg, cold vs warm
	$(PY) -m pytest tests/test_forecast.py tests/test_warmpool.py tests/test_whatif.py -q -m 'not slow' $(TESTFLAGS)
	$(PY) bench.py --forecast-storm $(FORECAST_STORM_S)

sentinel-chaos:  ## regression-sentinel proof: detector/incident/persistence suites + the injected-latency-step storm leg (bars: 0 steady false positives, step detected + attributed, evidence complete)
	$(PY) -m pytest tests/test_sentinel.py -q -m 'not slow' $(TESTFLAGS)
	$(PY) bench.py --regression-storm 80

sentinel-smoke:  ## sentinel-overhead gate: headline leg with and without the regression sentinel hooked (<1% self-accounted bar)
	$(PY) bench.py --sentinel-overhead-check --pods 2000 --iters 6 --solver ffd

dryrun-multichip:  ## validate the multi-chip sharding on a virtual CPU mesh
	env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
		XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) __graft_entry__.py

envtest:  ## boot a REAL kube-apiserver (kubebuilder-tools) and run the conformance suite against it
	hack/envtest.sh

image:  ## build the container image (controller + webhook + solver entrypoints)
	docker build -t karpenter-tpu:latest .

chart:  ## render the chart (helm-compatible templates; no helm needed)
	$(PY) hack/render_chart.py charts/karpenter-tpu

apply:  ## render + apply the chart to the current cluster
	$(PY) hack/render_chart.py charts/karpenter-tpu | kubectl apply -f -

webhook-certs:  ## generate CA+serving cert into CERTS_DIR and print install steps
	$(PY) hack/gen_webhook_certs.py $(or $(CERTS_DIR),webhook-certs)

webhook-cabundle:  ## inject a generated CA into deploy/webhook.yaml (CA=path/to/ca.crt)
	@$(PY) -c 'import sys; from karpenter_tpu.kube.certs import ca_bundle_b64; \
		m = open("deploy/webhook.yaml").read(); \
		sys.stdout.write(m.replace("$${CA_BUNDLE}", ca_bundle_b64("$(CA)")))'

run:  ## start the controller process against the in-memory cluster
	$(PY) -m karpenter_tpu.main

solver-sidecar:  ## start the TPU solver sidecar
	$(PY) -m karpenter_tpu.solver.service

.PHONY: dev test analyze analyze-baseline lint battletest deflake benchmark bench-compare benchmark-notrace profile-smoke benchmark-grid \
	benchmark-consolidation benchmark-storm benchmark-router-parity benchmark-affinity-dense benchmark-streamed chaos fleet-chaos crash-chaos overload-chaos stream-chaos corruption-chaos delta-chaos partition-chaos consolidation-chaos forecast-chaos sentinel-chaos sentinel-smoke dryrun-multichip run solver-sidecar \
	image chart apply webhook-certs webhook-cabundle
