"""Process wiring: build the manager with all controllers registered.

Mirrors ``cmd/controller/main.go:67-105``: options → cloud provider from the
registry → manager → register the controllers (provisioning, selection, pvc,
termination, interruption, node, consolidation, metrics-pod, metrics-node,
counter) with their watches → start. ``run_controller_process`` is the
``main()`` equivalent; it
returns the assembled runtime so embedding callers (tests, simulations, a
real-apiserver deployment shim) can drive or stop it.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from karpenter_tpu.cloudprovider import metrics as cpmetrics
from karpenter_tpu.cloudprovider import registry
from karpenter_tpu.cloudprovider.types import CloudProvider
from karpenter_tpu.controllers.consolidation import ConsolidationController
from karpenter_tpu.controllers.counter import CounterController
from karpenter_tpu.controllers.garbage_collection import GarbageCollectionController
from karpenter_tpu.controllers.interruption import InterruptionController
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.metrics_node import NodeMetricsController
from karpenter_tpu.controllers.metrics_pod import PodMetricsController
from karpenter_tpu.controllers.node import NodeController
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.pvc import PVCController
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.controllers.termination import TerminationController
from karpenter_tpu.controllers.warmpool import WarmPoolController
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.options import Options
from karpenter_tpu.webhook import Webhook

logger = logging.getLogger("karpenter")


@dataclass
class Runtime:
    """Everything a running controller process owns."""

    options: Options
    cluster: Cluster
    cloud_provider: CloudProvider
    manager: Manager
    provisioning: ProvisioningController
    selection: SelectionController
    termination: TerminationController
    interruption: InterruptionController
    webhook: Webhook
    garbage_collection: GarbageCollectionController = None
    journal: object = None  # LaunchJournal when --launch-journal is set
    servers: list = None  # HTTP servers (metrics, health) when serving
    elector: object = None  # LeaderElector when a lease is configured
    ownership: object = None  # fleet.ShardManager when shard leases are configured
    log_watcher: object = None  # LogLevelWatcher when a config file is set
    slo: object = None  # the SloEngine THIS runtime installed (obs/slo.py)
    profiler: object = None  # the SamplingProfiler THIS runtime installed
    telemetry: object = None  # the TelemetryPlane THIS runtime installed
    brownout: object = None  # BrownoutController when --brownout is on
    warmpool: WarmPoolController = None  # when --warm-pool is on
    forecast: object = None  # the ArrivalForecaster THIS runtime installed
    sentinel: object = None  # the SentinelEngine THIS runtime installed
    consolidation: ConsolidationController = None
    _gc_freeze_cancel: object = None  # set by _freeze_gc_when_warm

    def stop(self) -> None:
        if self._gc_freeze_cancel is not None:
            # cancel BEFORE restore: a freeze landing after restore() would
            # leak the frozen heap this stop exists to undo
            self._gc_freeze_cancel.set()
        if self.ownership is not None:
            # releases every shard lease (and fires on_lost per shard) so
            # survivors rebalance immediately instead of waiting out the
            # lease duration; a crash()-ed manager skips the release
            self.ownership.stop()
        if self.brownout is not None:
            # stops the ladder AND fully reverses it: a stopped replica
            # leaves no degradation behind (resilience/brownout.py)
            self.brownout.stop()
        self.manager.stop()
        self.provisioning.stop()
        self.termination.stop()
        for server in self.servers or []:
            server.shutdown()
        if self.elector is not None:
            self.elector.stop()
        if self.log_watcher is not None:
            self.log_watcher.stop()
        if hasattr(self.cluster, "stop"):
            self.cluster.stop()
        # detach the SLO engine this runtime installed (ownership-checked:
        # if a later-started replica's engine is current, it stays; a
        # runtime that never installed one detaches nothing)
        if self.slo is not None:
            from karpenter_tpu import obs

            obs.shutdown_slo(engine=self.slo)
        # detach the arrival forecaster this runtime installed (same
        # ownership-checked discipline)
        if self.forecast is not None:
            from karpenter_tpu import obs

            obs.shutdown_forecast(engine=self.forecast)
        # detach the regression sentinel this runtime installed (same
        # discipline; shutdown final-persists its baselines)
        if self.sentinel is not None:
            from karpenter_tpu import obs

            obs.shutdown_sentinel(engine=self.sentinel)
        # same ownership-checked teardown for the profiler and the
        # telemetry plane this runtime installed
        if self.profiler is not None or self.telemetry is not None:
            from karpenter_tpu import obs

            if self.profiler is not None:
                obs.shutdown_profiler(self.profiler)
            if self.telemetry is not None:
                obs.shutdown_telemetry(self.telemetry)
        # undo the post-warmup GC policy: a test booting a runtime
        # in-process must not leak a frozen heap into the rest of the run
        from karpenter_tpu.utils.gcpolicy import restore

        restore()


def _freeze_gc_when_warm(runtime: Runtime, timeout: float = 300.0) -> None:
    """Apply the GC freeze policy once the first provisioning worker has
    warmed (its solve compiled — the warm heap now exists). Waits in a
    daemon thread; gives up silently after ``timeout`` (no provisioner ever
    applied → nothing worth freezing). ``Runtime.stop`` cancels the wait —
    a freeze landing after stop's restore() would leak the frozen heap."""
    import threading
    import time as _t

    from karpenter_tpu.utils.gcpolicy import freeze_after_warmup

    cancel = runtime._gc_freeze_cancel = threading.Event()

    def wait() -> None:
        deadline = _t.monotonic() + timeout
        while _t.monotonic() < deadline and not cancel.is_set():
            workers = list(getattr(runtime.provisioning, "workers", {}).values())
            if any(w.warmed.is_set() for w in workers):
                # cancel is re-checked under gcpolicy's lock: stop() sets
                # the event BEFORE calling restore, so a freeze can never
                # land after restore
                freeze_after_warmup(unless=cancel)
                return
            cancel.wait(1.0)

    threading.Thread(target=wait, name="gc-freeze-when-warm", daemon=True).start()


def _serve_endpoints(runtime: Runtime) -> None:
    """Prometheus registry on :metrics_port, healthz/readyz on
    :health_probe_port (reference: cmd/controller/main.go:86-89,
    controllers/manager.go:54-59)."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from prometheus_client import start_http_server

    from karpenter_tpu import metrics as m

    metrics_server, _ = start_http_server(
        runtime.options.metrics_port, registry=m.REGISTRY
    )

    manager = runtime.manager

    class HealthHandler(BaseHTTPRequestHandler):
        timeout = 10  # a stalled probe client must not wedge the server

        def _send(self, body: bytes, ctype: str = "application/json"):
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            # every /debug/* body comes from a shared obs.debug_*_payload
            # helper — the sidecar health server serves the SAME bodies
            # (karplint `debug-endpoint` keeps the parity from drifting)
            import json
            from urllib.parse import urlsplit

            from karpenter_tpu import obs

            query = urlsplit(self.path).query
            if self.path in ("/healthz", "/readyz"):
                ok = manager.healthz()
                self.send_response(200 if ok else 503)
                self.end_headers()
                self.wfile.write(b"ok" if ok else b"unhealthy")
            elif self.path.startswith("/debug/traces"):
                # the in-memory trace ring: recent span trees, newest
                # first; ?limit=/?name= narrow to one trace family,
                # ?trace_id= is the exact lookup
                self._send(json.dumps(obs.debug_traces_payload(query)).encode())
            elif self.path.startswith("/debug/slo"):
                # live objective verdicts + burn rates from the online
                # SLO engine ({} until one is configured)
                self._send(json.dumps(obs.debug_slo_payload(query)).encode())
            elif self.path.startswith("/debug/flight"):
                # recorded slow-solve incidents (empty when no --flight-dir)
                self._send(json.dumps(obs.debug_flight_payload(query)).encode())
            elif self.path.startswith("/debug/profile"):
                # sampling-profiler folds: top-N self-time JSON, or the
                # collapsed-flamegraph corpus with ?format=collapsed
                ctype, body = obs.debug_profile_payload(query)
                self._send(body, ctype)
            elif self.path.startswith("/debug/fleet"):
                # the fleet telemetry plane: member inventory, fleet SLO
                # verdicts, stitched-trace index ({} until configured)
                self._send(json.dumps(obs.debug_fleet_payload(query)).encode())
            elif self.path.startswith("/debug/decisions"):
                # the decision audit log: newest provisioning-round
                # records (?limit=/?provisioner= narrow the window)
                self._send(json.dumps(obs.debug_decisions_payload(query)).encode())
            elif self.path.startswith("/debug/incidents"):
                # the regression sentinel's correlated incident records
                # (?id= for one full record with its evidence) + the
                # learned baseline table
                self._send(json.dumps(obs.debug_incidents_payload(query)).encode())
            elif self.path.startswith("/debug/forecast"):
                # per-provisioner arrival-rate predictions + warm-pool
                # horizon from the arrival forecaster ({} until one is
                # configured)
                self._send(json.dumps(obs.debug_forecast_payload(query)).encode())
            elif self.path.startswith("/debug/explain"):
                # per-pod scheduling explainability: ?pod=<name> returns
                # the newest decision's per-candidate elimination
                # breakdown (or the chosen placement when it scheduled)
                self._send(json.dumps(obs.debug_explain_payload(query)).encode())
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *args):  # silence per-request stderr noise
            return

    health = ThreadingHTTPServer(("0.0.0.0", runtime.options.health_probe_port), HealthHandler)
    health.daemon_threads = True
    threading.Thread(target=health.serve_forever, daemon=True, name="healthz").start()
    runtime.servers = [metrics_server, health]


def _build_cluster(options: Options) -> Cluster:
    """In-memory store by default; a real apiserver when configured
    (reference: cmd/controller/main.go:68-70 rate-limited kube client)."""
    if not options.kube_api_server:
        return Cluster()
    from karpenter_tpu.kube.apiserver import ApiCluster

    if options.kube_api_server == "in-cluster":
        return ApiCluster.from_env(
            qps=options.kube_client_qps, burst=options.kube_client_burst
        )
    return ApiCluster(
        options.kube_api_server,
        qps=options.kube_client_qps,
        burst=options.kube_client_burst,
    )


def build_runtime(
    options: Optional[Options] = None,
    cluster: Optional[Cluster] = None,
    cloud_provider: Optional[CloudProvider] = None,
    start_workers: bool = True,
    allow_pod_affinity: bool = True,
    consolidation_enabled: Optional[bool] = None,
    shard_identity: Optional[str] = None,
) -> Runtime:
    """Assemble (but do not start) the full controller process."""
    options = options or Options()
    if consolidation_enabled is None:
        consolidation_enabled = options.consolidation_enabled
    if cluster is None:
        cluster = _build_cluster(options)
    cloud_provider = cloud_provider or registry.new_cloud_provider(options.cloud_provider)
    # latency histograms on every provider method
    # (reference: cmd/controller/main.go:81 → metrics/cloudprovider.go:66)
    cloud_provider = cpmetrics.decorate(cloud_provider)

    # fleet sharding (docs/fleet.md): this replica runs workers only for the
    # provisioner shards whose lease it holds; the manager's claim/renew
    # loop starts in run_controller_process (tests drive tick() inline).
    # Shard keys come from the informer watch (WatchedShardKeys), not a
    # per-tick provisioner LIST: the watch keeps the key universe current
    # for free, and an added/deleted provisioner wakes the manager for an
    # immediate tick instead of waiting out the renew interval.
    ownership = None
    if options.shard_lease:
        from karpenter_tpu.fleet import ShardManager, WatchedShardKeys, build_lease_set

        lease_set = build_lease_set(
            options.shard_lease,
            cluster=cluster,
            identity=shard_identity,
            duration=options.shard_lease_duration,
        )
        shard_keys = WatchedShardKeys(cluster)
        ownership = ShardManager(lease_set, keys_fn=shard_keys.keys)
        shard_keys.on_change = ownership.request_tick

    # write-ahead launch journal (docs/launch-journal.md): records intent
    # before every cloud create; the GC controller replays what crashes
    # leave behind
    from karpenter_tpu.launch import build_journal

    journal = build_journal(options.launch_journal, cluster=cluster)

    manager = Manager(cluster)
    provisioning = ProvisioningController(
        cluster,
        cloud_provider,
        start_workers=start_workers,
        default_solver=options.default_solver,
        solver_service_address=options.solver_service_address or None,
        ownership=ownership,
        journal=journal,
        # pack-integrity knobs (docs/integrity.md): wire checksums on the
        # sidecar path + the native canary cross-check rate
        pack_checksum=options.pack_checksum,
        canary_rate=options.canary_rate,
        # streaming solver transport + zero-copy shm arena
        # (docs/solver-transport.md § Streaming)
        solver_stream=options.solver_stream,
        solver_shm_dir=options.solver_shm_dir,
        # resident delta encoding (docs/delta-encoding.md)
        solver_delta=options.solver_delta,
        # decision observability (docs/decisions.md): the consecutive-
        # failure threshold behind PodUnschedulable Warning events
        unschedulable_event_rounds=options.unschedulable_event_rounds,
        # warm-pool claiming (docs/forecasting.md): workers steal onto
        # standing speculative nodes before solving
        warm_pool=options.warm_pool,
    )
    selection = SelectionController(
        cluster, provisioning, allow_pod_affinity=allow_pod_affinity,
        # non-blocking enqueue: a 32-thread reconcile pool must not cap
        # batch formation at 32 pods/solve under an event storm (the
        # reference affords blocking because its 10k goroutines are free,
        # selection/controller.go:183); completion is verified by the 5s
        # requeue, in-flight pods are guarded by worker.is_pending
        wait=False,
    )
    termination = TerminationController(
        cluster, cloud_provider, start_queue=start_workers,
        fenced=(ownership.fenced if ownership is not None else None),
    )
    interruption = InterruptionController(
        cluster, cloud_provider, provisioning=provisioning, termination=termination,
        ownership=ownership,
    )
    node = NodeController(cluster, cloud_provider=cloud_provider)
    consolidation = ConsolidationController(
        cluster,
        cloud_provider,
        enabled=consolidation_enabled,
        solver_service_address=options.solver_service_address or None,
        wave_size=options.consolidation_wave_size,
        ownership=ownership,
        # disruption-safe waves (docs/consolidation.md): retirements run
        # through the interruption orchestrator's taint→replace→drain
        # ladder, every wave journals intent first so a crash mid-wave is
        # replayed by GC, and the budget caps concurrent disruption
        orchestrator=interruption.orchestrator,
        journal=journal,
        default_budget=options.consolidation_budget or None,
    )
    garbage_collection = GarbageCollectionController(
        cluster,
        cloud_provider,
        journal=journal,
        termination=termination,
        ownership=ownership,
        gc_interval=options.gc_interval,
        grace_period=options.gc_grace_period,
        warm_pool_ttl=options.warm_pool_ttl,
    )
    # predictive provisioning (docs/forecasting.md): the warm-pool wave
    # turns the arrival forecaster's upper band into standing speculative
    # capacity; the worker's steal claims it, the GC ladder reclaims it
    warmpool = None
    if options.warm_pool:
        warmpool = WarmPoolController(
            cluster,
            cloud_provider,
            provisioning,
            journal=journal,
            ownership=ownership,
            warm_pool_ttl=options.warm_pool_ttl,
            max_nodes=options.warm_pool_max_nodes,
        )
    # the SLO-driven brownout ladder (docs/overload.md): consumes burn
    # state from whatever SLO engine is installed (run_controller_process
    # installs it; the sensor reads lazily, so construction order is free)
    # and actuates the batchers, the router, and consolidation. Built here,
    # STARTED in run_controller_process alongside the engine.
    brownout = None
    if options.brownout_enabled:
        from karpenter_tpu.resilience.brownout import BrownoutController
        from karpenter_tpu.solver.router import default_router

        brownout = BrownoutController(
            provisioning=provisioning,
            consolidation=consolidation,
            router=default_router(),
            warmpool=warmpool,
            cluster=cluster,
            interval=options.brownout_interval,
        )

    counter = CounterController(cluster)
    pvc = PVCController(cluster)
    metrics_node = NodeMetricsController(cluster)
    metrics_pod = PodMetricsController(cluster)

    # concurrency mirrors the reference: selection widest, the rest 10
    # (selection/controller.go:183, provisioning/controller.go:152)
    manager.register("provisioning", provisioning.reconcile, concurrency=10)
    manager.register("selection", selection.reconcile, concurrency=32)
    manager.register("termination", termination.reconcile, concurrency=10)
    manager.register("interruption", interruption.reconcile, concurrency=2)
    manager.register("node", node.reconcile, concurrency=10)
    manager.register("consolidation", consolidation.reconcile, concurrency=2)
    manager.register("garbage_collection", garbage_collection.reconcile, concurrency=1)
    if warmpool is not None:
        manager.register("warmpool", warmpool.reconcile, concurrency=1)
    manager.register("counter", counter.reconcile, concurrency=2)
    manager.register("pvc", pvc.reconcile, concurrency=2)
    manager.register("metrics_node", metrics_node.reconcile, concurrency=2)
    manager.register("metrics_pod", metrics_pod.reconcile, concurrency=2)

    if ownership is not None:
        # a gained shard reconciles immediately (the worker must exist
        # before the owner's selection loop can route pods to it); a lost
        # shard stops its worker SYNCHRONOUSLY — the split-brain P0
        ownership.on_acquired = lambda name: manager.enqueue("provisioning", name)
        ownership.on_lost = provisioning.release_shard

    # watches
    cluster.watch(
        "provisioners", lambda e, o: manager.enqueue("provisioning", o.metadata.name)
    )
    cluster.watch(
        "pods", lambda e, o: manager.enqueue("selection", (o.metadata.name, o.metadata.namespace))
    )
    node.register(manager)
    interruption.register(manager)
    garbage_collection.register(manager)
    if warmpool is not None:
        warmpool.register(manager)
    consolidation.register(manager)
    counter.register(manager)
    pvc.register(manager)
    termination.register(manager)
    metrics_node.register(manager)
    metrics_pod.register(manager)

    return Runtime(
        options=options,
        cluster=cluster,
        cloud_provider=cloud_provider,
        manager=manager,
        provisioning=provisioning,
        selection=selection,
        termination=termination,
        interruption=interruption,
        webhook=Webhook(cloud_provider, default_solver=options.default_solver),
        garbage_collection=garbage_collection,
        journal=journal,
        ownership=ownership,
        brownout=brownout,
        warmpool=warmpool,
        consolidation=consolidation,
    )


def run_controller_process(options: Optional[Options] = None, serve: bool = True) -> Runtime:
    """The ``main()`` equivalent: build, wait for leadership when a lease is
    configured, start, and serve metrics/health."""
    runtime = build_runtime(options)
    from karpenter_tpu.logging_config import LogLevelWatcher, setup_logging

    setup_logging(runtime.options.log_level)
    # tracing + the slow-solve flight recorder (karpenter_tpu/obs):
    # /debug/traces and /debug/flight on the health port serve these
    from karpenter_tpu import obs

    obs.set_enabled(runtime.options.trace_enabled)
    if runtime.options.flight_dir:
        obs.configure_flight(
            runtime.options.flight_dir,
            budget_s=runtime.options.flight_budget_ms / 1e3,
        )
    # online SLO engine (docs/observability.md): objective verdicts and
    # burn rates from the span stream, served at /debug/slo and as
    # karpenter_slo_* metrics; flight records snapshot its burning panel
    objectives = (
        obs.load_objectives(runtime.options.slo_config)
        if runtime.options.slo_config
        else None
    )
    runtime.slo = obs.configure_slo(
        objectives=objectives, window_s=runtime.options.slo_window
    )
    # the arrival-rate forecaster (docs/forecasting.md): always on — it is
    # a finish-hook over spans the tracer already emits, and its
    # predictions back /debug/forecast whether or not --warm-pool spends
    # them on speculative capacity
    runtime.forecast = obs.configure_forecast(
        model=runtime.options.forecast_model,
        alpha=runtime.options.forecast_alpha,
    )
    # the regression sentinel (docs/observability.md): online latency
    # baselines + change-point detection off the same span stream, minting
    # correlated incident records at /debug/incidents; --sentinel-dir
    # persists baselines so a restart resumes instead of re-learning
    if runtime.options.sentinel_enabled:
        from karpenter_tpu.kube.events import recorder_for

        runtime.sentinel = obs.configure_sentinel(
            directory=runtime.options.sentinel_dir,
            recorder=recorder_for(runtime.cluster),
        )
    # the decision audit log (docs/decisions.md): /debug/decisions and
    # /debug/explain answer from the memory ring either way; a configured
    # --decision-dir additionally persists replayable records
    # (tools/replay_decision.py) across restarts
    from karpenter_tpu.obs import decisions as _decisions

    _decisions.set_enabled(runtime.options.explain_enabled)
    if runtime.options.decision_dir:
        obs.configure_decisions(runtime.options.decision_dir)
    # always-on sampling profiler (docs/telemetry.md): stack folds at
    # /debug/profile, in-window top folds on every flight record
    if runtime.options.profile_hz > 0:
        runtime.profiler = obs.configure_profiler(hz=runtime.options.profile_hz)
    # fleet telemetry plane: flush this member's trees/histograms/folds to
    # the shared dir and/or collect peers; /debug/fleet serves the merge
    if runtime.options.telemetry_dir or runtime.options.telemetry_peers:
        peers = [
            p for p in runtime.options.telemetry_peers.split(",") if p.strip()
        ]
        runtime.telemetry = obs.configure_telemetry(
            role="controller",
            directory=runtime.options.telemetry_dir,
            peers=peers,
            flush_interval=runtime.options.telemetry_flush_interval,
        )
    if runtime.brownout is not None:
        # the ladder's audit panel rides every flight record: a slow-solve
        # incident file answers "was the system already degrading?"
        obs.register_state("brownout", runtime.brownout.report)
        runtime.brownout.start()
    if runtime.options.log_config_file:
        runtime.log_watcher = LogLevelWatcher(runtime.options.log_config_file)
        runtime.log_watcher.start()
    from karpenter_tpu.kube.apiserver import ApiCluster

    if isinstance(runtime.cluster, ApiCluster):
        runtime.cluster.start()
        if not runtime.cluster.wait_for_sync(60):
            raise RuntimeError("apiserver cache never synced")
    if runtime.options.leader_election_lease:
        from karpenter_tpu.utils.lease import FileLease, LeaderElector

        def on_lost() -> None:
            # stop reconciling immediately; healthz flips 503 so the
            # liveness probe restarts the process as a fresh follower
            # (the reference exits on lost leadership)
            logger.critical("lost leadership lease; stopping controllers")
            runtime.manager.stop()

        spec = runtime.options.leader_election_lease
        if spec.startswith("kube:"):
            # cluster-scoped Lease object: kube:<namespace>/<name> (a bare
            # kube:<name> lands in kube-system)
            # (reference: cmd/controller/main.go:84-85)
            from karpenter_tpu.kube.leader import KubeLease

            if not isinstance(runtime.cluster, ApiCluster):
                # an in-memory store is per-process: every replica would
                # elect itself — silent split brain
                raise ValueError(
                    "kube: leader election requires --kube-api-server "
                    "(the in-memory cluster cannot coordinate replicas)"
                )
            ns_name = spec[len("kube:"):]
            if "/" in ns_name:
                namespace, _, name = ns_name.partition("/")
            else:
                namespace, name = "kube-system", ns_name
            lease = KubeLease(
                runtime.cluster,
                name=name or "karpenter-leader-election",
                namespace=namespace or "kube-system",
            )
        else:
            lease = FileLease(spec)
        runtime.elector = LeaderElector(lease, on_lost=on_lost)
        runtime.elector.start()
        logger.info("waiting for leadership (%s)", spec)
        runtime.elector.wait_for_leadership()
    if runtime.ownership is not None:
        # unlike leader election there is nothing to wait for: the replica
        # serves whatever shards it wins, starting with none
        runtime.ownership.start()
        logger.info(
            "fleet sharding active (%s, identity %s)",
            runtime.options.shard_lease, runtime.ownership.identity,
        )
    runtime.manager.start()
    if serve:
        _serve_endpoints(runtime)
    # freeze the warm heap out of future GC scans once the first worker has
    # actually warmed (compiled its solve) — collector passes over the
    # long-lived JAX/catalog/table objects were the solve-latency tail
    _freeze_gc_when_warm(runtime)
    logger.info(
        "karpenter-tpu controller started (provider=%s, solver=%s)",
        runtime.cloud_provider.name(),
        runtime.options.default_solver,
    )
    return runtime


if __name__ == "__main__":
    import time as _time

    from karpenter_tpu.options import parse_args

    rt = run_controller_process(parse_args())
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        rt.stop()
