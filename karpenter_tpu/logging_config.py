"""Logging setup with live log-level reload.

Mirrors the reference's knative zap wiring: named per-controller loggers and
a watched ``config-logging`` source that re-applies the level without a
restart (reference: cmd/controller/main.go:109-121; validated by its own
webhook, cmd/webhook/main.go:86-94). Here the source is a file — the
deployment mounts the ConfigMap as one (deploy/controller.yaml) — polled on
a short interval.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}

ROOT_LOGGER = "karpenter"

LOG_FORMAT = "%(asctime)s %(levelname)s %(name)s [%(trace_id)s/%(span_id)s] %(message)s"


class TraceContextFilter(logging.Filter):
    """Stamps ``trace_id``/``span_id`` (or ``-``) on every record passing
    through the handler it is attached to, so a log line from anywhere in
    the ``karpenter`` hierarchy can be grepped straight into its trace at
    ``/debug/traces``. Attached to HANDLERS, not loggers: logger-level
    filters don't apply to child loggers' records, and the point is every
    record, not just ones logged on the root name."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            from karpenter_tpu import obs

            span = obs.tracer().current()
        except Exception:
            span = None
        record.trace_id = span.trace_id if span is not None else "-"
        record.span_id = span.span_id if span is not None else "-"
        return True


_trace_filter = TraceContextFilter()


def install_trace_filter() -> None:
    """Attach the trace filter to every root handler; idempotent (live
    level reload and repeated setup_logging calls must not stack copies)."""
    for handler in logging.getLogger().handlers:
        if not any(isinstance(f, TraceContextFilter) for f in handler.filters):
            handler.addFilter(_trace_filter)


def setup_logging(level: str = "info") -> None:
    """Named-logger hierarchy under ``karpenter``; idempotent."""
    logging.basicConfig(format=LOG_FORMAT)
    install_trace_filter()
    apply_log_level(level)


def apply_log_level(level: str) -> bool:
    parsed = LEVELS.get(level.strip().lower())
    if parsed is None:
        logging.getLogger(ROOT_LOGGER).warning("ignoring invalid log level %r", level)
        return False
    logging.getLogger(ROOT_LOGGER).setLevel(parsed)
    return True


def validate_log_config(level: str) -> Optional[str]:
    """The config-validation webhook's check (/config-validation analog)."""
    if level.strip().lower() not in LEVELS:
        return f"log level {level!r} not in {sorted(LEVELS)}"
    return None


class LogLevelWatcher:
    """Polls a level file (the mounted ConfigMap key) and re-applies changes
    live — the config-logging watch analog."""

    def __init__(self, path: str, interval: float = 10.0):
        self.path = path
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last: Optional[str] = None

    def start(self) -> None:
        self._apply_once()
        self._thread = threading.Thread(target=self._run, daemon=True, name="log-config")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self._apply_once()

    def _apply_once(self) -> None:
        try:
            with open(self.path) as f:
                level = f.read().strip()
        except OSError:
            return
        if level and level != self._last:
            if apply_log_level(level):
                logging.getLogger(ROOT_LOGGER).info("log level now %s", level)
            self._last = level

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
