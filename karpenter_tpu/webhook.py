"""Admission webhook: Provisioner defaulting and validation.

Mirrors ``cmd/webhook`` + the knative admission plumbing: a ``defaulting``
pass (spec defaults, then the cloud provider's DefaultHook) and a
``validation`` pass (spec validation, then the ValidateHook)
(reference: cmd/webhook/main.go:46-94, apis/provisioning/v1alpha5/
provisioner_defaults.go:154-161, provisioner_validation.go:34-132,
register.go:225-227).

The provisioning controller re-runs both at Apply so the control loop is
safe without the webhook (reference: provisioning/controller.go:94-95) — the
webhook's job is fast feedback at ``kubectl apply`` time.
"""

from __future__ import annotations

from typing import List

from karpenter_tpu.api.provisioner import (
    SOLVER_FFD,
    Provisioner,
    default_provisioner,
    validate_provisioner,
)
from karpenter_tpu.cloudprovider.types import CloudProvider


class AdmissionError(Exception):
    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = errors


class Webhook:
    def __init__(self, cloud_provider: CloudProvider, default_solver: str = SOLVER_FFD):
        self.cloud_provider = cloud_provider
        self.default_solver = default_solver

    def default(self, provisioner: Provisioner) -> Provisioner:
        """CRD defaulting: framework defaults then the vendor hook
        (the /default-resource endpoint)."""
        default_provisioner(provisioner, self.default_solver)
        self.cloud_provider.default(provisioner.spec.constraints)
        return provisioner

    def validate(self, provisioner: Provisioner) -> None:
        """CRD validation: framework rules then the vendor hook
        (the /validate-resource endpoint). Raises AdmissionError."""
        errs = validate_provisioner(provisioner)
        errs += self.cloud_provider.validate(provisioner.spec.constraints)
        if errs:
            raise AdmissionError(errs)

    def admit(self, provisioner: Provisioner) -> Provisioner:
        """Default + validate, the full admission pass."""
        self.default(provisioner)
        self.validate(provisioner)
        return provisioner


# ---------------------------------------------------------------------------
# The webhook as a process: HTTP admission endpoints — the second binary
# (reference: cmd/webhook/main.go:46-94 serves /default-resource,
# /validate-resource, /config-validation).
# ---------------------------------------------------------------------------


def serialize_provisioner(p: Provisioner) -> dict:
    from karpenter_tpu.api.objects import NodeSelectorRequirement  # noqa: F401

    c = p.spec.constraints
    return {
        "metadata": {"name": p.metadata.name},
        "spec": {
            "labels": dict(c.labels),
            "taints": [
                {"key": t.key, "value": t.value, "effect": t.effect} for t in c.taints
            ],
            "requirements": [
                {"key": r.key, "operator": r.operator, "values": list(r.values)}
                for r in c.requirements.requirements
            ],
            "ttlSecondsAfterEmpty": p.spec.ttl_seconds_after_empty,
            "ttlSecondsUntilExpired": p.spec.ttl_seconds_until_expired,
            "limits": dict(p.spec.limits.resources) if p.spec.limits else None,
            "solver": p.spec.solver,
            "provider": c.provider,
        },
    }


def deserialize_provisioner(doc: dict) -> Provisioner:
    from karpenter_tpu.api.objects import NodeSelectorRequirement, ObjectMeta, Taint
    from karpenter_tpu.api.provisioner import (
        Constraints,
        Limits,
        ProvisionerSpec,
    )
    from karpenter_tpu.api.requirements import Requirements
    from karpenter_tpu.utils import resources as res

    spec = doc.get("spec", {})
    limits = spec.get("limits")
    return Provisioner(
        metadata=ObjectMeta(name=doc.get("metadata", {}).get("name", "default"), namespace=""),
        spec=ProvisionerSpec(
            constraints=Constraints(
                labels=dict(spec.get("labels", {})),
                taints=[
                    Taint(key=t.get("key", ""), value=t.get("value", ""),
                          effect=t.get("effect", "NoSchedule"))
                    for t in spec.get("taints", [])
                ],
                requirements=Requirements.new(
                    *(
                        NodeSelectorRequirement(
                            key=r["key"], operator=r["operator"],
                            values=list(r.get("values", [])),
                        )
                        for r in spec.get("requirements", [])
                    )
                ),
                provider=spec.get("provider"),
            ),
            ttl_seconds_after_empty=spec.get("ttlSecondsAfterEmpty"),
            ttl_seconds_until_expired=spec.get("ttlSecondsUntilExpired"),
            # kubectl-style quantity strings ("1Gi") become floats here
            limits=Limits(resources=res.parse_resource_list(limits)) if limits else None,
            solver=spec.get("solver", ""),
        ),
    )


def serve(webhook: Webhook, address: str = "0.0.0.0:8443"):
    """Start the admission HTTP server; returns the server object.

    POST /default-resource  → the defaulted provisioner document
    POST /validate-resource → {"allowed": bool, "errors": [...]}
    GET  /healthz           → 200
    """
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        # per-connection read deadline: a stalled client (short body vs its
        # Content-Length) must not wedge a handler thread forever
        timeout = 10

        def _respond(self, code: int, body: dict) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                self._respond(200, {"ok": True})
            else:
                self._respond(404, {"error": "not found"})

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length", 0))
            try:
                doc = json.loads(self.rfile.read(length) or b"{}")
                provisioner = deserialize_provisioner(doc)
            except Exception as e:
                self._respond(400, {"error": f"bad request: {e}"})
                return
            if self.path == "/default-resource":
                try:
                    webhook.default(provisioner)
                except Exception as e:  # hook crash → clean admission failure
                    self._respond(422, {"error": f"defaulting failed: {e}"})
                    return
                self._respond(200, serialize_provisioner(provisioner))
            elif self.path == "/validate-resource":
                try:
                    webhook.validate(provisioner)
                    self._respond(200, {"allowed": True, "errors": []})
                except AdmissionError as e:
                    self._respond(200, {"allowed": False, "errors": e.errors})
                except Exception as e:  # hook crash → denial, not a dropped conn
                    self._respond(200, {"allowed": False, "errors": [f"validation crashed: {e}"]})
            else:
                self._respond(404, {"error": "not found"})

        def log_message(self, *args):
            return

    host, port = address.rsplit(":", 1)
    server = ThreadingHTTPServer((host, int(port)), Handler)
    server.daemon_threads = True
    threading.Thread(target=server.serve_forever, daemon=True, name="webhook").start()
    return server


def main(argv=None) -> None:
    """Webhook process entrypoint: ``python -m karpenter_tpu.webhook``."""
    import argparse
    import time

    from karpenter_tpu.cloudprovider import registry

    ap = argparse.ArgumentParser(prog="karpenter-tpu-webhook")
    ap.add_argument("--address", default="0.0.0.0:8443")
    ap.add_argument("--cloud-provider", default="fake")
    ap.add_argument("--default-solver", default=SOLVER_FFD)
    args = ap.parse_args(argv)
    provider = registry.new_cloud_provider(args.cloud_provider)
    server = serve(Webhook(provider, default_solver=args.default_solver), args.address)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.shutdown()


if __name__ == "__main__":
    main()
