"""Admission webhook: Provisioner defaulting and validation.

Mirrors ``cmd/webhook`` + the knative admission plumbing: a ``defaulting``
pass (spec defaults, then the cloud provider's DefaultHook) and a
``validation`` pass (spec validation, then the ValidateHook)
(reference: cmd/webhook/main.go:46-94, apis/provisioning/v1alpha5/
provisioner_defaults.go:154-161, provisioner_validation.go:34-132,
register.go:225-227).

The provisioning controller re-runs both at Apply so the control loop is
safe without the webhook (reference: provisioning/controller.go:94-95) — the
webhook's job is fast feedback at ``kubectl apply`` time.
"""

from __future__ import annotations

from typing import List, Optional

from karpenter_tpu.api.provisioner import (
    SOLVER_FFD,
    Provisioner,
    default_provisioner,
    validate_provisioner,
)
from karpenter_tpu.cloudprovider.types import CloudProvider


class AdmissionError(Exception):
    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = errors


class Webhook:
    def __init__(self, cloud_provider: CloudProvider, default_solver: str = SOLVER_FFD):
        self.cloud_provider = cloud_provider
        self.default_solver = default_solver

    def default(self, provisioner: Provisioner) -> Provisioner:
        """CRD defaulting: framework defaults then the vendor hook
        (the /default-resource endpoint)."""
        default_provisioner(provisioner, self.default_solver)
        self.cloud_provider.default(provisioner.spec.constraints)
        return provisioner

    def validate(self, provisioner: Provisioner) -> None:
        """CRD validation: framework rules then the vendor hook
        (the /validate-resource endpoint). Raises AdmissionError."""
        errs = validate_provisioner(provisioner)
        errs += self.cloud_provider.validate(provisioner.spec.constraints)
        if errs:
            raise AdmissionError(errs)

    def admit(self, provisioner: Provisioner) -> Provisioner:
        """Default + validate, the full admission pass."""
        self.default(provisioner)
        self.validate(provisioner)
        return provisioner


# ---------------------------------------------------------------------------
# The webhook as a process: HTTP admission endpoints — the second binary
# (reference: cmd/webhook/main.go:46-94 serves /default-resource,
# /validate-resource, /config-validation).
# ---------------------------------------------------------------------------


def serialize_provisioner(p: Provisioner) -> dict:
    from karpenter_tpu.api.objects import NodeSelectorRequirement  # noqa: F401

    c = p.spec.constraints
    return {
        "metadata": {"name": p.metadata.name},
        "spec": {
            "labels": dict(c.labels),
            "taints": [
                {"key": t.key, "value": t.value, "effect": t.effect} for t in c.taints
            ],
            "requirements": [
                {"key": r.key, "operator": r.operator, "values": list(r.values)}
                for r in c.requirements.requirements
            ],
            "ttlSecondsAfterEmpty": p.spec.ttl_seconds_after_empty,
            "ttlSecondsUntilExpired": p.spec.ttl_seconds_until_expired,
            "limits": dict(p.spec.limits.resources) if p.spec.limits else None,
            "solver": p.spec.solver,
            "provider": c.provider,
        },
    }


def deserialize_provisioner(doc: dict) -> Provisioner:
    from karpenter_tpu.api.objects import NodeSelectorRequirement, ObjectMeta, Taint
    from karpenter_tpu.api.provisioner import (
        Constraints,
        Limits,
        ProvisionerSpec,
    )
    from karpenter_tpu.api.requirements import Requirements
    from karpenter_tpu.utils import resources as res

    spec = doc.get("spec", {})
    limits = spec.get("limits")
    return Provisioner(
        metadata=ObjectMeta(name=doc.get("metadata", {}).get("name", "default"), namespace=""),
        spec=ProvisionerSpec(
            constraints=Constraints(
                labels=dict(spec.get("labels", {})),
                taints=[
                    Taint(key=t.get("key", ""), value=t.get("value", ""),
                          effect=t.get("effect", "NoSchedule"))
                    for t in spec.get("taints", [])
                ],
                requirements=Requirements.new(
                    *(
                        NodeSelectorRequirement(
                            key=r["key"], operator=r["operator"],
                            values=list(r.get("values", [])),
                        )
                        for r in spec.get("requirements", [])
                    )
                ),
                provider=spec.get("provider"),
            ),
            ttl_seconds_after_empty=spec.get("ttlSecondsAfterEmpty"),
            ttl_seconds_until_expired=spec.get("ttlSecondsUntilExpired"),
            # kubectl-style quantity strings ("1Gi") become floats here
            limits=Limits(resources=res.parse_resource_list(limits)) if limits else None,
            solver=spec.get("solver", ""),
        ),
    )


def admission_review_response(webhook: Webhook, review: dict, path: str) -> dict:
    """Handle one admission.k8s.io/v1 AdmissionReview for ``path``
    (/default-resource mutates, /validate-resource validates).

    Mutating response: a JSONPatch ``add`` on /spec (add upserts — a
    metadata-only Provisioner has no /spec for ``replace`` to target).
    Validating response: allowed or denied with a Status message.
    (reference: the knative admission plumbing behind
    cmd/webhook/main.go:66-84.)
    """
    import base64
    import json

    from karpenter_tpu.kube import serde

    request = review.get("request") or {}
    uid = request.get("uid", "")

    def deny(errors: List[str]) -> dict:
        return {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": {
                "uid": uid,
                "allowed": False,
                "status": {"code": 400, "message": "; ".join(errors)},
            },
        }

    try:
        provisioner = serde.from_wire("provisioners", request.get("object") or {})
    except Exception as e:
        return deny([f"undecodable object: {e}"])
    if path == "/default-resource":
        try:
            webhook.default(provisioner)
        except Exception as e:
            return deny([f"defaulting failed: {e}"])
        patched = serde.to_wire("provisioners", provisioner)
        patch = [{"op": "add", "path": "/spec", "value": patched.get("spec", {})}]
        return {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": {
                "uid": uid,
                "allowed": True,
                "patchType": "JSONPatch",
                "patch": base64.b64encode(json.dumps(patch).encode()).decode(),
            },
        }
    try:
        webhook.validate(provisioner)
    except AdmissionError as e:
        return deny(e.errors)
    except Exception as e:
        return deny([f"validation crashed: {e}"])
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": {"uid": uid, "allowed": True},
    }


def serve(
    webhook: Webhook,
    address: str = "0.0.0.0:8443",
    tls_cert: Optional[str] = None,
    tls_key: Optional[str] = None,
):
    """Start the admission server; returns the server object.

    With ``tls_cert``/``tls_key`` the server speaks HTTPS — what an
    apiserver requires of a webhook (reference: cmd/webhook/main.go:46
    self-managed cert via knative certificates).

    POST /default-resource  → AdmissionReview with a JSONPatch, or (plain
                              provisioner doc in) the defaulted document
    POST /validate-resource → AdmissionReview allow/deny, or
                              {"allowed": bool, "errors": [...]}
    GET  /healthz           → 200
    """
    import json
    import ssl
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        # per-connection read deadline: a stalled client (short body vs its
        # Content-Length) must not wedge a handler thread forever
        timeout = 10

        def _respond(self, code: int, body: dict) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                self._respond(200, {"ok": True})
            else:
                self._respond(404, {"error": "not found"})

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length", 0))
            try:
                doc = json.loads(self.rfile.read(length) or b"{}")
            except Exception as e:
                self._respond(400, {"error": f"bad request: {e}"})
                return
            if self.path not in ("/default-resource", "/validate-resource"):
                self._respond(404, {"error": "not found"})
                return
            if doc.get("kind") == "AdmissionReview":
                self._respond(200, admission_review_response(webhook, doc, self.path))
                return
            # bespoke (non-AdmissionReview) protocol for direct callers
            try:
                provisioner = deserialize_provisioner(doc)
            except Exception as e:
                self._respond(400, {"error": f"bad request: {e}"})
                return
            if self.path == "/default-resource":
                try:
                    webhook.default(provisioner)
                except Exception as e:  # hook crash → clean admission failure
                    self._respond(422, {"error": f"defaulting failed: {e}"})
                    return
                self._respond(200, serialize_provisioner(provisioner))
            else:
                try:
                    webhook.validate(provisioner)
                    self._respond(200, {"allowed": True, "errors": []})
                except AdmissionError as e:
                    self._respond(200, {"allowed": False, "errors": e.errors})
                except Exception as e:  # hook crash → denial, not a dropped conn
                    self._respond(200, {"allowed": False, "errors": [f"validation crashed: {e}"]})

        def log_message(self, *args):
            return

    host, port = address.rsplit(":", 1)
    server = ThreadingHTTPServer((host, int(port)), Handler)
    server.daemon_threads = True
    if tls_cert and tls_key:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(tls_cert, tls_key)
        # handshake deferred to the per-connection handler thread (with its
        # 10s timeout): with the default handshake-on-accept, one idle
        # client would block accept() — and with failurePolicy: Fail that
        # stalls every Provisioner write in the cluster
        server.socket = ctx.wrap_socket(
            server.socket, server_side=True, do_handshake_on_connect=False
        )
    threading.Thread(target=server.serve_forever, daemon=True, name="webhook").start()
    return server


def main(argv=None) -> None:
    """Webhook process entrypoint: ``python -m karpenter_tpu.webhook``."""
    import argparse
    import time

    from karpenter_tpu.cloudprovider import registry

    ap = argparse.ArgumentParser(prog="karpenter-tpu-webhook")
    ap.add_argument("--address", default="0.0.0.0:8443")
    ap.add_argument("--cloud-provider", default="fake")
    ap.add_argument("--default-solver", default=SOLVER_FFD)
    ap.add_argument("--cert-dir", default="/tmp/karpenter-webhook-certs",
                    help="serving cert dir; cert is self-generated when absent")
    ap.add_argument("--service-name", default="karpenter-tpu-webhook")
    ap.add_argument("--service-namespace", default="karpenter")
    ap.add_argument("--no-tls", action="store_true", help="plain HTTP (dev only)")
    ap.add_argument("--kube-api-server", default="",
                    help="'in-cluster' or an apiserver URL; enables runtime "
                         "caBundle self-reconciliation of the webhook "
                         "registrations (reference: cmd/webhook/main.go:46-63)")
    ap.add_argument("--webhook-config", action="append", default=[],
                    metavar="KIND:NAME",
                    help="webhook registration to keep current, e.g. "
                         "validating:validation.webhook.provisioners.karpenter.sh "
                         "(repeatable; defaults to the two shipped registrations)")
    args = ap.parse_args(argv)
    provider = registry.new_cloud_provider(args.cloud_provider)
    tls_cert = tls_key = ca_path = None
    if not args.no_tls:
        from karpenter_tpu.kube.certs import ensure_serving_cert

        dns = [
            args.service_name,
            f"{args.service_name}.{args.service_namespace}",
            f"{args.service_name}.{args.service_namespace}.svc",
            f"{args.service_name}.{args.service_namespace}.svc.cluster.local",
        ]
        tls_cert, tls_key, ca_path = ensure_serving_cert(args.cert_dir, dns)
        print(f"serving cert ready; caBundle at {ca_path}")
    reconciler = None
    if args.kube_api_server and ca_path:
        from karpenter_tpu.kube.apiserver import ApiCluster
        from karpenter_tpu.kube.cabundle import CABundleReconciler

        _KIND_ALIASES = {
            "validating": "validatingwebhookconfigurations",
            "mutating": "mutatingwebhookconfigurations",
        }
        specs = args.webhook_config or [
            "mutating:defaulting.webhook.provisioners.karpenter.sh",
            "validating:validation.webhook.provisioners.karpenter.sh",
        ]
        configs = []
        for spec in specs:
            kind, _, name = spec.partition(":")
            configs.append((_KIND_ALIASES.get(kind, kind), name))
        if args.kube_api_server == "in-cluster":
            cluster = ApiCluster.from_env()
        else:
            cluster = ApiCluster(args.kube_api_server)
        # no informer start: the reconciler reads live and patches — the
        # webhook RBAC grants only get/update/patch on admissionregistration
        reconciler = CABundleReconciler(cluster, configs, ca_path).start()
        print(f"caBundle reconciler running for {configs}")
    server = serve(
        Webhook(provider, default_solver=args.default_solver),
        args.address,
        tls_cert=tls_cert,
        tls_key=tls_key,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.shutdown()
        if reconciler is not None:
            reconciler.stop()


if __name__ == "__main__":
    main()
