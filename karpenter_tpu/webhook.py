"""Admission webhook: Provisioner defaulting and validation.

Mirrors ``cmd/webhook`` + the knative admission plumbing: a ``defaulting``
pass (spec defaults, then the cloud provider's DefaultHook) and a
``validation`` pass (spec validation, then the ValidateHook)
(reference: cmd/webhook/main.go:46-94, apis/provisioning/v1alpha5/
provisioner_defaults.go:154-161, provisioner_validation.go:34-132,
register.go:225-227).

The provisioning controller re-runs both at Apply so the control loop is
safe without the webhook (reference: provisioning/controller.go:94-95) — the
webhook's job is fast feedback at ``kubectl apply`` time.
"""

from __future__ import annotations

from typing import List

from karpenter_tpu.api.provisioner import (
    SOLVER_FFD,
    Provisioner,
    default_provisioner,
    validate_provisioner,
)
from karpenter_tpu.cloudprovider.types import CloudProvider


class AdmissionError(Exception):
    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = errors


class Webhook:
    def __init__(self, cloud_provider: CloudProvider, default_solver: str = SOLVER_FFD):
        self.cloud_provider = cloud_provider
        self.default_solver = default_solver

    def default(self, provisioner: Provisioner) -> Provisioner:
        """CRD defaulting: framework defaults then the vendor hook
        (the /default-resource endpoint)."""
        default_provisioner(provisioner, self.default_solver)
        self.cloud_provider.default(provisioner.spec.constraints)
        return provisioner

    def validate(self, provisioner: Provisioner) -> None:
        """CRD validation: framework rules then the vendor hook
        (the /validate-resource endpoint). Raises AdmissionError."""
        errs = validate_provisioner(provisioner)
        errs += self.cloud_provider.validate(provisioner.spec.constraints)
        if errs:
            raise AdmissionError(errs)

    def admit(self, provisioner: Provisioner) -> Provisioner:
        """Default + validate, the full admission pass."""
        self.default(provisioner)
        self.validate(provisioner)
        return provisioner
