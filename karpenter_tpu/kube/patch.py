"""Read-modify-write helpers for list-valued merge-patch fields.

RFC 7386 (JSON merge patch) replaces arrays WHOLESALE: a patch carrying
``{"status": {"conditions": [mine]}}`` erases every condition another
writer owns — the PR-1 ``_set_active`` clobber. Any patch that writes a
multi-writer list field (``conditions``, ``taints``, ``finalizers``) must
therefore carry the FULL list: the freshest cached copy with one entry
upserted or removed. These helpers are that idiom, named — and karplint's
``patch-literal-list`` rule recognizes them, so routing list writes through
here is both the correct behavior and the lintable shape.

All helpers are pure: they return new lists and never mutate their inputs
(the codebase-wide replace-never-mutate convention — the inputs are often
live informer-cache objects).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

Wire = Dict[str, Any]


def upsert_keyed(existing: Sequence[Wire], entry: Wire, *, key: str) -> List[Wire]:
    """The full list with ``entry`` replacing the element sharing its
    ``key`` field (appended when absent). Order of the other elements is
    preserved; the upserted entry lands last — matching the append-on-change
    behavior status writers already exhibit."""
    ident = entry.get(key)
    out = [dict(e) for e in existing if e.get(key) != ident]
    out.append(dict(entry))
    return out


def without_keyed(existing: Sequence[Wire], ident: Any, *, key: str) -> List[Wire]:
    """The full list minus the element whose ``key`` field equals ``ident``."""
    return [dict(e) for e in existing if e.get(key) != ident]


def without_value(existing: Sequence[Any], value: Any) -> List[Any]:
    """Plain-value lists (finalizers): the full list minus ``value``."""
    return [v for v in existing if v != value]


def upsert_condition(existing: Sequence[Wire], condition: Wire) -> List[Wire]:
    """Conditions are keyed by ``type`` (knative/k8s convention)."""
    return upsert_keyed(existing, condition, key="type")


def upsert_taint(existing: Sequence[Wire], taint: Wire) -> List[Wire]:
    """Taints are keyed by ``key`` (one effect per taint key here; the
    callers never stack effects under one key)."""
    return upsert_keyed(existing, taint, key="key")
