"""Operator-visible Events for controller actions.

The reference snapshot emits no Events (SURVEY §5.5) — this is additive
capability: ``kubectl describe node``/``provisioner`` shows what the
controllers did (launched, bound N pods, terminated, consolidated) and why.

``EventRecorder`` mirrors client-go's recorder shape: fire-and-forget
(an event that fails to write must never fail the action that caused it),
deduplicating repeats of the same (object, reason, message) into a count
bump within an aggregation window, exactly like the apiserver's event
series handling.
"""

from __future__ import annotations

import copy
import logging
import threading
from collections import OrderedDict
from typing import Optional, Tuple

from karpenter_tpu.api.objects import Event, ObjectMeta
from karpenter_tpu.kube.client import Cluster

logger = logging.getLogger("karpenter.events")

AGGREGATION_WINDOW = 600.0  # repeats inside this window bump count

# annotation linking an emitted Event to the trace of the action that
# emitted it: `kubectl describe` output becomes greppable into
# /debug/traces (and the flight dir) by trace id
TRACE_ID_ANNOTATION = "karpenter.sh/trace-id"

# annotation linking an emitted Event to the DECISION that caused it: the
# id greps straight into /debug/decisions (and the --decision-dir ring,
# where tools/replay_decision.py can re-solve it). karplint's
# `event-decision-id` rule requires every Warning event on a
# provisioning/consolidation decision path to carry it.
DECISION_ID_ANNOTATION = "karpenter.sh/decision-id"


class EventRecorder:
    def __init__(self, cluster: Cluster, component: str = "karpenter-tpu"):
        self.cluster = cluster
        self.component = component
        self._lock = threading.Lock()
        # insertion/update-ordered so overflow evicts the least recently
        # UPDATED key in O(1) — an age-only prune cannot shrink the table
        # during a distinct-event storm inside the aggregation window
        self._seen: "OrderedDict[Tuple, Tuple[float, Event]]" = OrderedDict()
        self._counter = 0

    def _bump(self, key, now, exclude=None):
        """Under the lock: if ``key`` holds a live aggregation entry (other
        than ``exclude``, the object whose server copy is known pruned),
        bump its count and return ``(event, wire_snapshot)``. The snapshot
        is taken under the lock: the API write happens outside it and races
        with other threads' bumps, and a half-mutated event must never be
        serialized to the wire. Returns ``(None, None)`` on miss."""
        with self._lock:
            hit = self._seen.get(key)
            if (
                hit is None
                or hit[1] is exclude
                or now - hit[0] >= AGGREGATION_WINDOW
            ):
                return None, None
            ev = hit[1]
            ev.count += 1
            ev.last_timestamp = now
            self._seen[key] = (now, ev)
            self._seen.move_to_end(key)
            return ev, copy.copy(ev)

    def event(
        self,
        involved_kind: str,
        involved_name: str,
        reason: str,
        message: str,
        type: str = "Normal",
        namespace: str = "",
        decision_id: str = "",
    ) -> Optional[Event]:
        """Record an event; returns the stored object (or None on failure —
        recording is never allowed to break the calling controller)."""
        try:
            now = self.cluster.clock()
            key = (involved_kind, involved_name, namespace, reason, message)
            # The lock guards only _seen/_counter bookkeeping; API writes
            # happen outside it so a slow apiserver call can't serialize
            # every controller's event emission behind this recorder.
            ev, snapshot = self._bump(key, now)
            stale = None
            if ev is not None:
                try:
                    self.cluster.update("events", snapshot)
                except Exception:
                    stale = ev  # pruned server-side: re-create below
                else:
                    return ev
            # re-check: another thread may have created this key while we
            # were outside the lock (ADVICE r4). Bump that fresh event
            # instead of creating a near-simultaneous duplicate — unless
            # the entry is the very object whose update just failed, which
            # must be replaced, not bumped forever.
            ev, snapshot = self._bump(key, now, exclude=stale)
            if ev is not None:
                try:
                    self.cluster.update("events", snapshot)
                except Exception:
                    pass  # fire-and-forget; aggregation already recorded
                return ev
            with self._lock:
                self._counter += 1
                name = f"{involved_name}.{self._counter:x}.{int(now)}"
            meta = ObjectMeta(name=name, namespace=namespace or "default")
            # annotate with the active trace id — inside the same guarded
            # region as the write: tracing trouble must never fail the
            # traced action (recording is fire-and-forget all the way down)
            from karpenter_tpu import obs

            span = obs.tracer().current()
            if span is not None:
                meta.annotations[TRACE_ID_ANNOTATION] = span.trace_id
            # the decision-id annotation (empty = the emitter predates any
            # decision, e.g. a shed before the first round recorded)
            if decision_id:
                meta.annotations[DECISION_ID_ANNOTATION] = decision_id
            ev = Event(
                metadata=meta,
                involved_kind=involved_kind,
                involved_name=involved_name,
                involved_namespace=namespace,
                reason=reason,
                message=message,
                type=type,
                source_component=self.component,
                first_timestamp=now,
                last_timestamp=now,
            )
            self.cluster.create("events", ev)
            with self._lock:
                self._seen[key] = (now, ev)
                self._seen.move_to_end(key)
                # hard cap: evict least-recently-updated (an evicted key
                # merely loses aggregation — its next emit re-creates)
                while len(self._seen) > 4096:
                    self._seen.popitem(last=False)
            return ev
        except Exception:
            logger.debug("event emit failed", exc_info=True)
            return None


_NULL = None


def recorder_for(cluster: Cluster) -> EventRecorder:
    """One recorder per cluster object (controllers share it)."""
    rec = getattr(cluster, "_event_recorder", None)
    if rec is None:
        rec = EventRecorder(cluster)
        try:
            cluster._event_recorder = rec
        except AttributeError:
            pass
    return rec
