"""Kubernetes wire-format serialization.

Maps the framework's lightweight object model (``api/objects.py``,
``api/provisioner.py``) to and from real Kubernetes JSON — camelCase field
names, resource quantities as strings, RFC3339 timestamps — so the apiserver
``Cluster`` backend (``kube/apiserver.py``) speaks to an actual cluster, not
a bespoke protocol. The reference gets this from ``k8s.io/api`` codegen
(SURVEY §2.2); here the mapping is explicit per kind.

``to_wire(kind, obj)`` / ``from_wire(kind, doc)`` cover every kind the
controllers reconcile plus coordination Leases for leader election.
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional

from karpenter_tpu.api.objects import (
    Affinity,
    Container,
    ContainerPort,
    DaemonSet,
    LabelSelector,
    Lease,
    Node,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodCondition,
    PodDisruptionBudget,
    PodSpec,
    PodStatus,
    PreferredSchedulingTerm,
    StorageClass,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    Volume,
    WeightedPodAffinityTerm,
)
from karpenter_tpu.api.provisioner import (
    Condition,
    Constraints,
    KubeletConfiguration,
    Limits,
    Provisioner,
    ProvisionerSpec,
    ProvisionerStatus,
)
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.utils import resources as res

# kind -> (apiVersion, Kind, namespaced)
KIND_INFO: Dict[str, Any] = {
    "pods": ("v1", "Pod", True),
    "nodes": ("v1", "Node", False),
    "daemonsets": ("apps/v1", "DaemonSet", True),
    "provisioners": ("karpenter.sh/v1alpha5", "Provisioner", False),
    "pvcs": ("v1", "PersistentVolumeClaim", True),
    "pvs": ("v1", "PersistentVolume", False),
    "storageclasses": ("storage.k8s.io/v1", "StorageClass", False),
    "pdbs": ("policy/v1", "PodDisruptionBudget", True),
    "leases": ("coordination.k8s.io/v1", "Lease", True),
    "validatingwebhookconfigurations": (
        "admissionregistration.k8s.io/v1", "ValidatingWebhookConfiguration", False,
    ),
    "mutatingwebhookconfigurations": (
        "admissionregistration.k8s.io/v1", "MutatingWebhookConfiguration", False,
    ),
    "events": ("v1", "Event", True),
}


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def _ts(value: Optional[float]) -> Optional[str]:
    if value is None or not value:
        return None
    return (
        datetime.datetime.fromtimestamp(value, tz=datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ")
    )


# public name for controllers composing status patches in wire shape
wire_ts = _ts


def _ts_micro(value: Optional[float]) -> Optional[str]:
    """MicroTime — Lease acquire/renew times carry sub-second precision
    (k8s.io/apimachinery MicroTime); plain RFC3339 seconds would break
    short leases."""
    if value is None or not value:
        return None
    return (
        datetime.datetime.fromtimestamp(value, tz=datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.%fZ")
    )


def parse_ts(value) -> Optional[float]:
    if value is None or value == "":
        return None
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).replace("Z", "+00:00")
    return datetime.datetime.fromisoformat(s).timestamp()


def _quantity(value: float) -> str:
    if value == int(value):
        return str(int(value))
    return f"{value:g}"


def quantities(rl: Dict[str, float]) -> Dict[str, str]:
    return {k: _quantity(v) for k, v in rl.items()}


def parse_quantities(raw: Optional[Dict[str, Any]]) -> Dict[str, float]:
    return {k: res.parse_quantity(v) for k, v in (raw or {}).items()}


def _drop_none(d: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in d.items() if v is not None and v != {} and v != []}


# ---------------------------------------------------------------------------
# metadata
# ---------------------------------------------------------------------------


def meta_to_wire(m: ObjectMeta, namespaced: bool = True) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": m.name}
    if namespaced and m.namespace:
        out["namespace"] = m.namespace
    if m.labels:
        out["labels"] = dict(m.labels)
    if m.annotations:
        out["annotations"] = dict(m.annotations)
    if m.finalizers:
        out["finalizers"] = list(m.finalizers)
    if m.owner_references:
        out["ownerReferences"] = [
            {"apiVersion": o.api_version, "kind": o.kind, "name": o.name, "uid": ""}
            for o in m.owner_references
        ]
    if m.uid:
        out["uid"] = m.uid
    if m.creation_timestamp:
        out["creationTimestamp"] = _ts(m.creation_timestamp)
    if m.deletion_timestamp is not None:
        out["deletionTimestamp"] = _ts(m.deletion_timestamp)
    if m.resource_version:
        out["resourceVersion"] = str(m.resource_version)
    return out


def meta_from_wire(doc: Dict[str, Any]) -> ObjectMeta:
    # k8s documents resourceVersions as opaque strings; etcd's happen to be
    # numeric, and the cache-freshness guards use numeric ordering as a
    # best-effort heuristic. A non-numeric RV (proxy, alternative storage)
    # must degrade to 0 — which the guards treat as "unknown: always
    # accept" (last-write-wins), see ApiCluster._apply_event — rather than
    # raise and kill the watch loop's event processing.
    rv = doc.get("resourceVersion") or 0
    try:
        rv = int(rv)
    except (TypeError, ValueError):
        rv = 0
    return ObjectMeta(
        name=doc.get("name", ""),
        namespace=doc.get("namespace", "default"),
        labels=dict(doc.get("labels") or {}),
        annotations=dict(doc.get("annotations") or {}),
        finalizers=list(doc.get("finalizers") or []),
        owner_references=[
            OwnerReference(
                api_version=o.get("apiVersion", ""),
                kind=o.get("kind", ""),
                name=o.get("name", ""),
            )
            for o in doc.get("ownerReferences") or []
        ],
        uid=doc.get("uid", "") or "",
        creation_timestamp=parse_ts(doc.get("creationTimestamp")) or 0.0,
        deletion_timestamp=parse_ts(doc.get("deletionTimestamp")),
        resource_version=rv,
    )


# ---------------------------------------------------------------------------
# shared sub-objects
# ---------------------------------------------------------------------------


def _req_to_wire(r: NodeSelectorRequirement) -> Dict[str, Any]:
    out = {"key": r.key, "operator": r.operator}
    if r.values:
        out["values"] = list(r.values)
    return out


def _req_from_wire(d: Dict[str, Any]) -> NodeSelectorRequirement:
    return NodeSelectorRequirement(
        key=d.get("key", ""), operator=d.get("operator", ""), values=list(d.get("values") or [])
    )


def _term_to_wire(t: NodeSelectorTerm) -> Dict[str, Any]:
    return {"matchExpressions": [_req_to_wire(r) for r in t.match_expressions]}


def _term_from_wire(d: Dict[str, Any]) -> NodeSelectorTerm:
    return NodeSelectorTerm(
        match_expressions=[_req_from_wire(r) for r in d.get("matchExpressions") or []]
    )


def _selector_to_wire(s: Optional[LabelSelector]) -> Optional[Dict[str, Any]]:
    if s is None:
        return None
    return _drop_none(
        {
            "matchLabels": dict(s.match_labels) or None,
            "matchExpressions": [_req_to_wire(r) for r in s.match_expressions] or None,
        }
    )


def _selector_from_wire(d: Optional[Dict[str, Any]]) -> Optional[LabelSelector]:
    if d is None:
        return None
    return LabelSelector(
        match_labels=dict(d.get("matchLabels") or {}),
        match_expressions=[_req_from_wire(r) for r in d.get("matchExpressions") or []],
    )


def taint_to_wire(t: Taint) -> Dict[str, Any]:
    return _drop_none({"key": t.key, "value": t.value or None, "effect": t.effect})


_taint_to_wire = taint_to_wire  # internal alias


def _taint_from_wire(d: Dict[str, Any]) -> Taint:
    return Taint(key=d.get("key", ""), value=d.get("value", "") or "", effect=d.get("effect", "NoSchedule"))


def _pod_affinity_term_to_wire(t: PodAffinityTerm) -> Dict[str, Any]:
    return _drop_none(
        {
            "labelSelector": _selector_to_wire(t.label_selector),
            "topologyKey": t.topology_key,
            "namespaces": list(t.namespaces) or None,
        }
    )


def _pod_affinity_term_from_wire(d: Dict[str, Any]) -> PodAffinityTerm:
    return PodAffinityTerm(
        label_selector=_selector_from_wire(d.get("labelSelector")),
        topology_key=d.get("topologyKey", ""),
        namespaces=list(d.get("namespaces") or []),
    )


def _affinity_to_wire(a: Optional[Affinity]) -> Optional[Dict[str, Any]]:
    if a is None:
        return None
    out: Dict[str, Any] = {}
    if a.node_affinity is not None:
        na: Dict[str, Any] = {}
        if a.node_affinity.required:
            na["requiredDuringSchedulingIgnoredDuringExecution"] = {
                "nodeSelectorTerms": [_term_to_wire(t) for t in a.node_affinity.required]
            }
        if a.node_affinity.preferred:
            na["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": p.weight, "preference": _term_to_wire(p.preference)}
                for p in a.node_affinity.preferred
            ]
        out["nodeAffinity"] = na
    for attr, key in (("pod_affinity", "podAffinity"), ("pod_anti_affinity", "podAntiAffinity")):
        pa = getattr(a, attr)
        if pa is None:
            continue
        block: Dict[str, Any] = {}
        if pa.required:
            block["requiredDuringSchedulingIgnoredDuringExecution"] = [
                _pod_affinity_term_to_wire(t) for t in pa.required
            ]
        if pa.preferred:
            block["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": w.weight, "podAffinityTerm": _pod_affinity_term_to_wire(w.pod_affinity_term)}
                for w in pa.preferred
            ]
        out[key] = block
    return out or None


def _affinity_from_wire(d: Optional[Dict[str, Any]]) -> Optional[Affinity]:
    if not d:
        return None
    out = Affinity()
    na = d.get("nodeAffinity")
    if na:
        req = na.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
        out.node_affinity = NodeAffinity(
            required=[_term_from_wire(t) for t in req.get("nodeSelectorTerms") or []],
            preferred=[
                PreferredSchedulingTerm(
                    weight=p.get("weight", 1), preference=_term_from_wire(p.get("preference") or {})
                )
                for p in na.get("preferredDuringSchedulingIgnoredDuringExecution") or []
            ],
        )
    for key, cls, attr in (
        ("podAffinity", PodAffinity, "pod_affinity"),
        ("podAntiAffinity", PodAntiAffinity, "pod_anti_affinity"),
    ):
        pa = d.get(key)
        if not pa:
            continue
        setattr(
            out,
            attr,
            cls(
                required=[
                    _pod_affinity_term_from_wire(t)
                    for t in pa.get("requiredDuringSchedulingIgnoredDuringExecution") or []
                ],
                preferred=[
                    WeightedPodAffinityTerm(
                        weight=w.get("weight", 1),
                        pod_affinity_term=_pod_affinity_term_from_wire(w.get("podAffinityTerm") or {}),
                    )
                    for w in pa.get("preferredDuringSchedulingIgnoredDuringExecution") or []
                ],
            ),
        )
    if out.node_affinity is None and out.pod_affinity is None and out.pod_anti_affinity is None:
        return None
    return out


def _pod_spec_to_wire(s: PodSpec) -> Dict[str, Any]:
    return _drop_none(
        {
            "nodeName": s.node_name or None,
            "nodeSelector": dict(s.node_selector) or None,
            "affinity": _affinity_to_wire(s.affinity),
            "tolerations": [
                _drop_none(
                    {
                        "key": t.key or None,
                        "operator": t.operator,
                        "value": t.value or None,
                        "effect": t.effect or None,
                        "tolerationSeconds": t.toleration_seconds,
                    }
                )
                for t in s.tolerations
            ]
            or None,
            "containers": [
                _drop_none(
                    {
                        "name": c.name,
                        "resources": _drop_none(
                            {
                                "requests": quantities(c.requests) or None,
                                "limits": quantities(c.limits) or None,
                            }
                        )
                        or None,
                        "ports": [
                            _drop_none(
                                {
                                    "hostPort": p.host_port or None,
                                    "hostIP": p.host_ip or None,
                                    "protocol": p.protocol,
                                }
                            )
                            for p in c.ports
                        ]
                        or None,
                    }
                )
                for c in s.containers
            ]
            or None,
            "topologySpreadConstraints": [
                _drop_none(
                    {
                        "maxSkew": t.max_skew,
                        "topologyKey": t.topology_key,
                        "whenUnsatisfiable": t.when_unsatisfiable,
                        "labelSelector": _selector_to_wire(t.label_selector),
                    }
                )
                for t in s.topology_spread_constraints
            ]
            or None,
            "priorityClassName": s.priority_class_name or None,
            "volumes": [
                _drop_none(
                    {
                        "name": v.name,
                        "persistentVolumeClaim": (
                            {"claimName": v.persistent_volume_claim}
                            if v.persistent_volume_claim
                            else None
                        ),
                    }
                )
                for v in s.volumes
            ]
            or None,
            "terminationGracePeriodSeconds": s.termination_grace_period_seconds,
        }
    )


def _pod_spec_from_wire(d: Dict[str, Any]) -> PodSpec:
    return PodSpec(
        node_name=d.get("nodeName", "") or "",
        node_selector=dict(d.get("nodeSelector") or {}),
        affinity=_affinity_from_wire(d.get("affinity")),
        tolerations=[
            Toleration(
                key=t.get("key", "") or "",
                operator=t.get("operator", "Equal"),
                value=t.get("value", "") or "",
                effect=t.get("effect", "") or "",
                toleration_seconds=t.get("tolerationSeconds"),
            )
            for t in d.get("tolerations") or []
        ],
        containers=[
            Container(
                name=c.get("name", "app"),
                requests=parse_quantities((c.get("resources") or {}).get("requests")),
                limits=parse_quantities((c.get("resources") or {}).get("limits")),
                ports=[
                    ContainerPort(
                        host_port=p.get("hostPort", 0) or 0,
                        host_ip=p.get("hostIP", "") or "",
                        protocol=p.get("protocol", "TCP"),
                    )
                    for p in c.get("ports") or []
                ],
            )
            for c in d.get("containers") or []
        ],
        topology_spread_constraints=[
            TopologySpreadConstraint(
                max_skew=t.get("maxSkew", 1),
                topology_key=t.get("topologyKey", ""),
                when_unsatisfiable=t.get("whenUnsatisfiable", "DoNotSchedule"),
                label_selector=_selector_from_wire(t.get("labelSelector")),
            )
            for t in d.get("topologySpreadConstraints") or []
        ],
        priority_class_name=d.get("priorityClassName", "") or "",
        volumes=[
            Volume(
                name=v.get("name", ""),
                persistent_volume_claim=(v.get("persistentVolumeClaim") or {}).get("claimName", ""),
            )
            for v in d.get("volumes") or []
        ],
        termination_grace_period_seconds=d.get("terminationGracePeriodSeconds", 30) or 30,
    )


def _conditions_to_wire(conds: List[PodCondition]) -> List[Dict[str, Any]]:
    return [
        _drop_none({"type": c.type, "status": c.status, "reason": c.reason or None})
        for c in conds
    ]


def _conditions_from_wire(raw) -> List[PodCondition]:
    return [
        PodCondition(type=c.get("type", ""), status=c.get("status", ""), reason=c.get("reason", "") or "")
        for c in raw or []
    ]


# ---------------------------------------------------------------------------
# per-kind
# ---------------------------------------------------------------------------


def _pod_to_wire(p: Pod) -> Dict[str, Any]:
    return {
        "spec": _pod_spec_to_wire(p.spec),
        "status": _drop_none(
            {
                "phase": p.status.phase or None,
                "conditions": _conditions_to_wire(p.status.conditions) or None,
                "nominatedNodeName": p.status.nominated_node_name or None,
            }
        ),
    }


def _pod_from_wire(doc: Dict[str, Any]) -> Pod:
    status = doc.get("status") or {}
    return Pod(
        metadata=meta_from_wire(doc.get("metadata") or {}),
        spec=_pod_spec_from_wire(doc.get("spec") or {}),
        status=PodStatus(
            phase=status.get("phase", "Pending") or "Pending",
            conditions=_conditions_from_wire(status.get("conditions")),
            nominated_node_name=status.get("nominatedNodeName", "") or "",
        ),
    )


def _node_to_wire(n: Node) -> Dict[str, Any]:
    return {
        "spec": _drop_none(
            {
                "taints": [_taint_to_wire(t) for t in n.spec.taints] or None,
                "unschedulable": n.spec.unschedulable or None,
                "providerID": n.spec.provider_id or None,
            }
        ),
        "status": _drop_none(
            {
                "capacity": quantities(n.status.capacity) or None,
                "allocatable": quantities(n.status.allocatable) or None,
                "conditions": _conditions_to_wire(n.status.conditions) or None,
                "phase": n.status.phase or None,
            }
        ),
    }


def _node_from_wire(doc: Dict[str, Any]) -> Node:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    return Node(
        metadata=meta_from_wire(doc.get("metadata") or {}),
        spec=NodeSpec(
            taints=[_taint_from_wire(t) for t in spec.get("taints") or []],
            unschedulable=bool(spec.get("unschedulable", False)),
            provider_id=spec.get("providerID", "") or "",
        ),
        status=NodeStatus(
            capacity=parse_quantities(status.get("capacity")),
            allocatable=parse_quantities(status.get("allocatable")),
            conditions=_conditions_from_wire(status.get("conditions")),
            phase=status.get("phase", "") or "",
        ),
    )


def _daemonset_to_wire(d: DaemonSet) -> Dict[str, Any]:
    return {"spec": {"template": {"spec": _pod_spec_to_wire(d.pod_template)}}}


def _daemonset_from_wire(doc: Dict[str, Any]) -> DaemonSet:
    template = ((doc.get("spec") or {}).get("template") or {}).get("spec") or {}
    return DaemonSet(
        metadata=meta_from_wire(doc.get("metadata") or {}),
        pod_template=_pod_spec_from_wire(template),
    )


def _prov_conditions_to_wire(conds: List[Condition]) -> List[Dict[str, Any]]:
    # knative apis.Condition wire shape (reference: provisioner_status.go:28-33)
    return [
        _drop_none(
            {
                "type": c.type,
                "status": c.status,
                "severity": c.severity or None,
                "reason": c.reason or None,
                "message": c.message or None,
                "lastTransitionTime": _ts(c.last_transition_time),
            }
        )
        for c in conds
    ]


def prov_condition_to_wire(c: Condition) -> Dict[str, Any]:
    """Wire shape of one provisioner condition — controllers build status
    patches from this so the patch and the serializer can never drift."""
    return _prov_conditions_to_wire([c])[0]


def _prov_conditions_from_wire(raw) -> List[Condition]:
    return [
        Condition(
            type=c.get("type", ""),
            status=c.get("status", "Unknown") or "Unknown",
            severity=c.get("severity", "") or "",
            reason=c.get("reason", "") or "",
            message=c.get("message", "") or "",
            last_transition_time=parse_ts(c.get("lastTransitionTime")),
        )
        for c in raw or []
    ]


def _provisioner_to_wire(p: Provisioner) -> Dict[str, Any]:
    c = p.spec.constraints
    spec = _drop_none(
        {
            "labels": dict(c.labels) or None,
            "taints": [_taint_to_wire(t) for t in c.taints] or None,
            "requirements": [_req_to_wire(r) for r in c.requirements.requirements] or None,
            "kubeletConfiguration": (
                {"clusterDNS": list(c.kubelet_configuration.cluster_dns)}
                if c.kubelet_configuration is not None
                else None
            ),
            "provider": c.provider,
            "ttlSecondsAfterEmpty": p.spec.ttl_seconds_after_empty,
            "ttlSecondsUntilExpired": p.spec.ttl_seconds_until_expired,
            "limits": (
                {"resources": quantities(p.spec.limits.resources)}
                if p.spec.limits is not None
                else None
            ),
            "solver": p.spec.solver or None,
            "disruptionBudget": p.spec.disruption_budget,
        }
    )
    return {
        "spec": spec,
        "status": _drop_none(
            {
                "lastScaleTime": _ts(p.status.last_scale_time),
                "resources": quantities(p.status.resources) or None,
                "conditions": _prov_conditions_to_wire(p.status.conditions) or None,
            }
        ),
    }


def _provisioner_from_wire(doc: Dict[str, Any]) -> Provisioner:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    kc = spec.get("kubeletConfiguration")
    limits = spec.get("limits")
    meta = meta_from_wire(doc.get("metadata") or {})
    return Provisioner(
        metadata=meta,
        spec=ProvisionerSpec(
            constraints=Constraints(
                labels=dict(spec.get("labels") or {}),
                taints=[_taint_from_wire(t) for t in spec.get("taints") or []],
                requirements=Requirements.new(
                    *(_req_from_wire(r) for r in spec.get("requirements") or [])
                ),
                kubelet_configuration=(
                    KubeletConfiguration(cluster_dns=list(kc.get("clusterDNS") or []))
                    if kc is not None
                    else None
                ),
                provider=spec.get("provider"),
            ),
            ttl_seconds_after_empty=spec.get("ttlSecondsAfterEmpty"),
            ttl_seconds_until_expired=spec.get("ttlSecondsUntilExpired"),
            limits=(
                Limits(resources=parse_quantities(limits.get("resources")))
                if limits is not None
                else None
            ),
            solver=spec.get("solver", "") or "",
            disruption_budget=(
                str(spec["disruptionBudget"])
                if spec.get("disruptionBudget") is not None
                else None
            ),
        ),
        status=ProvisionerStatus(
            last_scale_time=parse_ts(status.get("lastScaleTime")),
            resources=parse_quantities(status.get("resources")),
            conditions=_prov_conditions_from_wire(status.get("conditions")),
        ),
    )


def _pvc_to_wire(p: PersistentVolumeClaim) -> Dict[str, Any]:
    return {
        "spec": _drop_none(
            {
                "storageClassName": p.storage_class_name or None,
                "volumeName": p.volume_name or None,
            }
        )
    }


def _pvc_from_wire(doc: Dict[str, Any]) -> PersistentVolumeClaim:
    spec = doc.get("spec") or {}
    return PersistentVolumeClaim(
        metadata=meta_from_wire(doc.get("metadata") or {}),
        storage_class_name=spec.get("storageClassName", "") or "",
        volume_name=spec.get("volumeName", "") or "",
    )


def _pv_to_wire(p: PersistentVolume) -> Dict[str, Any]:
    spec: Dict[str, Any] = {}
    if p.node_affinity_required:
        spec["nodeAffinity"] = {
            "required": {"nodeSelectorTerms": [_term_to_wire(t) for t in p.node_affinity_required]}
        }
    return {"spec": spec}


def _pv_from_wire(doc: Dict[str, Any]) -> PersistentVolume:
    req = (((doc.get("spec") or {}).get("nodeAffinity") or {}).get("required") or {})
    return PersistentVolume(
        metadata=meta_from_wire(doc.get("metadata") or {}),
        node_affinity_required=[_term_from_wire(t) for t in req.get("nodeSelectorTerms") or []],
    )


def _storageclass_to_wire(s: StorageClass) -> Dict[str, Any]:
    # TopologySelectorTerm: matchLabelExpressions [{key, values}]
    return _drop_none(
        {
            "provisioner": "karpenter.test/provisioner",
            "allowedTopologies": [
                {
                    "matchLabelExpressions": [
                        {"key": r.key, "values": list(r.values)} for r in t.match_expressions
                    ]
                }
                for t in s.allowed_topologies
            ]
            or None,
        }
    )


def _storageclass_from_wire(doc: Dict[str, Any]) -> StorageClass:
    return StorageClass(
        metadata=meta_from_wire(doc.get("metadata") or {}),
        allowed_topologies=[
            NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(
                        key=e.get("key", ""), operator="In", values=list(e.get("values") or [])
                    )
                    for e in t.get("matchLabelExpressions") or []
                ]
            )
            for t in doc.get("allowedTopologies") or []
        ],
    )


def _pdb_to_wire(p: PodDisruptionBudget) -> Dict[str, Any]:
    return {
        "spec": _drop_none(
            {
                "selector": _selector_to_wire(p.selector),
                "minAvailable": p.min_available,
                "maxUnavailable": p.max_unavailable,
            }
        )
    }


def _pdb_from_wire(doc: Dict[str, Any]) -> PodDisruptionBudget:
    spec = doc.get("spec") or {}
    return PodDisruptionBudget(
        metadata=meta_from_wire(doc.get("metadata") or {}),
        selector=_selector_from_wire(spec.get("selector")),
        min_available=spec.get("minAvailable"),
        max_unavailable=spec.get("maxUnavailable"),
    )


def _lease_to_wire(l: Lease) -> Dict[str, Any]:
    return {
        "spec": _drop_none(
            {
                "holderIdentity": l.holder_identity or None,
                "leaseDurationSeconds": l.lease_duration_seconds,
                "acquireTime": _ts_micro(l.acquire_time),
                "renewTime": _ts_micro(l.renew_time),
                "leaseTransitions": l.lease_transitions or None,
            }
        )
    }


def _lease_from_wire(doc: Dict[str, Any]) -> Lease:
    spec = doc.get("spec") or {}
    return Lease(
        metadata=meta_from_wire(doc.get("metadata") or {}),
        holder_identity=spec.get("holderIdentity", "") or "",
        lease_duration_seconds=spec.get("leaseDurationSeconds", 15) or 15,
        acquire_time=parse_ts(spec.get("acquireTime")),
        renew_time=parse_ts(spec.get("renewTime")),
        lease_transitions=spec.get("leaseTransitions", 0) or 0,
    )


def _vwc_to_wire(obj) -> Dict[str, Any]:
    # webhooks entries are raw wire dicts (see api.objects) — passthrough
    return {"webhooks": [dict(w) for w in obj.webhooks]}


def _vwc_from_wire(doc: Dict[str, Any]):
    from karpenter_tpu.api.objects import ValidatingWebhookConfiguration

    return ValidatingWebhookConfiguration(
        metadata=meta_from_wire(doc.get("metadata") or {}),
        webhooks=[dict(w) for w in doc.get("webhooks") or []],
    )


def _event_to_wire(e) -> Dict[str, Any]:
    return _drop_none(
        {
            "involvedObject": _drop_none(
                {
                    "kind": e.involved_kind,
                    "name": e.involved_name,
                    "namespace": e.involved_namespace or None,
                }
            ),
            "reason": e.reason,
            "message": e.message,
            "type": e.type,
            "count": e.count,
            "source": {"component": e.source_component},
            "firstTimestamp": _ts(e.first_timestamp),
            "lastTimestamp": _ts(e.last_timestamp),
        }
    )


def _event_from_wire(doc: Dict[str, Any]):
    from karpenter_tpu.api.objects import Event

    inv = doc.get("involvedObject") or {}
    return Event(
        metadata=meta_from_wire(doc.get("metadata") or {}),
        involved_kind=inv.get("kind", ""),
        involved_name=inv.get("name", ""),
        involved_namespace=inv.get("namespace", ""),
        reason=doc.get("reason", ""),
        message=doc.get("message", ""),
        type=doc.get("type", "Normal"),
        count=int(doc.get("count") or 1),
        source_component=(doc.get("source") or {}).get("component", ""),
        first_timestamp=parse_ts(doc.get("firstTimestamp")) or 0.0,
        last_timestamp=parse_ts(doc.get("lastTimestamp")) or 0.0,
    )


_TO = {
    "pods": _pod_to_wire,
    "nodes": _node_to_wire,
    "daemonsets": _daemonset_to_wire,
    "provisioners": _provisioner_to_wire,
    "pvcs": _pvc_to_wire,
    "pvs": _pv_to_wire,
    "storageclasses": _storageclass_to_wire,
    "pdbs": _pdb_to_wire,
    "leases": _lease_to_wire,
    "validatingwebhookconfigurations": _vwc_to_wire,
    "mutatingwebhookconfigurations": _vwc_to_wire,
    "events": _event_to_wire,
}

_FROM = {
    "pods": _pod_from_wire,
    "nodes": _node_from_wire,
    "daemonsets": _daemonset_from_wire,
    "provisioners": _provisioner_from_wire,
    "pvcs": _pvc_from_wire,
    "pvs": _pv_from_wire,
    "storageclasses": _storageclass_from_wire,
    "pdbs": _pdb_from_wire,
    "leases": _lease_from_wire,
    "validatingwebhookconfigurations": _vwc_from_wire,
    "mutatingwebhookconfigurations": _vwc_from_wire,
    "events": _event_from_wire,
}


def json_merge(target, patch):
    """RFC 7386 JSON merge patch."""
    if not isinstance(patch, dict):
        return patch
    if not isinstance(target, dict):
        target = {}
    out = dict(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = json_merge(out.get(k), v)
    return out


def to_wire(kind: str, obj) -> Dict[str, Any]:
    api_version, k8s_kind, namespaced = KIND_INFO[kind]
    doc = {"apiVersion": api_version, "kind": k8s_kind}
    doc.update(_TO[kind](obj))
    doc["metadata"] = meta_to_wire(obj.metadata, namespaced)
    return doc


def from_wire(kind: str, doc: Dict[str, Any]):
    obj = _FROM[kind](doc)
    if not KIND_INFO[kind][2]:
        # cluster-scoped: the framework's store convention is namespace ""
        obj.metadata.namespace = ""
    return obj
