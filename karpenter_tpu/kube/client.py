"""In-memory cluster state store with watch events.

The reference's coordination substrate is the Kubernetes API server (watches,
list/get, patches, Bind/Evict subresources — SURVEY.md §5.8). This framework
is cluster-agnostic: controllers speak to this ``Cluster`` interface, which a
deployment can back with a real apiserver client; the in-memory implementation
is the test/benchmark substrate (the reference's envtest/fake-client analog).

Optimistic concurrency: every mutation bumps ``resource_version``; watches are
synchronous callbacks dispatched outside the store lock.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from karpenter_tpu.api.objects import (
    DaemonSet,
    LabelSelector,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
    StorageClass,
)
from karpenter_tpu.api.provisioner import Provisioner

WatchFn = Callable[[str, object], None]  # (event_type, object)


class Conflict(Exception):
    pass


class NotFound(Exception):
    pass


def resolve_pdb_threshold(value, total: int) -> Optional[int]:
    """PDB minAvailable/maxUnavailable accept ints or percentages
    ("50%"); percentages resolve against the matching-pod count. The
    disruption controller resolves BOTH with roundUp=true
    (intstr.GetScaledValueFromIntOrPercent): maxUnavailable "50%" of 3
    pods allows 2 evictions, not 1."""
    if value is None:
        return None
    if isinstance(value, int):
        return value
    s = str(value).strip()
    if s.endswith("%"):
        return math.ceil(total * float(s[:-1]) / 100.0)
    return int(s)


class _Store:
    def __init__(self):
        self.objects: Dict[Tuple[str, str], object] = {}  # (namespace, name) -> obj
        self.watchers: List[WatchFn] = []


class Cluster:
    """Typed object store: pods, nodes, daemonsets, provisioners, PVCs, PVs,
    storage classes, PDBs."""

    KINDS = ("pods", "nodes", "daemonsets", "provisioners", "pvcs", "pvs", "storageclasses", "pdbs", "leases", "validatingwebhookconfigurations", "mutatingwebhookconfigurations", "events")

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._lock = threading.RLock()
        self._stores: Dict[str, _Store] = {k: _Store() for k in self.KINDS}
        self._version = 0
        self.clock = clock or time.time
        # spec.nodeName index, maintained incrementally on every pod event
        # (all mutation paths — including the apiserver backend's
        # watch/cache writes — funnel through _notify; seed() indexes
        # directly). Incremental upkeep keeps drain sweeps O(pods-moved),
        # not O(nodes × pods) re-scans.
        self._pods_by_node: Dict[str, Dict[Tuple[str, str], Pod]] = {}
        self._pod_node_of: Dict[Tuple[str, str], str] = {}

    # -- generic helpers ---------------------------------------------------
    def version(self) -> int:
        """Monotonic store version: bumped by every mutation (and by
        ``seed``). A matching version proves NO object in any store moved
        between two reads — what the resident plan-reuse guard
        (solver/delta.py) keys topology-round reuse on. Reading the int is
        atomic under the GIL; no lock needed."""
        return self._version

    def _key(self, obj) -> Tuple[str, str]:
        return (obj.metadata.namespace, obj.metadata.name)

    def _index_pod(self, event: str, pod: Pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        with self._lock:
            old = self._pod_node_of.get(key)
            if event == "DELETED":
                if old is not None:
                    self._pods_by_node.get(old, {}).pop(key, None)
                    self._pod_node_of.pop(key, None)
                return
            new = pod.spec.node_name or None
            if old is not None and old != new:
                self._pods_by_node.get(old, {}).pop(key, None)
                self._pod_node_of.pop(key, None)
            if new is not None:
                self._pod_node_of[key] = new
                # keep the LATEST object (the apiserver backend replaces
                # pod objects on watch events)
                self._pods_by_node.setdefault(new, {})[key] = pod

    def _notify(self, kind: str, event: str, obj) -> None:
        if kind == "pods":
            self._index_pod(event, obj)
        for w in list(self._stores[kind].watchers):
            w(event, obj)

    def watch(self, kind: str, fn: WatchFn) -> None:
        self._stores[kind].watchers.append(fn)

    def seed(self, kind: str, obj) -> object:
        """Insert an object WITHOUT mutating it or dispatching events — for
        read-only shadow stores built from live objects (consolidation
        planning); the live cluster remains the owner of the object.

        A shadow's ``pods_on_node`` index reflects seed-time state: in-place
        mutations by the OWNING cluster (bind/merge_patch) bump only the
        owner's index generation, so use a shadow within one planning pass,
        not as a long-lived view."""
        with self._lock:
            self._stores[kind].objects[self._key(obj)] = obj
            # the store's content moved even though the object is untouched:
            # version-keyed consumers (the resident plan-reuse guard in
            # solver/delta.py) must see seeded state as a new cluster state
            self._version += 1
        if kind == "pods":
            self._index_pod("ADDED", obj)  # no events, but the index must see it
        return obj

    def create(self, kind: str, obj) -> object:
        with self._lock:
            store = self._stores[kind]
            key = self._key(obj)
            if key in store.objects:
                raise Conflict(f"{kind} {key} already exists")
            self._version += 1
            obj.metadata.resource_version = self._version
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = self.clock()
            store.objects[key] = obj
        self._notify(kind, "ADDED", obj)
        return obj

    def get(self, kind: str, name: str, namespace: str = "default"):
        with self._lock:
            obj = self._stores[kind].objects.get((namespace, name))
        if obj is None:
            raise NotFound(f"{kind} {namespace}/{name} not found")
        return obj

    def try_get(self, kind: str, name: str, namespace: str = "default"):
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def update(self, kind: str, obj) -> object:
        with self._lock:
            store = self._stores[kind]
            key = self._key(obj)
            if key not in store.objects:
                raise NotFound(f"{kind} {key} not found")
            self._version += 1
            obj.metadata.resource_version = self._version
            store.objects[key] = obj
        self._notify(kind, "MODIFIED", obj)
        return obj

    def merge_patch(self, kind: str, name: str, patch: dict, namespace: str = "default"):
        """RFC 7386 merge patch in Kubernetes wire shape — the reference's
        single-patch-per-reconcile idiom (node/controller.go:106-115), so
        controllers patch uniformly against this store and ``ApiCluster``.
        Identity-preserving: the stored object is updated in place (watchers
        and tests hold references to it)."""
        import dataclasses

        from karpenter_tpu.kube import serde

        with self._lock:
            obj = self._stores[kind].objects.get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            merged_doc = serde.json_merge(serde.to_wire(kind, obj), patch)
            fresh = serde.from_wire(kind, merged_doc)
            fresh.metadata.namespace = obj.metadata.namespace
            fresh.metadata.uid = obj.metadata.uid
            fresh.metadata.creation_timestamp = obj.metadata.creation_timestamp
            fresh.metadata.deletion_timestamp = obj.metadata.deletion_timestamp
            for f in dataclasses.fields(obj):
                setattr(obj, f.name, getattr(fresh, f.name))
            self._version += 1
            obj.metadata.resource_version = self._version
        self._notify(kind, "MODIFIED", obj)
        return obj

    def patch_status(self, kind: str, name: str, status: dict, namespace: str = "default"):
        """Merge-patch the status subresource (``status`` is the wire-shape
        dict of status fields). This is the ONLY route by which controllers
        persist status for kinds whose CRD enables ``subresources.status``
        (deploy/crd.yaml): a real apiserver silently drops status changes
        carried on main-resource writes, so carrying them on ``update()``
        works against this in-memory store but not in production."""
        return self.merge_patch(kind, name, {"status": status}, namespace=namespace)

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        """Delete with finalizer semantics: objects carrying finalizers only
        get a deletion timestamp; removal happens when finalizers clear.
        Repeat deletes of an already-terminating object are no-ops, like the
        apiserver — finalizers must never be bypassed by a second delete."""
        with self._lock:
            store = self._stores[kind]
            obj = store.objects.get((namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            if obj.metadata.finalizers:
                if obj.metadata.deletion_timestamp is not None:
                    return  # already terminating
                obj.metadata.deletion_timestamp = self.clock()
                self._version += 1
                obj.metadata.resource_version = self._version
                event = "MODIFIED"
            else:
                if obj.metadata.deletion_timestamp is None:
                    obj.metadata.deletion_timestamp = self.clock()
                del store.objects[(namespace, name)]
                event = "DELETED"
        self._notify(kind, event, obj)

    def remove_finalizer(self, kind: str, obj, finalizer: str) -> None:
        with self._lock:
            if finalizer in obj.metadata.finalizers:
                obj.metadata.finalizers.remove(finalizer)
            if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
                key = self._key(obj)
                self._stores[kind].objects.pop(key, None)
                deleted = True
            else:
                deleted = False
        self._notify(kind, "DELETED" if deleted else "MODIFIED", obj)

    def list(self, kind: str, namespace: Optional[str] = None) -> List:
        with self._lock:
            objs = list(self._stores[kind].objects.values())
        if namespace is not None:
            objs = [o for o in objs if o.metadata.namespace == namespace]
        return objs

    # -- typed conveniences ------------------------------------------------
    def pods(self, namespace: Optional[str] = None) -> List[Pod]:
        return self.list("pods", namespace)

    def nodes(self) -> List[Node]:
        return self.list("nodes")

    def daemonsets(self) -> List[DaemonSet]:
        return self.list("daemonsets")

    def provisioners(self) -> List[Provisioner]:
        return self.list("provisioners")

    def list_pods_matching(
        self, namespace: Optional[str], selector: Optional[LabelSelector]
    ) -> List[Pod]:
        pods = self.pods(namespace)
        if selector is None:
            return pods
        return [p for p in pods if selector.matches(p.metadata.labels)]

    def pods_on_node(self, node_name: str) -> List[Pod]:
        """The `spec.nodeName` field-index equivalent (reference:
        manager.go:39): incrementally maintained, so the per-node queries
        the node/termination/metrics controllers issue are O(pods on that
        node) instead of a full-store scan each."""
        with self._lock:
            return list(self._pods_by_node.get(node_name, {}).values())

    # -- subresources ------------------------------------------------------
    def bind(self, pod: Pod, node_name: str) -> None:
        """The Bind subresource: assign pod to node."""
        with self._lock:
            pod.spec.node_name = node_name
            self._version += 1
            pod.metadata.resource_version = self._version
        self._notify("pods", "MODIFIED", pod)

    def evict_with_hint(self, pod: Pod):
        """``(evicted, retry_after_seconds)``: the Retry-After-aware evict
        surface the termination queue prefers. The in-memory store has no
        pacing opinion (None); the real-apiserver backend overrides this to
        surface the server's 429 ``Retry-After`` header so rate-limited
        requeues honor the server's schedule instead of a blind interval."""
        return self.evict(pod), None

    def evict(self, pod: Pod) -> bool:
        """The Evict subresource. Returns False (HTTP 429 analog) if a PDB
        would be violated; otherwise deletes the pod with the same finalizer
        semantics as ``delete`` (there is no kubelet here, so eviction
        completes immediately, like envtest)."""
        with self._lock:
            # already-terminating pods evict without PDB enforcement, like
            # the apiserver — they no longer count against the budget
            if pod.metadata.deletion_timestamp is not None and pod.metadata.finalizers:
                return True
            for pdb in self.list("pdbs", pod.metadata.namespace):
                if pdb.selector is None or not pdb.selector.matches(pod.metadata.labels):
                    continue
                matching = [
                    p
                    for p in self.pods(pod.metadata.namespace)
                    if pdb.selector is None or pdb.selector.matches(p.metadata.labels)
                ]
                healthy = [p for p in matching if p.metadata.deletion_timestamp is None]
                min_avail = resolve_pdb_threshold(pdb.min_available, len(matching))
                max_unavail = resolve_pdb_threshold(pdb.max_unavailable, len(matching))
                if min_avail is not None and len(healthy) - 1 < min_avail:
                    return False
                if max_unavail is not None and (len(matching) - (len(healthy) - 1)) > max_unavail:
                    return False
            key = self._key(pod)
            if pod.metadata.finalizers:
                # (terminating finalizer pods short-circuited above)
                pod.metadata.deletion_timestamp = self.clock()
                self._version += 1
                pod.metadata.resource_version = self._version
                event = "MODIFIED"
            else:
                self._stores["pods"].objects.pop(key, None)
                pod.metadata.deletion_timestamp = pod.metadata.deletion_timestamp or self.clock()
                event = "DELETED"
        self._notify("pods", event, pod)
        return True
