"""An in-process Kubernetes apiserver double speaking the real protocol.

The reference's integration substrate is envtest — a real kube-apiserver +
etcd booted per suite (``pkg/test/environment.go:53-98``). The equivalent
here: this server exposes the actual Kubernetes REST surface (list/get/
create/update/merge-patch/finalizer-aware delete, Binding and Eviction
subresources, chunked ``?watch=true`` streams of newline-delimited JSON
events) over a real TCP socket, backed by the in-memory ``Cluster``.
``ApiCluster`` connects to it exactly as it would to a production
apiserver, so the full controller stack is exercised across a genuine
HTTP/serialization boundary.

Usage::

    env = TestApiServer()
    env.start()
    cluster = ApiCluster(env.url)
    cluster.start(); cluster.wait_for_sync()
    ...
    env.stop()
"""

from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from karpenter_tpu.kube import serde
from karpenter_tpu.kube.client import Cluster, Conflict, NotFound

# plural -> kind (reverse of apiserver.RESOURCES)
PLURALS: Dict[str, str] = {
    "pods": "pods",
    "nodes": "nodes",
    "daemonsets": "daemonsets",
    "provisioners": "provisioners",
    "persistentvolumeclaims": "pvcs",
    "persistentvolumes": "pvs",
    "storageclasses": "storageclasses",
    "poddisruptionbudgets": "pdbs",
    "leases": "leases",
    "validatingwebhookconfigurations": "validatingwebhookconfigurations",
    "mutatingwebhookconfigurations": "mutatingwebhookconfigurations",
    "events": "events",
}


from karpenter_tpu.kube.serde import json_merge as merge_patch  # shared RFC 7386 impl

# Kinds whose CRD declares ``subresources: {status: {}}`` (deploy/crd.yaml):
# like a real apiserver, main-resource writes to these kinds silently keep
# the CURRENT status, and status changes must come through the ``/status``
# subresource.
STATUS_SUBRESOURCE_KINDS = {"provisioners"}


def _status(code: int, reason: str, message: str) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Status",
        "status": "Failure" if code >= 400 else "Success",
        "code": code,
        "reason": reason,
        "message": message,
    }


class _Request:
    """Parsed REST path: /api/v1/namespaces/{ns}/pods/{name}/{sub}."""

    def __init__(self, path: str):
        u = urlparse(path)
        self.query = parse_qs(u.query)
        parts = [p for p in u.path.split("/") if p]
        # strip the group/version prefix: api/v1 or apis/<group>/<version>
        if parts and parts[0] == "api":
            parts = parts[2:]
        elif parts and parts[0] == "apis":
            parts = parts[3:]
        self.namespace: Optional[str] = None
        if parts and parts[0] == "namespaces" and len(parts) >= 2:
            self.namespace = parts[1]
            parts = parts[2:]
        self.plural = parts[0] if parts else ""
        self.name = parts[1] if len(parts) > 1 else None
        self.subresource = parts[2] if len(parts) > 2 else None
        self.kind = PLURALS.get(self.plural)

    @property
    def watch(self) -> bool:
        return self.query.get("watch", ["false"])[0] == "true"


class TestApiServer:
    __test__ = False  # not a pytest class, despite the name

    def __init__(
        self,
        cluster: Optional[Cluster] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        chaos=None,
    ):
        self.cluster = cluster or Cluster()
        # control-plane chaos (testing/chaos.py ApiServerChaos): when set,
        # every request consults it first — injected 5xx, 429-with-
        # Retry-After, latency, and blackout connection drops wrap the
        # whole REST surface, watch connects included. Settable live so a
        # storm leg can phase chaos on and off mid-run.
        self.chaos = chaos
        # the PDB pacing hint a blocked eviction advertises (Retry-After)
        self.eviction_retry_after = 1.0
        self._watch_queues: Dict[str, list] = {k: [] for k in Cluster.KINDS}
        self._watch_lock = threading.Lock()
        # recent events per kind, stamped with the store version, so a
        # watch starting at resourceVersion=N replays everything after N —
        # without this, objects created between a client's initial list and
        # its watch connection are silently lost (real apiserver semantics)
        import collections

        self._history: Dict[str, "collections.deque"] = {
            k: collections.deque(maxlen=4096) for k in Cluster.KINDS
        }
        for kind in Cluster.KINDS:
            self.cluster.watch(kind, self._fanout(kind))
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send_json(self, code: int, doc: dict, headers=None) -> None:
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _chaos(self, method: str) -> bool:
                """True when the chaos layer handled (or dropped) the
                request; the real handler must return immediately."""
                chaos = server.chaos
                if chaos is None:
                    return False
                return chaos.intercept(self, method, self.path)

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(length) or b"{}")

            def do_GET(self):
                if self._chaos("GET"):
                    return
                req = _Request(self.path)
                if req.kind is None:
                    return self._send_json(404, _status(404, "NotFound", f"no resource {req.plural}"))
                if req.name is None:
                    if req.watch:
                        return server._serve_watch(self, req)
                    return server._serve_list(self, req)
                try:
                    obj = server._get(req)
                except NotFound as e:
                    return self._send_json(404, _status(404, "NotFound", str(e)))
                self._send_json(200, serde.to_wire(req.kind, obj))

            def do_POST(self):
                if self._chaos("POST"):
                    return
                req = _Request(self.path)
                if req.kind is None:
                    return self._send_json(404, _status(404, "NotFound", f"no resource {req.plural}"))
                doc = self._body()
                if req.subresource == "binding":
                    return server._serve_binding(self, req, doc)
                if req.subresource == "eviction":
                    return server._serve_eviction(self, req, doc)
                obj = serde.from_wire(req.kind, doc)
                if req.namespace is not None and serde.KIND_INFO[req.kind][2]:
                    obj.metadata.namespace = req.namespace
                try:
                    created = server.cluster.create(req.kind, obj)
                except Conflict as e:
                    return self._send_json(409, _status(409, "AlreadyExists", str(e)))
                self._send_json(201, serde.to_wire(req.kind, created))

            def do_PUT(self):
                if self._chaos("PUT"):
                    return
                req = _Request(self.path)
                if req.kind is None or req.name is None:
                    return self._send_json(404, _status(404, "NotFound", "bad path"))
                doc = self._body()
                obj = serde.from_wire(req.kind, doc)
                try:
                    current = server._get(req)
                except NotFound as e:
                    return self._send_json(404, _status(404, "NotFound", str(e)))
                sent_rv = obj.metadata.resource_version
                if sent_rv and sent_rv != current.metadata.resource_version:
                    return self._send_json(
                        409, _status(409, "Conflict", f"resourceVersion {sent_rv} is stale")
                    )
                obj.metadata.namespace = current.metadata.namespace
                obj.metadata.uid = current.metadata.uid
                obj.metadata.creation_timestamp = current.metadata.creation_timestamp
                if current.metadata.deletion_timestamp is not None:
                    obj.metadata.deletion_timestamp = current.metadata.deletion_timestamp
                if req.kind in STATUS_SUBRESOURCE_KINDS:
                    if req.subresource == "status":
                        # PUT to /status replaces status only
                        current.status = obj.status
                        obj = current
                    else:
                        # main-resource write: the apiserver keeps the
                        # current status when subresources.status is on
                        obj.status = current.status
                elif req.subresource:
                    return self._send_json(
                        404, _status(404, "NotFound", f"no subresource {req.subresource}")
                    )
                server.cluster.update(req.kind, obj)
                self._send_json(200, serde.to_wire(req.kind, obj))

            def do_PATCH(self):
                if self._chaos("PATCH"):
                    return
                req = _Request(self.path)
                if req.kind is None or req.name is None:
                    return self._send_json(404, _status(404, "NotFound", "bad path"))
                patch = self._body()
                try:
                    current = server._get(req)
                except NotFound as e:
                    return self._send_json(404, _status(404, "NotFound", str(e)))
                if req.kind in STATUS_SUBRESOURCE_KINDS:
                    if req.subresource == "status":
                        # only the status field of the patch applies
                        patch = (
                            {"status": patch["status"]}
                            if patch.get("status") is not None
                            else {}
                        )
                    elif "status" in patch:
                        # main-resource patch: status changes are dropped
                        patch = {k: v for k, v in patch.items() if k != "status"}
                elif req.subresource:
                    return self._send_json(
                        404, _status(404, "NotFound", f"no subresource {req.subresource}")
                    )
                merged_doc = merge_patch(serde.to_wire(req.kind, current), patch)
                obj = serde.from_wire(req.kind, merged_doc)
                obj.metadata.namespace = current.metadata.namespace
                obj.metadata.uid = current.metadata.uid
                obj.metadata.creation_timestamp = current.metadata.creation_timestamp
                obj.metadata.deletion_timestamp = current.metadata.deletion_timestamp
                if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
                    # patching away the last finalizer frees a terminating
                    # object, like the apiserver's finalizer GC
                    server.cluster.update(req.kind, obj)
                    server.cluster.remove_finalizer(req.kind, obj, "")
                else:
                    server.cluster.update(req.kind, obj)
                self._send_json(200, serde.to_wire(req.kind, obj))

            def do_DELETE(self):
                if self._chaos("DELETE"):
                    return
                req = _Request(self.path)
                if req.kind is None or req.name is None:
                    return self._send_json(404, _status(404, "NotFound", "bad path"))
                namespace = req.namespace if req.namespace is not None else server._default_ns(req.kind)
                try:
                    obj = server.cluster.get(req.kind, req.name, namespace=namespace)
                    server.cluster.delete(req.kind, req.name, namespace=namespace)
                except NotFound as e:
                    return self._send_json(404, _status(404, "NotFound", str(e)))
                still = server.cluster.try_get(req.kind, req.name, namespace=namespace)
                if still is not None:
                    # finalizers pinned it: terminating, not gone
                    return self._send_json(200, serde.to_wire(req.kind, still))
                self._send_json(200, _status(200, "Success", "deleted"))

        class _Server(ThreadingHTTPServer):
            def handle_error(self, request, client_address):
                import sys

                exc = sys.exc_info()[1]
                # chaos blackout drops and impatient clients (short event
                # deadlines) tear connections mid-request routinely — that
                # is the scenario, not a server bug worth a traceback
                if isinstance(exc, OSError):
                    return
                super().handle_error(request, client_address)

        self._httpd = _Server((host, port), Handler)
        self._httpd.daemon_threads = True
        self.url = f"http://{host}:{self._httpd.server_address[1]}"

    # -- store helpers -----------------------------------------------------
    def _default_ns(self, kind: str) -> str:
        return "" if not serde.KIND_INFO[kind][2] else "default"

    def _get(self, req: _Request):
        namespace = req.namespace if req.namespace is not None else None
        if namespace is not None:
            return self.cluster.get(req.kind, req.name, namespace=namespace)
        # cluster-scoped or cross-namespace lookup by name
        for obj in self.cluster.list(req.kind):
            if obj.metadata.name == req.name:
                return obj
        raise NotFound(f"{req.kind} {req.name} not found")

    def _fanout(self, kind: str):
        def push(event: str, obj) -> None:
            doc = serde.to_wire(kind, obj)
            ev = {"type": event, "object": doc}
            with self._watch_lock:
                self._history[kind].append((self.cluster._version, ev))
                for q in self._watch_queues[kind]:
                    q.put(ev)

        return push

    # -- list / watch ------------------------------------------------------
    def _serve_list(self, handler, req: _Request) -> None:
        objs = self.cluster.list(req.kind, req.namespace)
        api_version, k8s_kind, _ = serde.KIND_INFO[req.kind]
        doc = {
            "apiVersion": api_version,
            "kind": f"{k8s_kind}List",
            "metadata": {"resourceVersion": str(self.cluster._version)},
            "items": [serde.to_wire(req.kind, o) for o in objs],
        }
        handler._send_json(200, doc)

    def _serve_watch(self, handler, req: _Request) -> None:
        q: "queue.Queue" = queue.Queue()
        try:
            since = int(req.query.get("resourceVersion", ["0"])[0] or 0)
        except ValueError:
            since = 0
        try:
            # real apiserver semantics: the stream ends with a clean EOF
            # after timeoutSeconds, and the client resumes from the last RV
            timeout_s = float(req.query.get("timeoutSeconds", ["0"])[0] or 0)
        except ValueError:
            timeout_s = 0.0
        deadline = time.monotonic() + timeout_s if timeout_s > 0 else None
        with self._watch_lock:
            # replay-then-register atomically: nothing between `since` and
            # "now" may be dropped, nothing live may jump the backlog
            for seq, ev in self._history[req.kind]:
                if seq > since:
                    q.put(ev)
            self._watch_queues[req.kind].append(q)
        last_rv = since  # highest RV delivered on THIS stream; bookmarks
        # must never advance the client past an undelivered event
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Transfer-Encoding", "chunked")
            handler.end_headers()

            def send_chunk(data: bytes) -> None:
                handler.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                handler.wfile.flush()

            while True:
                if deadline is not None and time.monotonic() >= deadline:
                    # terminal chunk: clean EOF the client resumes from
                    handler.wfile.write(b"0\r\n\r\n")
                    handler.wfile.flush()
                    return
                try:
                    event = q.get(timeout=1.0)
                except queue.Empty:
                    # heartbeat bookmark keeps half-open connections honest
                    # and advances the client's resume RV like a real
                    # apiserver's allowWatchBookmarks
                    send_chunk(
                        json.dumps(
                            {
                                "type": "BOOKMARK",
                                "object": {"metadata": {"resourceVersion": str(last_rv)}},
                            }
                        ).encode()
                        + b"\n"
                    )
                    continue
                if req.namespace is not None:
                    meta = (event["object"].get("metadata") or {})
                    if meta.get("namespace", "default") != req.namespace:
                        continue
                try:
                    last_rv = int((event["object"].get("metadata") or {}).get("resourceVersion") or last_rv)
                except (TypeError, ValueError):
                    pass
                send_chunk(json.dumps(event).encode() + b"\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            with self._watch_lock:
                try:
                    self._watch_queues[req.kind].remove(q)
                except ValueError:
                    pass

    # -- subresources ------------------------------------------------------
    def _serve_binding(self, handler, req: _Request, doc: dict) -> None:
        namespace = req.namespace if req.namespace is not None else "default"
        pod = self.cluster.try_get("pods", req.name, namespace=namespace)
        if pod is None:
            return handler._send_json(404, _status(404, "NotFound", f"pod {req.name}"))
        node_name = (doc.get("target") or {}).get("name", "")
        if pod.spec.node_name:
            # real apiserver semantics: Binding an already-assigned pod is
            # 409 even to the same node — migration must evict and let the
            # workload recreate
            return handler._send_json(
                409,
                _status(409, "Conflict", f"pod {req.name} is already assigned to {pod.spec.node_name}"),
            )
        self.cluster.bind(pod, node_name)
        handler._send_json(201, _status(201, "Created", "bound"))

    def _serve_eviction(self, handler, req: _Request, doc: dict) -> None:
        namespace = req.namespace if req.namespace is not None else "default"
        pod = self.cluster.try_get("pods", req.name, namespace=namespace)
        if pod is None:
            return handler._send_json(404, _status(404, "NotFound", f"pod {req.name}"))
        if not self.cluster.evict(pod):
            # real apiserver semantics: the PDB 429 carries Retry-After so
            # the evictor requeues on the server's schedule, not a blind one
            return handler._send_json(
                429, _status(429, "TooManyRequests", "disruption budget violated"),
                headers={"Retry-After": f"{self.eviction_retry_after:g}"},
            )
        handler._send_json(201, _status(201, "Created", "evicted"))

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
