"""Resilient kube transport: the one choke point every apiserver call takes.

Every subsystem shares exactly one dependency — the Kubernetes API server —
and before this module each ``ApiCluster`` write was a single-shot HTTP
request: no retry, no backoff, no 429 handling, no flow control, so a
10-second apiserver blip failed every bind, status patch, shard-lease
renewal, and journal write in flight. :class:`KubeTransport` funnels all of
``ApiCluster``'s traffic through per-verb-class policy (docs/partition.md):

- **read** (uncached GET/LIST) and **mutate** (PUT/PATCH/DELETE — all
  idempotent against apiserver optimistic concurrency): jittered retries on
  connection errors and 5xx, bounded by a hard per-operation deadline that
  the ambient reconcile-round :class:`~karpenter_tpu.resilience.Budget`
  further caps.
- **create** (POST: create/bind/evict — NOT idempotent at the HTTP layer):
  never retried here. Creates keep riding their existing idempotency
  ladders (launch tokens, the 409-rebind check) one level up.
- **watch** (the informer re-list): no transport retry — the watch loop
  owns its own jittered exponential backoff, and stacking two retry layers
  would multiply load against a struggling apiserver.
- **events**: zero retries and a short deadline — an Event write must never
  hold a reconcile hostage; failures are counted
  (``karpenter_kube_events_dropped_total``) and dropped by the recorder.

A 429 anywhere is obeyed, not retried blindly: the server's ``Retry-After``
is slept (retryable classes) or surfaced as :class:`KubeThrottled` so the
caller's own requeue can honor it (eviction's rate-limited queue). 429s
count as breaker *successes* — a throttling apiserver is alive.

Client-side flow control is a QPS/burst token bucket
(``--kube-qps``/``--kube-burst``, client-go's limiter analog) with
mutations prioritized over reads: a reserve slice of the bucket is only
spendable by writes, so an informer re-list storm after a partition heals
cannot starve the binds that actually drain pending pods.

A :class:`~karpenter_tpu.resilience.CircuitBreaker` (availability
semantics, shared across verb classes) records every attempt; while OPEN,
requests fail fast with :class:`ApiUnavailable` and ``ApiCluster`` flips
into degraded read-from-cache mode (``get_live`` serves the informer view).
The lease layer classifies these transport verdicts with
:func:`is_unreachable` — an unreachable apiserver is NOT a peer holding
the lease, and must fence rather than instantly resign (kube/leader.py).

Observability: ``karpenter_kube_request_duration_seconds{verb,kind,code}``
per attempt, retry/throttle counters, and one ``kube.request`` span per
logical call so the SLO engine can carry a ``kube.p99`` objective.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from karpenter_tpu import metrics
from karpenter_tpu.resilience import CircuitBreaker
from karpenter_tpu.resilience.policy import current_budget, decorrelated_jitter

logger = logging.getLogger("karpenter.kube.transport")

# verb classes (module constants so call sites read declaratively)
VERB_READ = "read"
VERB_MUTATE = "mutate"
VERB_CREATE = "create"
VERB_WATCH = "watch"
VERB_EVENTS = "events"
VERB_LEASE = "lease"

DEPENDENCY = "kube-apiserver"


class ApiUnavailable(Exception):
    """The apiserver is unreachable (breaker open, or the call was not
    even attempted). Callers with a cache may degrade to it; the lease
    layer reads this as UNREACHABLE, never as a lost lease."""


class KubeThrottled(Exception):
    """Flow control refused the call — either the apiserver answered 429
    (``retry_after`` carries its Retry-After hint) or the client-side
    limiter timed out. Callers honor the hint instead of a blind retry."""

    def __init__(self, message: str, retry_after: Optional[float] = None):
        super().__init__(message)
        self.retry_after = retry_after


def is_unreachable(exc: BaseException) -> bool:
    """Is this failure an UNREACHABLE apiserver (as opposed to a positive
    answer like 404/409, or a programming error)? The lease layer's
    REJECTED/UNREACHABLE split hangs off this classification — a fenced
    replica and a genuinely outbid one behave very differently."""
    if isinstance(exc, (ApiUnavailable, KubeThrottled)):
        return True
    if isinstance(exc, (OSError, http.client.HTTPException, json.JSONDecodeError)):
        return True  # connection refused/reset, timeouts, torn responses
    status = getattr(exc, "status", None)
    if isinstance(status, int) and (status >= 500 or status == 429):
        return True  # ApiError: the server is present but failing
    return False


@dataclass(frozen=True)
class VerbPolicy:
    """Per-verb-class transport policy."""

    max_attempts: int
    deadline: float  # hard per-operation allowance (budget-capped further)
    limiter_wait: float  # longest the flow limiter may park this call
    priority: bool  # mutation-priority lane in the flow limiter
    count_drops: bool = False  # events: failures increment the drop counter
    # lease traffic IS the fencing signal: it must never be fast-failed by
    # a breaker that some OTHER traffic opened (a 1s blip would read as a
    # 5s outage to the lease layer — spurious fleet-wide fencing). Bypass
    # the breaker's allow() gate; outcomes are still recorded.
    bypass_breaker: bool = False


POLICIES = {
    VERB_READ: VerbPolicy(max_attempts=3, deadline=15.0, limiter_wait=5.0, priority=False),
    VERB_MUTATE: VerbPolicy(max_attempts=3, deadline=15.0, limiter_wait=5.0, priority=True),
    VERB_CREATE: VerbPolicy(max_attempts=1, deadline=15.0, limiter_wait=5.0, priority=True),
    VERB_WATCH: VerbPolicy(max_attempts=1, deadline=15.0, limiter_wait=5.0, priority=False),
    VERB_EVENTS: VerbPolicy(
        max_attempts=1, deadline=2.0, limiter_wait=0.2, priority=False, count_drops=True
    ),
    # single attempt (the renew loop is the retry), short deadline (a
    # renew slower than the renew cadence is useless), breaker-bypassed
    VERB_LEASE: VerbPolicy(
        max_attempts=1, deadline=5.0, limiter_wait=1.0, priority=True,
        bypass_breaker=True,
    ),
}

# connection/transport failures worth a retry (a 5xx status is handled by
# code, not exception type)
_TRANSPORT_ERRORS = (OSError, http.client.HTTPException, json.JSONDecodeError)


class FlowLimiter:
    """QPS/burst token bucket with a mutation-priority reserve.

    The client-go limiter is one undifferentiated bucket; here the last
    ``reserve`` tokens are spendable only by priority (mutating) calls, so
    a read storm — the informer re-list wave after a partition heals is
    the canonical one — drains the bucket down to the reserve and no
    further, and binds/patches keep flowing at full rate."""

    def __init__(
        self,
        qps: float,
        burst: int,
        reserve_fraction: float = 0.1,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.qps = max(float(qps), 0.001)
        self.burst = max(int(burst), 1)
        self.reserve = max(1.0, self.burst * reserve_fraction) if self.burst > 1 else 0.0
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._tokens = float(self.burst)  # guarded-by: self._lock
        self._last = clock()  # guarded-by: self._lock

    def _refill_locked(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
        self._last = now

    def try_take(self, priority: bool) -> bool:
        floor = 0.0 if priority else self.reserve
        with self._lock:
            self._refill_locked()
            if self._tokens - 1.0 >= floor:
                self._tokens -= 1.0
                return True
            return False

    def take(self, priority: bool, timeout: float) -> Tuple[bool, bool]:
        """Block (bounded) for a token. Returns ``(acquired, waited)`` —
        ``waited`` flags that flow control actually delayed the caller, so
        the transport can count client-side throttling."""
        if self.try_take(priority):
            return True, False
        deadline = self._clock() + max(timeout, 0.0)
        while True:
            if self.try_take(priority):
                return True, True
            remaining = deadline - self._clock()
            if remaining <= 0:
                return False, True
            self._sleep(min(max(1.0 / self.qps, 0.001), remaining, 0.05))


class KubeTransport:
    """The choke point: flow control → breaker → attempt loop with
    per-verb-class retry/backoff — see the module docstring."""

    def __init__(
        self,
        qps: float = 200.0,
        burst: int = 300,
        breaker: Optional[CircuitBreaker] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        backoff_base: float = 0.05,
        backoff_cap: float = 2.0,
    ):
        self.limiter = FlowLimiter(qps, burst, clock=clock, sleep=sleep)
        # availability semantics: trips on a windowed failure rate, so a
        # chaos-level error rate keeps flowing while a dead apiserver
        # opens within a handful of calls; 429s record as SUCCESS.
        self.breaker = breaker or CircuitBreaker(
            dependency=DEPENDENCY, open_seconds=5.0, clock=clock
        )
        self._clock = clock
        self._sleep = sleep
        self._backoff_base = backoff_base
        self._backoff_cap = backoff_cap

    def degraded(self) -> bool:
        """Is the transport currently refusing calls (breaker open, inside
        its cool-off)? Controllers use this to flip into read-from-cache
        mode instead of paying a fast-fail per read."""
        from karpenter_tpu.resilience.breaker import OPEN

        return self.breaker.state == OPEN and not self.breaker.available()

    def _allowance(self, policy: VerbPolicy) -> float:
        budget = current_budget.get()
        if budget is None:
            return policy.deadline
        return min(policy.deadline, max(budget.remaining(), 0.0))

    def request(
        self,
        verb_class: str,
        method: str,
        kind: str,
        attempt: Callable[[], Tuple[int, dict, Optional[float]]],
    ) -> Tuple[int, dict, Optional[float]]:
        """Run one logical request through the policy ladder. ``attempt``
        performs one HTTP round trip and returns
        ``(status, body, retry_after_seconds_or_None)``; the transport
        decides retries. Returns the final attempt's triple — positive
        answers (2xx/404/409/...) go back to the caller for disposition."""
        from karpenter_tpu import obs

        policy = POLICIES[verb_class]
        try:
            with obs.tracer().span(
                "kube.request",
                attrs={"verb": method, "kind": kind, "class": verb_class},
            ) as sp:
                status, doc, retry_after, attempts = self._request_inner(
                    policy, verb_class, method, kind
                )(attempt)
                sp.set_attribute("code", status)
                if attempts > 1:
                    sp.set_attribute("retries", attempts - 1)
                if policy.count_drops and status >= 500:
                    # a 5xx final answer is returned (the caller raises and
                    # the recorder swallows): that write IS a dropped event
                    # and must count like the exception-shaped drops do
                    metrics.KUBE_EVENTS_DROPPED.inc()
                return status, doc, retry_after
        except Exception:
            if policy.count_drops:
                metrics.KUBE_EVENTS_DROPPED.inc()
            raise

    def _request_inner(self, policy: VerbPolicy, verb_class: str, method: str, kind: str):
        def run(attempt):
            start = self._clock()
            allowance = self._allowance(policy)
            taken, waited = self.limiter.take(
                policy.priority, min(policy.limiter_wait, max(allowance, 0.0))
            )
            if waited:
                metrics.KUBE_THROTTLED.labels(source="client").inc()
            if not taken:
                raise KubeThrottled(
                    f"kube client flow control refused {method} {kind} "
                    f"(qps {self.limiter.qps:g}/burst {self.limiter.burst})",
                    retry_after=1.0 / self.limiter.qps,
                )
            backoffs = decorrelated_jitter(self._backoff_base, self._backoff_cap)
            attempts = 0
            while True:
                if not policy.bypass_breaker and not self.breaker.allow():
                    raise ApiUnavailable(
                        f"apiserver circuit open; {method} {kind} not attempted"
                    )
                attempts += 1
                t0 = self._clock()
                try:
                    status, doc, retry_after = attempt()
                except _TRANSPORT_ERRORS as e:
                    self.breaker.record_failure()
                    self._observe(method, kind, "error", t0)
                    pause = next(backoffs)
                    if (
                        attempts >= policy.max_attempts
                        or self._clock() - start + pause > allowance
                    ):
                        raise
                    metrics.KUBE_REQUEST_RETRIES.labels(verb_class=verb_class).inc()
                    logger.debug(
                        "kube %s %s transport error (%s); retry %d in %.2fs",
                        method, kind, e, attempts, pause,
                    )
                    self._sleep(pause)
                    continue
                self._observe(method, kind, str(status), t0)
                if status == 429:
                    # a throttling apiserver is ALIVE: breaker success, and
                    # the server's own hint paces the retry (or the caller)
                    self.breaker.record_success()
                    metrics.KUBE_THROTTLED.labels(source="server").inc()
                    hint = retry_after if retry_after is not None else next(backoffs)
                    if (
                        policy.max_attempts > 1
                        and attempts < policy.max_attempts
                        and self._clock() - start + hint <= allowance
                    ):
                        metrics.KUBE_REQUEST_RETRIES.labels(
                            verb_class=verb_class
                        ).inc()
                        self._sleep(hint)
                        continue
                    raise KubeThrottled(
                        f"apiserver throttled {method} {kind} "
                        f"(Retry-After {hint:.2f}s)",
                        retry_after=hint,
                    )
                if status >= 500:
                    self.breaker.record_failure()
                    pause = next(backoffs)
                    if (
                        policy.max_attempts > 1
                        and attempts < policy.max_attempts
                        and self._clock() - start + pause <= allowance
                    ):
                        metrics.KUBE_REQUEST_RETRIES.labels(
                            verb_class=verb_class
                        ).inc()
                        self._sleep(pause)
                        continue
                    return status, doc, retry_after, attempts
                # every sub-500 answer — success, 404, 409, 403 — is the
                # apiserver being alive and decisive
                self.breaker.record_success()
                return status, doc, retry_after, attempts

        return run

    def _observe(self, method: str, kind: str, code: str, t0: float) -> None:
        metrics.KUBE_REQUEST_DURATION.labels(
            verb=method, kind=kind or "unknown", code=code
        ).observe(max(self._clock() - t0, 0.0))
