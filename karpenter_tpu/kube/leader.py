"""Cluster-scoped leader election over a coordination.k8s.io/v1 Lease.

Replaces the single-host flock lease for multi-host deployments
(reference: cmd/controller/main.go:84-85, lease id
``karpenter-leader-election``). Same contract as ``utils.lease.FileLease``
so ``LeaderElector`` drives either: ``try_acquire`` (non-blocking),
``renew`` on heartbeat, ``release`` on shutdown. Safety against split
brain comes from apiserver optimistic concurrency — a stale
resourceVersion update returns 409 Conflict, and the loser backs off.
"""

from __future__ import annotations

import logging
import os
import uuid
from typing import Optional

logger = logging.getLogger("karpenter.kube.leader")

from karpenter_tpu.api.objects import Lease, ObjectMeta
from karpenter_tpu.kube.client import Cluster, Conflict, NotFound

DEFAULT_LEASE_NAME = "karpenter-leader-election"
DEFAULT_LEASE_NAMESPACE = "kube-system"


class KubeLease:
    def __init__(
        self,
        cluster: Cluster,
        name: str = DEFAULT_LEASE_NAME,
        namespace: str = DEFAULT_LEASE_NAMESPACE,
        identity: Optional[str] = None,
        duration: float = 15.0,
    ):
        self.cluster = cluster
        self.name = name
        self.namespace = namespace
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        # leaseDurationSeconds is an integer ≥ 1 on the wire
        self.duration = max(1, int(round(duration)))

    def _now(self) -> float:
        return self.cluster.clock()

    def _get(self) -> Optional[Lease]:
        getter = getattr(self.cluster, "get_live", None)
        if getter is not None:
            try:
                return getter("leases", self.name, namespace=self.namespace)
            except NotFound:
                return None
        return self.cluster.try_get("leases", self.name, namespace=self.namespace)

    def _expired(self, lease: Lease) -> bool:
        renew = lease.renew_time or lease.acquire_time or 0.0
        return renew + lease.lease_duration_seconds <= self._now()

    def try_acquire(self) -> bool:
        try:
            return self._try_acquire()
        except Exception:
            # transport blips and unexpected apiserver errors must read as
            # "not acquired", never kill the elector thread (split brain)
            logger.exception("lease acquire failed; retrying on next tick")
            return False

    def _try_acquire(self) -> bool:
        now = self._now()
        current = self._get()
        if current is None:
            lease = Lease(
                metadata=ObjectMeta(name=self.name, namespace=self.namespace),
                holder_identity=self.identity,
                lease_duration_seconds=self.duration,
                acquire_time=now,
                renew_time=now,
                lease_transitions=0,
            )
            try:
                self.cluster.create("leases", lease)
                return True
            except Conflict:
                return False  # racer created it first
        if (
            current.holder_identity == self.identity
            or not current.holder_identity  # released
            or self._expired(current)
        ):
            if current.holder_identity != self.identity:
                current.lease_transitions += 1
                current.acquire_time = now
            current.holder_identity = self.identity
            current.renew_time = now
            try:
                self.cluster.update("leases", current)
                return True
            except (Conflict, NotFound):
                return False  # a racer's update landed first
        return False

    def renew(self) -> bool:
        try:
            current = self._get()
            if current is None or current.holder_identity != self.identity or self._expired(current):
                return False
            current.renew_time = self._now()
            self.cluster.update("leases", current)
            return True
        except Exception:
            # failed renewal reads as lost leadership — the safe direction
            logger.exception("lease renew failed; treating as lost")
            return False

    def release(self) -> None:
        try:
            current = self._get()
            if current is not None and current.holder_identity == self.identity:
                current.holder_identity = ""
                current.renew_time = None
                self.cluster.update("leases", current)
        except Exception:
            logger.exception("lease release failed (expires on its own)")

    def holder(self) -> Optional[str]:
        try:
            current = self._get()
        except Exception:
            return None
        if current is None or not current.holder_identity or self._expired(current):
            return None
        return current.holder_identity
