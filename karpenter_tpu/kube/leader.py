"""Cluster-scoped leader election over a coordination.k8s.io/v1 Lease.

Replaces the single-host flock lease for multi-host deployments
(reference: cmd/controller/main.go:84-85, lease id
``karpenter-leader-election``). Same contract as ``utils.lease.FileLease``
so ``LeaderElector`` drives either: ``try_acquire`` (non-blocking),
``renew`` on heartbeat, ``release`` on shutdown. Safety against split
brain comes from apiserver optimistic concurrency — a stale
resourceVersion update returns 409 Conflict, and the loser backs off.
"""

from __future__ import annotations

import logging
import os
import uuid
from typing import Optional

logger = logging.getLogger("karpenter.kube.leader")

from karpenter_tpu.api.objects import Lease, ObjectMeta
from karpenter_tpu.kube.client import Cluster, Conflict, NotFound
from karpenter_tpu.kube.transport import is_unreachable

DEFAULT_LEASE_NAME = "karpenter-leader-election"
DEFAULT_LEASE_NAMESPACE = "kube-system"

# Fraction of the lease duration BEFORE nominal expiry at which an
# unreachable-apiserver hold gives up and fences: the margin is the window
# in which a peer (with a working apiserver — asymmetric partition) could
# claim the expired lease while this replica still believes it holds it.
FENCE_MARGIN_FRACTION = 0.2


class FenceStatus:
    """Shared REJECTED-vs-UNREACHABLE verdict for a family of leases.

    A failed renewal used to read as "a peer took the lease" no matter the
    cause, so a 10-second apiserver blip synchronously tore down every
    provisioner worker in the fleet. The split (docs/partition.md):

    - **REJECTED** — the apiserver ANSWERED and the answer was "not yours"
      (a peer holds it, it expired server-side, a racer's write won):
      lose the lease NOW, exactly as before.
    - **UNREACHABLE** — the apiserver did not answer: the hold is still
      plausibly ours, so keep serving until the lease's own expiry minus a
      safety margin... then **fence**: assume a peer may own the shard and
      refuse cloud mutations until the control plane answers again.

    One status object is shared by every lease of a ``KubeLeaseSet`` so a
    single successful round trip — even a rejected one — un-fences the
    whole replica (reachability is a property of the apiserver, not of one
    Lease object)."""

    def __init__(self):
        # plain bool: written by the lease-manager thread, read lock-free
        # by launch guards and the GC sweep (attribute reads are atomic)
        self._fenced = False

    def fence(self) -> None:
        if not self._fenced:
            logger.warning(
                "FENCED: apiserver unreachable past lease expiry margin — "
                "refusing cloud mutations until the control plane answers"
            )
        self._fenced = True

    def contact(self) -> None:
        """Any completed apiserver round trip proves reachability."""
        if self._fenced:
            logger.info("apiserver reachable again; fence lifted")
        self._fenced = False

    @property
    def fenced(self) -> bool:
        return self._fenced


class KubeLease:
    def __init__(
        self,
        cluster: Cluster,
        name: str = DEFAULT_LEASE_NAME,
        namespace: str = DEFAULT_LEASE_NAMESPACE,
        identity: Optional[str] = None,
        duration: float = 15.0,
        status: Optional[FenceStatus] = None,
    ):
        self.cluster = cluster
        self.name = name
        self.namespace = namespace
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        # leaseDurationSeconds is an integer ≥ 1 on the wire
        self.duration = max(1, int(round(duration)))
        # REJECTED-vs-UNREACHABLE verdict sink, shared across a lease set
        self.status = status if status is not None else FenceStatus()
        # client-clock expiry of OUR hold (set on successful acquire/renew):
        # the unreachable-apiserver grace window is judged against this,
        # never against anything a peer could have written
        self._held_until: Optional[float] = None
        self._unreachable_since: Optional[float] = None

    def _now(self) -> float:
        return self.cluster.clock()

    def _get(self) -> Optional[Lease]:
        getter = getattr(self.cluster, "get_live", None)
        if getter is not None:
            try:
                out = getter("leases", self.name, namespace=self.namespace)
            except NotFound:
                out = None
            # a completed round trip — even a 404 — proves reachability
            self.status.contact()
            return out
        out = self.cluster.try_get("leases", self.name, namespace=self.namespace)
        self.status.contact()  # the in-memory store always answers
        return out

    def _expired(self, lease: Lease) -> bool:
        renew = lease.renew_time or lease.acquire_time or 0.0
        return renew + lease.lease_duration_seconds <= self._now()

    def _mark_held(self, now: Optional[float] = None) -> None:
        if now is None:
            now = self._now()
        self._held_until = now + self.duration
        self._unreachable_since = None
        self.status.contact()

    def try_acquire(self) -> bool:
        # timestamp BEFORE the round trip: the server-side expiry runs from
        # the renew/acquire time stamped at call entry, so marking the hold
        # with a post-RTT clock would inflate _held_until by the acquire
        # latency and eat into the fence safety margin
        now = self._now()
        try:
            ok = self._try_acquire()
        except Exception as e:
            # transport blips and unexpected apiserver errors must read as
            # "not acquired", never kill the elector thread (split brain)
            if is_unreachable(e):
                logger.debug(
                    "lease acquire unreachable; retrying on next tick",
                    exc_info=True,
                )
            else:
                logger.exception("lease acquire failed; retrying on next tick")
            return False
        if ok:
            self._mark_held(now)
        return ok

    def _try_acquire(self) -> bool:
        now = self._now()
        current = self._get()
        if current is None:
            lease = Lease(
                metadata=ObjectMeta(name=self.name, namespace=self.namespace),
                holder_identity=self.identity,
                lease_duration_seconds=self.duration,
                acquire_time=now,
                renew_time=now,
                lease_transitions=0,
            )
            try:
                self.cluster.create("leases", lease)
                return True
            except Conflict:
                return False  # racer created it first
        if (
            current.holder_identity == self.identity
            or not current.holder_identity  # released
            or self._expired(current)
        ):
            if current.holder_identity != self.identity:
                current.lease_transitions += 1
                current.acquire_time = now
            current.holder_identity = self.identity
            current.renew_time = now
            try:
                self.cluster.update("leases", current)
                return True
            except (Conflict, NotFound):
                return False  # a racer's update landed first
        return False

    def renew(self) -> bool:
        """Renew the hold. REJECTED (the apiserver answered "not yours" —
        a peer holds it, it expired server-side, a racer's write won) is a
        lost lease NOW, exactly as before fencing existed. UNREACHABLE (no
        answer at all) keeps the hold until OUR OWN copy of the expiry
        minus a safety margin, then fences — a 10s apiserver blip must not
        read as fleet-wide lease loss (docs/partition.md)."""
        now = self._now()
        try:
            current = self._get()
            if current is None or current.holder_identity != self.identity or self._expired(current):
                self._held_until = None
                return False  # REJECTED: positively not ours any more
            current.renew_time = now
            self.cluster.update("leases", current)
        except Exception as e:
            if is_unreachable(e):
                return self._renew_unreachable(now)
            # Conflict (a racer's write landed first), RBAC, programming
            # errors: the apiserver ANSWERED — lost is the safe direction
            logger.exception("lease renew rejected; treating as lost")
            self._held_until = None
            return False
        self._mark_held(now)
        return True

    def _renew_unreachable(self, now: float) -> bool:
        margin = FENCE_MARGIN_FRACTION * self.duration
        if self._held_until is not None and now < self._held_until - margin:
            # still inside our own hold: no peer can legitimately own this
            # lease yet, so keep serving — zero churn across a short blip
            if self._unreachable_since is None:
                self._unreachable_since = now
                logger.warning(
                    "apiserver unreachable; lease %s held optimistically "
                    "(%.1fs until fence)",
                    self.name, self._held_until - margin - now,
                )
            return True
        # past expiry-minus-margin with the apiserver still silent: a peer
        # whose apiserver works (asymmetric partition) may claim the
        # expired lease any moment — fence, and report the hold lost
        self.status.fence()
        self._held_until = None
        self._unreachable_since = None
        return False

    def release(self) -> None:
        self._held_until = None
        self._unreachable_since = None
        try:
            current = self._get()
            if current is not None and current.holder_identity == self.identity:
                current.holder_identity = ""
                current.renew_time = None
                self.cluster.update("leases", current)
        except Exception:
            logger.exception("lease release failed (expires on its own)")

    def holder(self) -> Optional[str]:
        try:
            current = self._get()
        except Exception:
            return None
        if current is None or not current.holder_identity or self._expired(current):
            return None
        return current.holder_identity


class KubeLeaseSet:
    """Keyed lease set over coordination.k8s.io/v1 Leases — the cluster-
    scoped counterpart of ``utils.lease.FileLeaseSet`` (same contract, so
    ``fleet.ShardManager`` drives either). Each shard key maps to one Lease
    object (``<prefix>-shard-<slug>``); replica membership is its own Lease
    per replica (``<prefix>-member-<identity>``) that the holder heartbeats.
    Split-brain safety is the apiserver's optimistic concurrency, exactly as
    in :class:`KubeLease`."""

    def __init__(
        self,
        cluster: Cluster,
        prefix: str = "karpenter-shard",
        namespace: str = DEFAULT_LEASE_NAMESPACE,
        identity: Optional[str] = None,
        duration: float = 15.0,
    ):
        self.cluster = cluster
        self.prefix = prefix
        self.namespace = namespace
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.duration = duration
        # ONE fence status across every lease of the set: reachability is a
        # property of the apiserver, so a single successful round trip on
        # ANY lease (or the member LIST) un-fences the whole replica
        self.status = FenceStatus()
        self._leases: dict = {}  # key -> KubeLease (lazily built; single-thread ShardManager use)
        self._member_lease: Optional[KubeLease] = None
        # one LIVE namespace LIST serves a whole tick (heartbeat's member
        # scan AND snapshot's holder resolution): (listing, fetched_at)
        self._listing: tuple = ((), float("-inf"))

    def fenced(self) -> bool:
        """Is this replica FENCED (apiserver unreachable past a held
        lease's expiry margin)? ``fleet.ShardManager.fenced`` reads this;
        launch guards and the GC sweep refuse cloud mutations while True."""
        return self.status.fenced

    def _list_leases(self, max_age: Optional[float] = None) -> list:
        """List the namespace's leases UNCACHED — against a real apiserver
        the informer plane deliberately does not watch leases, so the
        cached ``list`` would only show this process's own writes; the
        in-memory Cluster has no ``list_live`` and its ``list`` is
        authoritative. ``max_age`` lets the second caller in one tick
        reuse the first's listing instead of re-LISTing."""
        now = self.cluster.clock()
        listing, fetched_at = self._listing
        if max_age is not None and now - fetched_at <= max_age:
            return list(listing)
        lister = getattr(self.cluster, "list_live", None)
        if lister is not None:
            leases = lister("leases", namespace=self.namespace)
        else:
            leases = self.cluster.list("leases", namespace=self.namespace)
        self.status.contact()  # a completed LIST proves reachability
        self._listing = (tuple(leases), now)
        return list(leases)

    def _name_for(self, key: str) -> str:
        # DNS-1123-safe and collision-free: slugified key + a short content
        # hash (two keys differing only in stripped characters stay distinct)
        import hashlib
        import re

        slug = re.sub(r"[^a-z0-9-]+", "-", key.lower()).strip("-")[:40] or "x"
        digest = hashlib.blake2b(key.encode(), digest_size=4).hexdigest()
        return f"{self.prefix}-shard-{slug}-{digest}"

    def _lease_for(self, key: str) -> KubeLease:
        lease = self._leases.get(key)
        if lease is None:
            lease = self._leases[key] = KubeLease(
                self.cluster,
                name=self._name_for(key),
                namespace=self.namespace,
                identity=self.identity,
                duration=self.duration,
                status=self.status,
            )
        return lease

    # -- membership ---------------------------------------------------------
    def heartbeat(self) -> set:
        if self._member_lease is None:
            self._member_lease = KubeLease(
                self.cluster,
                name=f"{self.prefix}-member-{self.identity}",
                namespace=self.namespace,
                identity=self.identity,
                duration=self.duration,
                status=self.status,
            )
        if not self._member_lease.renew():
            self._member_lease.try_acquire()
        return self.members()

    @staticmethod
    def _expiry(lease) -> float:
        renew = lease.renew_time or lease.acquire_time or 0.0
        return renew + lease.lease_duration_seconds

    def members(self) -> set:
        try:
            leases = self._list_leases()
        except Exception as e:
            if is_unreachable(e):
                logger.debug("member lease list unreachable", exc_info=True)
            else:
                logger.exception("listing member leases failed")
            return {self.identity}
        prefix = f"{self.prefix}-member-"
        now = self.cluster.clock()
        out = set()
        for lease in leases:
            if not lease.metadata.name.startswith(prefix):
                continue
            if lease.holder_identity and self._expiry(lease) > now:
                out.add(lease.holder_identity)
            elif self._expiry(lease) + 4 * self.duration <= now:
                # GC long-dead member Leases: identities are per-process
                # (pid+uuid in the NAME), so crashed replicas would leak
                # one object per restart forever — any live replica's
                # tick may collect them once they are unambiguously stale
                try:
                    self.cluster.delete(
                        "leases", lease.metadata.name, namespace=self.namespace
                    )
                except Exception:
                    logger.debug(
                        "stale member lease GC failed", exc_info=True
                    )
        out.add(self.identity)
        return out

    def resign(self) -> None:
        """Delete (not just blank) this replica's member Lease: the
        identity is baked into the object NAME, so a released-but-kept
        object is permanent garbage no future process reuses."""
        if self._member_lease is None:
            return
        try:
            self.cluster.delete(
                "leases", self._member_lease.name, namespace=self.namespace
            )
        except Exception:
            logger.exception("member lease delete failed (GC'd by a peer later)")

    # -- per-key leases -----------------------------------------------------
    def try_acquire(self, key: str) -> bool:
        return self._lease_for(key).try_acquire()

    def renew_many(self, keys) -> set:
        renewed = set()
        for key in keys:
            if self._lease_for(key).renew():
                renewed.add(key)
        return renewed

    def release(self, key: str) -> None:
        self._lease_for(key).release()

    def release_all(self) -> None:
        for key in list(self._leases):
            self._leases[key].release()

    def holder(self, key: str) -> Optional[str]:
        return self._lease_for(key).holder()

    def snapshot(self, keys=None) -> dict:
        """Live key → holder map from ONE namespace LIST: each desired
        key's slugged Lease name is matched against the listing, so a
        fresh replica resolves holders for keys it never touched without
        a GET per key per tick (at 200 provisioners × 3 replicas that
        would be 120 GETs/s against the apiserver)."""
        wanted = set(keys or ()) | set(self._leases)
        if not wanted:
            return {}
        try:
            # reuse heartbeat's listing when it ran within this tick — the
            # shard manager calls heartbeat then snapshot back to back, and
            # two full LISTs per tick per replica would double the
            # apiserver load for the same bytes
            leases = self._list_leases(max_age=min(1.0, self.duration / 3.0))
        except Exception as e:
            if is_unreachable(e):
                logger.debug("shard lease list unreachable", exc_info=True)
            else:
                logger.exception("listing shard leases failed")
            return {}
        by_name = {lease.metadata.name: lease for lease in leases}
        now = self.cluster.clock()
        out = {}
        for key in wanted:
            lease = by_name.get(self._name_for(key))
            if (
                lease is not None
                and lease.holder_identity
                and self._expiry(lease) > now
            ):
                out[key] = lease.holder_identity
        return out
