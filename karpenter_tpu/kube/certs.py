"""Self-managed webhook serving certificates.

The reference webhook self-manages its TLS cert via knative's certificates
controller (``cmd/webhook/main.go:46``): generate a CA + leaf for the
webhook Service's DNS names, serve HTTPS with the leaf, and publish the CA
bundle for the ``ValidatingWebhookConfiguration.clientConfig.caBundle``.
``ensure_serving_cert`` reproduces that: idempotent per cert-dir, rotating
automatically when the cert is near expiry or the DNS names changed.
"""

from __future__ import annotations

import datetime
import os
from typing import List, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.x509.oid import NameOID

CERT_VALIDITY_DAYS = 365
ROTATE_BEFORE_DAYS = 30


def _new_key() -> rsa.RSAPrivateKey:
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _write(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)
    os.chmod(path, 0o600)


def generate(cert_dir: str, dns_names: List[str]) -> Tuple[str, str, str]:
    """Generate (or re-sign under an existing CA) a serving cert for
    ``dns_names`` into ``cert_dir``. Returns (cert_path, key_path, ca_path).

    The CA (cert + key) persists in the cert dir and is REUSED on leaf
    rotation: the registered ``caBundle`` in the webhook configurations
    must stay valid across renewals — minting a fresh CA every rotation
    would break apiserver→webhook TLS until the bundle is re-injected."""
    os.makedirs(cert_dir, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)
    not_after = now + datetime.timedelta(days=CERT_VALIDITY_DAYS)

    ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "karpenter-tpu-webhook-ca")])
    ca_key_path = os.path.join(cert_dir, "ca.key")
    ca_path = os.path.join(cert_dir, "ca.crt")
    ca_key = ca_cert = None
    if os.path.exists(ca_key_path) and os.path.exists(ca_path):
        try:
            with open(ca_key_path, "rb") as f:
                ca_key = serialization.load_pem_private_key(f.read(), password=None)
            with open(ca_path, "rb") as f:
                ca_cert = x509.load_pem_x509_certificate(f.read())
            if ca_cert.not_valid_after_utc - now < datetime.timedelta(days=ROTATE_BEFORE_DAYS):
                ca_key = ca_cert = None  # CA itself near expiry: reissue
        except (ValueError, TypeError):
            ca_key = ca_cert = None
    if ca_key is None:
        ca_key = _new_key()
        ca_cert = (
            x509.CertificateBuilder()
            .subject_name(ca_name)
            .issuer_name(ca_name)
            .public_key(ca_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(not_after)
            .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
            .sign(ca_key, hashes.SHA256())
        )

    leaf_key = _new_key()
    leaf = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, dns_names[0])]))
        .issuer_name(ca_name)
        .public_key(leaf_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(not_after)
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName(n) for n in dns_names]),
            critical=False,
        )
        .add_extension(
            x509.ExtendedKeyUsage([x509.ExtendedKeyUsageOID.SERVER_AUTH]), critical=False
        )
        .sign(ca_key, hashes.SHA256())
    )

    cert_path = os.path.join(cert_dir, "tls.crt")
    key_path = os.path.join(cert_dir, "tls.key")
    _write(cert_path, leaf.public_bytes(serialization.Encoding.PEM))
    _write(
        key_path,
        leaf_key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        ),
    )
    _write(ca_path, ca_cert.public_bytes(serialization.Encoding.PEM))
    _write(
        ca_key_path,
        ca_key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        ),
    )
    return cert_path, key_path, ca_path


def _expiring(pem_path: str) -> bool:
    try:
        with open(pem_path, "rb") as f:
            cert = x509.load_pem_x509_certificate(f.read())
    except (OSError, ValueError):
        return True
    now = datetime.datetime.now(datetime.timezone.utc)
    return cert.not_valid_after_utc - now < datetime.timedelta(days=ROTATE_BEFORE_DAYS)


def _needs_rotation(cert_path: str, ca_path: str, dns_names: List[str]) -> bool:
    # the CA's own expiry matters as much as the leaf's: a re-signed leaf
    # can outlive a reused CA, and an expired CA in the registered caBundle
    # fails every apiserver handshake with nothing else prompting rotation
    if _expiring(cert_path) or _expiring(ca_path):
        return True
    try:
        with open(cert_path, "rb") as f:
            cert = x509.load_pem_x509_certificate(f.read())
        sans = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName
        ).value.get_values_for_type(x509.DNSName)
    except (OSError, ValueError, x509.ExtensionNotFound):
        return True
    return set(sans) != set(dns_names)


def ensure_serving_cert(cert_dir: str, dns_names: List[str]) -> Tuple[str, str, str]:
    """Idempotent: reuse a valid existing cert, else (re)generate.
    Returns (cert_path, key_path, ca_path).

    On a read-only cert dir (Secret volume) that needs rotation, the
    existing cert is served with a loud warning — a soon-to-expire cert
    beats a crash loop that (failurePolicy: Fail) blocks every
    Provisioner write; rotation there is `make webhook-certs` + Secret
    update, outside the pod."""
    cert_path = os.path.join(cert_dir, "tls.crt")
    key_path = os.path.join(cert_dir, "tls.key")
    ca_path = os.path.join(cert_dir, "ca.crt")
    have_all = all(os.path.exists(p) for p in (cert_path, key_path, ca_path))
    if have_all and not _needs_rotation(cert_path, ca_path, dns_names):
        return cert_path, key_path, ca_path
    try:
        return generate(cert_dir, dns_names)
    except OSError:
        if have_all:
            import logging

            logging.getLogger("karpenter.webhook").warning(
                "cert dir %s is not writable and the cert needs rotation; "
                "serving the existing cert — regenerate the Secret with "
                "`make webhook-certs`", cert_dir,
            )
            return cert_path, key_path, ca_path
        raise


def ca_bundle_b64(ca_path: str) -> str:
    """Base64 CA bundle for webhook clientConfig.caBundle."""
    import base64

    with open(ca_path, "rb") as f:
        return base64.b64encode(f.read()).decode()
