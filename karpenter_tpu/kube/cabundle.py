"""Webhook-registration caBundle self-reconciliation.

The reference's webhook process doesn't just serve its cert — it keeps the
admission registration's ``clientConfig.caBundle`` current at runtime
(knative ``certificates.NewController``, reference: cmd/webhook/main.go:46-63).
Without this, a CA rotation on a live cluster (``kube/certs.py`` reissues a
near-expiry CA) leaves the registration pointing at the OLD CA: the
apiserver rejects every webhook call, and with ``failurePolicy: Fail`` that
blocks every Provisioner write until an operator re-runs
``make webhook-cabundle``.

``CABundleReconciler`` closes the loop: read the live registration, compare
every webhook entry's caBundle to the CA on disk, and write ONE update with
the bundles rewritten when they differ. Reads are uncached (``get_live``
against an apiserver backend) — a reconciler that trusts a stale informer
view of its own write target can flap.
"""

from __future__ import annotations

import base64
import logging
import threading
from typing import Callable, List, Optional

from karpenter_tpu.kube.client import Cluster, NotFound

logger = logging.getLogger("karpenter.webhook.cabundle")

RESYNC_SECONDS = 300.0  # certs rotate on the order of days; minutes is ample

WEBHOOK_CONFIG_KINDS = (
    "validatingwebhookconfigurations",
    "mutatingwebhookconfigurations",
)


class CABundleReconciler:
    def __init__(
        self,
        cluster: Cluster,
        configs: List,  # (kind, name) pairs; kind in WEBHOOK_CONFIG_KINDS
        ca_path: str,
        resync_seconds: float = RESYNC_SECONDS,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.cluster = cluster
        self.configs = [tuple(c) for c in configs]
        self.ca_path = ca_path
        self.resync_seconds = resync_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _current_bundle(self) -> str:
        with open(self.ca_path, "rb") as f:
            return base64.b64encode(f.read()).decode()

    def _get_live(self, kind: str, name: str):
        getter = getattr(self.cluster, "get_live", None)
        if getter is not None:
            return getter(kind, name, namespace="")
        return self.cluster.get(kind, name, namespace="")

    def reconcile_once(self) -> int:
        """Returns how many registrations were updated."""
        try:
            bundle = self._current_bundle()
        except OSError as e:
            logger.warning("cannot read CA at %s: %s", self.ca_path, e)
            return 0
        updated = 0
        for kind, name in self.configs:
            try:
                cfg = self._get_live(kind, name)
            except NotFound:
                logger.warning("webhook configuration %s not found", name)
                continue
            except Exception as e:
                logger.error("reading webhook configuration %s: %s", name, e)
                continue
            stale = [
                w.get("name", "?")
                for w in cfg.webhooks
                if (w.get("clientConfig") or {}).get("caBundle") != bundle
            ]
            if not stale:
                continue
            # JSON merge-patch replaces lists wholesale, so ship the FULL
            # webhooks array with only the bundles rewritten — every other
            # field (rules, sideEffects, ...) round-trips untouched
            webhooks = []
            for w in cfg.webhooks:
                w = dict(w)
                cc = dict(w.get("clientConfig") or {})
                cc["caBundle"] = bundle
                w["clientConfig"] = cc
                webhooks.append(w)
            try:
                self.cluster.merge_patch(kind, name, {"webhooks": webhooks}, namespace="")
                updated += 1
                logger.info(
                    "updated caBundle of %s (stale webhooks: %s)", name, ", ".join(stale)
                )
            except Exception as e:
                logger.error("patching webhook configuration %s: %s", name, e)
        return updated

    def start(self) -> "CABundleReconciler":
        def loop():
            while not self._stop.is_set():
                self.reconcile_once()
                self._stop.wait(self.resync_seconds)

        self._thread = threading.Thread(target=loop, daemon=True, name="cabundle")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
