"""Real-apiserver ``Cluster`` backend.

``ApiCluster`` speaks plain Kubernetes REST — list/watch with resync,
create/update/merge-patch/delete, the Bind and Eviction subresources — and
maintains informer-style local caches fed by watch streams, so reads served
to reconcilers are cache reads exactly like controller-runtime's
(reference: pkg/controllers/manager.go:34-46). Every request passes the
client-side QPS/burst token bucket (reference: cmd/controller/main.go:68-70,
options.go:42-43).

Transport is stdlib ``http.client`` (no kubernetes client dependency):
chunked watch streams are newline-delimited JSON events, exactly the
apiserver protocol. TLS + bearer-token auth cover in-cluster use;
``from_env()`` builds the in-cluster config from the standard service
account mount.

Writes go to the server; the local cache is updated from the server's
response immediately (not waiting for the watch echo) so a reconciler that
writes then reads sees its own write, matching the reference's
optimistic-concurrency flow.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import ssl
import threading
import time
from http.client import HTTPConnection, HTTPSConnection
from typing import Dict, Optional, Tuple
from urllib.parse import urlparse

from karpenter_tpu.api.objects import Pod
from karpenter_tpu.kube import serde
from karpenter_tpu.kube.client import Cluster, Conflict, NotFound
from karpenter_tpu.kube.transport import (
    VERB_CREATE,
    VERB_EVENTS,
    VERB_LEASE,
    VERB_MUTATE,
    VERB_READ,
    VERB_WATCH,
    ApiUnavailable,
    KubeThrottled,
    KubeTransport,
)

logger = logging.getLogger("karpenter.kube.apiserver")

# kind -> (api prefix, resource plural)
RESOURCES: Dict[str, Tuple[str, str]] = {
    "pods": ("/api/v1", "pods"),
    "nodes": ("/api/v1", "nodes"),
    "daemonsets": ("/apis/apps/v1", "daemonsets"),
    "provisioners": ("/apis/karpenter.sh/v1alpha5", "provisioners"),
    "pvcs": ("/api/v1", "persistentvolumeclaims"),
    "pvs": ("/api/v1", "persistentvolumes"),
    "storageclasses": ("/apis/storage.k8s.io/v1", "storageclasses"),
    "pdbs": ("/apis/policy/v1", "poddisruptionbudgets"),
    "leases": ("/apis/coordination.k8s.io/v1", "leases"),
    "validatingwebhookconfigurations": (
        "/apis/admissionregistration.k8s.io/v1", "validatingwebhookconfigurations",
    ),
    "mutatingwebhookconfigurations": (
        "/apis/admissionregistration.k8s.io/v1", "mutatingwebhookconfigurations",
    ),
    "events": ("/api/v1", "events"),
}

WATCH_RECONNECT_DELAY = 1.0
# the watch loop's failure backoff doubles per consecutive failure (with
# jitter) up to this cap, and resets on any successful list — a down
# apiserver costs each kind one paced probe, not a re-list hot loop
WATCH_BACKOFF_CAP = 30.0
# idle watch reads give up and reconnect after this long, so a stop() or a
# silently-dead connection never wedges a watch thread indefinitely
WATCH_READ_TIMEOUT = 60.0
# server-side watch timeout: below WATCH_READ_TIMEOUT so an idle stream ends
# with a clean EOF (resumable from the last RV) rather than a socket timeout
WATCH_TIMEOUT_SECONDS = 45

# Kinds the informer plane watches by default: everything EXCEPT leases and
# webhook registrations. Leader election reads its Lease with uncached
# get_live (kube/leader.py) and the caBundle reconciler reads its
# registration the same way, so informers there are dead weight — leases
# would churn on every node-heartbeat cluster-wide, and BOTH require
# list/watch RBAC the shipped manifests deliberately do not grant (watching
# without it 403s forever and fails wait_for_sync).
WATCH_KINDS = tuple(
    k for k in Cluster.KINDS
    if k not in (
        # write-mostly kinds the controllers never read back: informers on
        # them are churn + RBAC surface for nothing
        "leases", "validatingwebhookconfigurations", "mutatingwebhookconfigurations",
        "events",
    )
)


class ApiError(Exception):
    def __init__(self, status: int, body: str = ""):
        super().__init__(f"apiserver returned {status}: {body[:200]}")
        self.status = status
        self.body = body


def _raise_for(status: int, body: str):
    if status == 404:
        raise NotFound(body or "not found")
    if status == 409:
        raise Conflict(body or "conflict")
    raise ApiError(status, body)


class ApiCluster(Cluster):
    """Cluster interface against a real apiserver; see module docstring.

    The inherited in-memory stores act as the informer cache: reads
    (``get``/``list``/``pods_on_node``/…) and watch registration are served
    by the base class against cache contents; mutations override the base
    to issue REST calls and then apply the server's view to the cache.
    """

    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure_skip_verify: bool = False,
        qps: float = 200.0,
        burst: int = 300,
        kinds: Optional[Tuple[str, ...]] = None,
        clock=None,
    ):
        super().__init__(clock=clock)
        u = urlparse(base_url)
        self._scheme = u.scheme or "http"
        self._host = u.hostname or "127.0.0.1"
        self._port = u.port or (443 if self._scheme == "https" else 80)
        self._token = token
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if self._scheme == "https":
            self._ssl_ctx = ssl.create_default_context(cafile=ca_file)
            if insecure_skip_verify:
                self._ssl_ctx.check_hostname = False
                self._ssl_ctx.verify_mode = ssl.CERT_NONE
        # the resilient transport choke point (kube/transport.py): per-verb
        # retries, 429/Retry-After handling, mutation-priority flow control
        # (the old bare TokenBucket generalized), circuit breaker, metrics
        self.transport = KubeTransport(qps=qps, burst=burst)
        # Event writes must never hold a reconcile hostage: short connect/
        # read timeout on the events verb class (tests shrink it)
        self.events_timeout = 2.0
        # lease ops get their own short timeout: a renew slower than the
        # renew cadence is useless, and a 30s connect hang into a real
        # packet-dropping partition would blow past the fencing margin
        self.lease_timeout = 5.0
        # watch-loop failure backoff knobs (tests shrink them to count
        # re-list attempts in CI time)
        self.watch_backoff_base = WATCH_RECONNECT_DELAY
        self.watch_backoff_cap = WATCH_BACKOFF_CAP
        # kind -> full re-LIST attempts (regression surface for the
        # blackout hot-loop fix; the prometheus twin is KUBE_RELISTS)
        self.relist_attempts: Dict[str, int] = {}
        self._watch_kinds = tuple(kinds) if kinds is not None else WATCH_KINDS
        self._stop = threading.Event()
        self._threads: list = []
        self._watch_conns: Dict[str, object] = {}
        self._synced: Dict[str, threading.Event] = {
            k: threading.Event() for k in self._watch_kinds
        }

    @classmethod
    def from_env(cls, qps: float = 200.0, burst: int = 300) -> "ApiCluster":
        """In-cluster config from the standard service-account mount."""
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        sa = "/var/run/secrets/kubernetes.io/serviceaccount"
        token = None
        token_path = os.path.join(sa, "token")
        if os.path.exists(token_path):
            with open(token_path) as f:
                token = f.read().strip()
        ca = os.path.join(sa, "ca.crt")
        return cls(
            f"https://{host}:{port}",
            token=token,
            ca_file=ca if os.path.exists(ca) else None,
            qps=qps,
            burst=burst,
        )

    # -- transport ---------------------------------------------------------
    def _connect(self, timeout: Optional[float] = 30.0):
        if self._scheme == "https":
            return HTTPSConnection(self._host, self._port, timeout=timeout, context=self._ssl_ctx)
        return HTTPConnection(self._host, self._port, timeout=timeout)

    def _headers(self, content_type: Optional[str] = None) -> Dict[str, str]:
        h = {"Accept": "application/json"}
        if content_type:
            h["Content-Type"] = content_type
        if self._token:
            h["Authorization"] = f"Bearer {self._token}"
        return h

    _VERB_CLASS = {"GET": VERB_READ, "POST": VERB_CREATE}

    def _request(
        self, method: str, path: str, body: Optional[dict] = None,
        content_type: str = "application/json",
        kind: str = "", verb_class: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> Tuple[int, dict]:
        """One logical apiserver call through the transport choke point.
        ``verb_class`` defaults from the method (GET→read, POST→create,
        PUT/PATCH/DELETE→mutate); Event writes pass ``events`` explicitly
        (zero retries, short deadline, drop-counted)."""
        if verb_class is None:
            verb_class = self._VERB_CLASS.get(method, VERB_MUTATE)
        if kind == "leases" and verb_class != VERB_WATCH:
            # lease traffic IS the fencing signal (kube/leader.py): single
            # attempt, short deadline, never fast-failed by a breaker some
            # OTHER traffic opened (kube/transport.py VERB_LEASE)
            verb_class = VERB_LEASE
            if timeout is None:
                timeout = self.lease_timeout
        status, doc, _hint = self.transport.request(
            verb_class, method, kind,
            lambda: self._attempt(method, path, body, content_type, timeout),
        )
        return status, doc

    def _attempt(
        self, method: str, path: str, body: Optional[dict],
        content_type: str, timeout: Optional[float],
    ) -> Tuple[int, dict, Optional[float]]:
        """One HTTP round trip: (status, body, Retry-After seconds)."""
        conn = self._connect(timeout=timeout if timeout is not None else 30.0)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            conn.request(method, path, body=payload, headers=self._headers(content_type))
            resp = conn.getresponse()
            raw = resp.read()
            doc = json.loads(raw) if raw else {}
            retry_after: Optional[float] = None
            header = resp.getheader("Retry-After")
            if header:
                try:
                    retry_after = float(header)
                except ValueError:
                    retry_after = None
            return resp.status, doc, retry_after
        finally:
            conn.close()

    def _path(self, kind: str, namespace: Optional[str], name: Optional[str] = None,
              subresource: Optional[str] = None, query: str = "") -> str:
        prefix, plural = RESOURCES[kind]
        _, _, namespaced = serde.KIND_INFO[kind]
        parts = [prefix]
        if namespaced and namespace is not None and namespace != "":
            parts += ["namespaces", namespace]
        parts.append(plural)
        if name is not None:
            parts.append(name)
        if subresource:
            parts.append(subresource)
        return "/".join(parts) + (f"?{query}" if query else "")

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start the list+watch loop for every kind."""
        for kind in self._watch_kinds:
            t = threading.Thread(
                target=self._watch_loop, args=(kind,), daemon=True,
                name=f"watch-{kind}",
            )
            self._threads.append(t)
            t.start()

    def stop(self) -> None:
        self._stop.set()
        # unblock threads sitting in watch reads
        for conn in list(self._watch_conns.values()):
            try:
                conn.close()
            except Exception:
                pass
        for t in self._threads:
            t.join(timeout=2)

    def wait_for_sync(self, timeout: float = 30.0) -> bool:
        """Block until every kind's cache saw its initial list."""
        deadline = time.monotonic() + timeout
        for kind in self._watch_kinds:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not self._synced[kind].wait(timeout=remaining):
                return False
        return True

    # -- informer loop -----------------------------------------------------
    def _watch_loop(self, kind: str) -> None:
        """List once, then watch forever — resuming each reconnect from the
        last-seen event resourceVersion. Re-listing happens only when the
        server says the RV is too old (410 Gone / ERROR event) or on a
        transport error, never on routine idle stream ends: client-go resyncs
        on the order of hours, and a full re-LIST dispatches MODIFIED for
        every cached object, requeueing every controller key.

        Consecutive failures back off with jittered exponential delays (base
        doubled per failure up to ``watch_backoff_cap``, reset by any
        successful list) — a down apiserver costs one paced probe per kind,
        not a re-list hot loop multiplied by every replica in the fleet."""
        import random

        rv: Optional[str] = None
        failures = 0
        while not self._stop.is_set():
            try:
                if rv is None:
                    rv = self._relist(kind)
                    self._synced[kind].set()
                    failures = 0  # success resets the backoff ladder
                rv = self._stream(kind, rv)
            except Exception as e:
                if self._stop.is_set():
                    return
                failures += 1
                delay = min(
                    self.watch_backoff_cap,
                    self.watch_backoff_base * (2 ** min(failures - 1, 16)),
                )
                delay *= 0.5 + random.random()  # jitter: 0.5x..1.5x
                logger.debug(
                    "watch %s disconnected (%s); re-listing in %.2fs "
                    "(failure %d)", kind, e, delay, failures,
                )
                rv = None  # unknown delta state: resync with a full list
                self._stop.wait(delay)

    def _relist(self, kind: str) -> str:
        """Full list; reconcile the cache to it (resync), dispatching
        ADDED/MODIFIED/DELETED deltas to registered watchers.

        The list snapshot can be OLDER than local writes already applied to
        the cache (a create raced the reconnect), so the list's
        resourceVersion gates both overwrites and evictions — mirroring
        ``_apply_event``'s per-object guard."""
        from karpenter_tpu import metrics

        self.relist_attempts[kind] = self.relist_attempts.get(kind, 0) + 1
        metrics.KUBE_RELISTS.labels(kind=kind).inc()
        # the `watch` verb class: flow-limited, breaker-recorded, but NOT
        # transport-retried — this loop owns the pacing, and stacking two
        # retry layers would multiply load on a struggling apiserver
        status, doc = self._request(
            "GET", self._path(kind, None), kind=kind, verb_class=VERB_WATCH
        )
        if status != 200:
            raise ApiError(status, str(doc))
        rv = str((doc.get("metadata") or {}).get("resourceVersion") or "0")
        try:
            list_rv = int(rv)
        except ValueError:
            list_rv = 0
        fresh = {}
        for item in doc.get("items") or []:
            obj = serde.from_wire(kind, item)
            fresh[(obj.metadata.namespace, obj.metadata.name)] = obj
        notify_fresh = []
        deleted = []
        with self._lock:
            store = self._stores[kind]
            for key, obj in fresh.items():
                current = store.objects.get(key)
                # rv 0 = unparseable/opaque RV: ordering is unknowable, so
                # last-write-wins (never silently freeze the cache)
                if (
                    current is not None
                    and obj.metadata.resource_version > 0
                    and current.metadata.resource_version > obj.metadata.resource_version
                ):
                    continue  # cache holds a newer (locally-written) view
                store.objects[key] = obj
                notify_fresh.append(obj)
            for key in set(store.objects) - set(fresh):
                current = store.objects[key]
                if current.metadata.resource_version > list_rv:
                    continue  # created after the list snapshot — not deleted
                del store.objects[key]
                deleted.append(current)
        for obj in notify_fresh:
            self._notify(kind, "MODIFIED", obj)
        for obj in deleted:
            self._notify(kind, "DELETED", obj)
        return rv

    def _stream(self, kind: str, rv: str) -> Optional[str]:
        """Consume one watch stream until disconnect. Returns the
        resourceVersion to resume the next watch from (each event — and
        BOOKMARK events, which exist for exactly this — advances it), or
        ``None`` when the server declared the RV too old (410 Gone / ERROR
        event) and the caller must re-list. A finite read timeout (idle
        watches reconnect) plus connection tracking keeps ``stop()`` from
        leaving threads blocked in reads forever."""
        conn = self._connect(timeout=WATCH_READ_TIMEOUT)
        self._watch_conns[kind] = conn
        try:
            path = self._path(
                kind, None,
                query=(
                    f"watch=true&resourceVersion={rv}&allowWatchBookmarks=true"
                    f"&timeoutSeconds={WATCH_TIMEOUT_SECONDS}"
                ),
            )
            conn.request("GET", path, headers=self._headers())
            resp = conn.getresponse()
            if resp.status == 410:
                return None  # too-old resourceVersion: caller re-lists
            if resp.status != 200:
                raise ApiError(resp.status, resp.read().decode(errors="replace"))
            buf = b""
            while not self._stop.is_set():
                chunk = resp.read1(65536)
                if not chunk:
                    return rv  # clean EOF (server timeout): resume from rv
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    event = json.loads(line)
                    etype = event.get("type")
                    obj_rv = ((event.get("object") or {}).get("metadata") or {}).get(
                        "resourceVersion"
                    )
                    if obj_rv:
                        rv = str(obj_rv)
                    if etype == "BOOKMARK":
                        continue
                    if etype == "ERROR":
                        return None  # 410 Gone mid-stream: re-list
                    obj = serde.from_wire(kind, event.get("object") or {})
                    self._apply_event(kind, etype, obj)
            return rv
        except socket.timeout:
            return rv  # idle past the read timeout: resume from rv
        finally:
            self._watch_conns.pop(kind, None)
            conn.close()

    def _apply_event(self, kind: str, etype: str, obj) -> None:
        if self._stop.is_set():
            return  # a stopped cluster must not feed stopped watchers
        key = (obj.metadata.namespace, obj.metadata.name)
        with self._lock:
            store = self._stores[kind]
            if etype == "DELETED":
                store.objects.pop(key, None)
            else:
                current = store.objects.get(key)
                # rv 0 = opaque/unparseable RV: accept (last-write-wins) —
                # dropping on 0 >= 0 would freeze the cache permanently
                if (
                    current is not None
                    and obj.metadata.resource_version > 0
                    and current.metadata.resource_version >= obj.metadata.resource_version
                ):
                    return  # our own write already applied a newer view
                store.objects[key] = obj
        self._notify(kind, etype, obj)

    def _cache_put(self, kind: str, obj) -> None:
        with self._lock:
            self._stores[kind].objects[(obj.metadata.namespace, obj.metadata.name)] = obj

    def degraded(self) -> bool:
        """Is the transport refusing apiserver calls (breaker open)?
        Controllers treat True as "serve the informer cache"; the lease
        layer treats it as UNREACHABLE and fences on its own clock."""
        return self.transport.degraded()

    def get_live(self, kind: str, name: str, namespace: str = "default"):
        """Uncached GET straight from the server — leader election must
        never trust a stale informer view. While the apiserver breaker is
        OPEN, watched kinds degrade to the informer cache (counted on
        ``karpenter_kube_degraded_reads_total``); un-watched kinds (leases)
        have no cache to fall back on, and the failure propagates so the
        lease layer can fence instead of trusting anything stale."""
        try:
            status, doc = self._request(
                "GET", self._path(kind, namespace, name), kind=kind
            )
        except ApiUnavailable:
            # only WATCHED kinds have an informer cache worth serving; an
            # un-watched kind's store holds nothing but this process's own
            # write echoes, and handing the lease layer its own stale
            # renewal back would corrupt the REJECTED/UNREACHABLE split
            if kind in self._watch_kinds:
                cached = self.try_get(kind, name, namespace=namespace)
                if cached is not None:
                    from karpenter_tpu import metrics

                    metrics.KUBE_DEGRADED_READS.inc()
                    return cached
            raise
        if status != 200:
            _raise_for(status, str(doc))
        return serde.from_wire(kind, doc)

    def list_live(self, kind: str, namespace: Optional[str] = None):
        """Uncached collection GET straight from the server. The fleet
        shard-lease set (kube/leader.py ``KubeLeaseSet``) must see PEER
        replicas' lease objects, and leases are deliberately not
        informer-watched (WATCH_KINDS) — the cached ``list`` only ever
        shows this process's own writes for those kinds, so there is no
        cache worth degrading to here: failures propagate and the lease
        layer classifies them (REJECTED vs UNREACHABLE)."""
        status, doc = self._request("GET", self._path(kind, namespace), kind=kind)
        if status != 200:
            _raise_for(status, str(doc))
        return [serde.from_wire(kind, item) for item in doc.get("items") or []]

    # -- mutations (REST) --------------------------------------------------
    def _write_policy(self, kind: str) -> dict:
        """Extra ``_request`` kwargs for a write to ``kind``: Event writes
        ride the zero-retry/short-deadline ``events`` class — recording is
        fire-and-forget and must never block a reconcile on a slow
        apiserver (drops are counted, kube/transport.py)."""
        if kind == "events":
            return {"verb_class": VERB_EVENTS, "timeout": self.events_timeout}
        return {}

    def create(self, kind: str, obj):
        status, doc = self._request(
            "POST", self._path(kind, obj.metadata.namespace), serde.to_wire(kind, obj),
            kind=kind, **self._write_policy(kind),
        )
        if status not in (200, 201):
            _raise_for(status, str(doc))
        fresh = serde.from_wire(kind, doc)
        # propagate server-assigned identity onto the caller's object
        obj.metadata.resource_version = fresh.metadata.resource_version
        obj.metadata.uid = fresh.metadata.uid
        obj.metadata.creation_timestamp = fresh.metadata.creation_timestamp
        self._cache_put(kind, fresh)
        self._notify(kind, "ADDED", fresh)
        return obj

    def update(self, kind: str, obj):
        status, doc = self._request(
            "PUT",
            self._path(kind, obj.metadata.namespace, obj.metadata.name),
            serde.to_wire(kind, obj),
            kind=kind, **self._write_policy(kind),
        )
        if status != 200:
            _raise_for(status, str(doc))
        fresh = serde.from_wire(kind, doc)
        obj.metadata.resource_version = fresh.metadata.resource_version
        self._cache_put(kind, fresh)
        self._notify(kind, "MODIFIED", fresh)
        return obj

    def merge_patch(
        self,
        kind: str,
        name: str,
        patch: dict,
        namespace: str = "default",
        subresource: Optional[str] = None,
    ):
        """JSON merge-patch — the reference's single-patch-per-reconcile
        idiom (node/controller.go:106-115)."""
        status, doc = self._request(
            "PATCH",
            self._path(kind, namespace, name, subresource),
            patch,
            content_type="application/merge-patch+json",
            kind=kind,
        )
        if status != 200:
            _raise_for(status, str(doc))
        fresh = serde.from_wire(kind, doc)
        self._cache_put(kind, fresh)
        self._notify(kind, "MODIFIED", fresh)
        return fresh

    def patch_status(self, kind: str, name: str, status: dict, namespace: str = "default"):
        """Merge-patch against the ``/status`` subresource — the apiserver
        drops status changes on main-resource writes for kinds with
        ``subresources.status`` (deploy/crd.yaml), so controllers must come
        through here."""
        return self.merge_patch(
            kind, name, {"status": status}, namespace=namespace, subresource="status"
        )

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        status, doc = self._request(
            "DELETE", self._path(kind, namespace, name), kind=kind
        )
        if status not in (200, 202):
            _raise_for(status, str(doc))
        # finalizer semantics live on the server: a finalized object comes
        # back MODIFIED with deletionTimestamp; a free one is gone
        if doc.get("kind") == "Status" or not doc:
            with self._lock:
                obj = self._stores[kind].objects.pop((namespace, name), None)
            if obj is not None:
                self._notify(kind, "DELETED", obj)
            return
        fresh = serde.from_wire(kind, doc)
        if fresh.metadata.deletion_timestamp is not None and fresh.metadata.finalizers:
            self._cache_put(kind, fresh)
            self._notify(kind, "MODIFIED", fresh)
        else:
            with self._lock:
                self._stores[kind].objects.pop((namespace, name), None)
            self._notify(kind, "DELETED", fresh)

    def remove_finalizer(self, kind: str, obj, finalizer: str) -> None:
        from karpenter_tpu.kube.patch import without_value

        # RFC 7386 replaces the array wholesale: carry the FULL remaining
        # list (RMW of the caller's copy), mirrored back into it so repeat
        # calls stay idempotent against the same object
        finalizers = without_value(obj.metadata.finalizers, finalizer)
        obj.metadata.finalizers[:] = finalizers
        fresh = self.merge_patch(
            kind,
            obj.metadata.name,
            {"metadata": {"finalizers": finalizers}},
            namespace=obj.metadata.namespace,
        )
        obj.metadata.resource_version = fresh.metadata.resource_version
        if fresh.metadata.deletion_timestamp is not None and not fresh.metadata.finalizers:
            # dropping the last finalizer of a terminating object frees it
            with self._lock:
                gone = self._stores[kind].objects.pop(
                    (obj.metadata.namespace, obj.metadata.name), None
                )
            if gone is not None:
                self._notify(kind, "DELETED", fresh)

    # -- subresources ------------------------------------------------------
    def bind(self, pod: Pod, node_name: str) -> None:
        # VERB_CREATE: a Binding POST is not idempotent at the HTTP layer —
        # the transport never retries it, and the 409 arm below is the
        # idempotency ladder (a lost response followed by a re-bind to the
        # SAME node already achieved the goal)
        status, doc = self._request(
            "POST",
            self._path("pods", pod.metadata.namespace, pod.metadata.name, "binding"),
            {
                "apiVersion": "v1",
                "kind": "Binding",
                "metadata": {"name": pod.metadata.name},
                "target": {"apiVersion": "v1", "kind": "Node", "name": node_name},
            },
            kind="pods",
        )
        if status == 409:
            # idempotent retry: a lost response followed by a re-bind to the
            # SAME node already achieved the goal; anything else is a real
            # conflict
            try:
                live = self.get_live("pods", pod.metadata.name, pod.metadata.namespace)
            except NotFound:
                live = None
            if live is None or live.spec.node_name != node_name:
                _raise_for(status, str(doc))
            # cache the server's (fresher) view, not the caller's stale copy
            pod.spec.node_name = node_name
            pod.metadata.resource_version = live.metadata.resource_version
            self._cache_put("pods", live)
            self._notify("pods", "MODIFIED", live)
            return
        if status not in (200, 201):
            _raise_for(status, str(doc))
        pod.spec.node_name = node_name
        self._cache_put("pods", pod)
        self._notify("pods", "MODIFIED", pod)

    def evict(self, pod: Pod) -> bool:
        return self.evict_with_hint(pod)[0]

    def evict_with_hint(self, pod: Pod) -> Tuple[bool, Optional[float]]:
        """Eviction + the server's pacing opinion: a PDB-blocked eviction
        answers 429 WITH a ``Retry-After`` header, and discarding it made
        termination requeue on a blind interval — the hint rides back so
        the eviction queue can honor the server's own schedule."""
        try:
            status, doc, retry_after = self.transport.request(
                VERB_CREATE, "POST", "pods",
                lambda: self._attempt(
                    "POST",
                    self._path(
                        "pods", pod.metadata.namespace, pod.metadata.name, "eviction"
                    ),
                    {
                        "apiVersion": "policy/v1",
                        "kind": "Eviction",
                        "metadata": {
                            "name": pod.metadata.name,
                            "namespace": pod.metadata.namespace,
                        },
                    },
                    "application/json",
                    None,
                ),
            )
        except KubeThrottled as e:
            # PDB would be violated (or the apiserver itself throttled the
            # POST): not evicted, retry when the server said to
            return False, e.retry_after
        if status == 429:
            return False, retry_after  # unreachable: transport raises — kept for safety
        if status == 404:
            return True, None  # already gone
        if status not in (200, 201):
            _raise_for(status, str(doc))
        return True, None
