from karpenter_tpu.kube.client import Cluster  # noqa: F401
