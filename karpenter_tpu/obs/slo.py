"""Online SLO evaluation: declarative objectives judged from live spans.

The system measures everything (stage spans, session hit rate, flight
records) but until now every judgment happened offline — a human reading
BENCH JSON against the BASELINE north star. This module closes that gap:
objectives are declared in a one-line grammar (``solve.p99 < 100ms``),
evaluated ONLINE from the tracer's span-completion hook, and exposed as
``karpenter_slo_*`` metrics plus ``GET /debug/slo`` on both health
servers. PR-9+ autopilot consumes this layer as its sensor; humans consume
it as "is the objective being met RIGHT NOW, and how fast is the error
budget burning".

Objective grammar (docs/observability.md has the full table)::

    <source>.<stat> <op> <value>[unit]

    solve.p99 < 100ms                  # solver.solve span durations
    provision.success_rate >= 0.999    # error-free provision.round fraction
    time_to_bind.p99 < 5s              # round duration + admission window
    session.catalog_hit_rate >= 0.9    # session_stats hit/miss events
    sidecar.pack.p99 < 100ms           # the sidecar's own end-to-end span

Design constraints, in order:

- **Hook-side cost is O(1).** A span completion does one bucket increment
  under a short lock. Quantiles, burn rates, and gauge publication happen
  on slice rotation and on snapshot — never per event.
- **Log-linear histograms.** Buckets grow by ``GROWTH`` (1.05) per step,
  so a quantile read off the sketch is within ~2.5% of the exact value —
  the bench acceptance bar (online vs offline percentile within 5%) is a
  property of the bucket scheme, not luck.
- **Trace-id exemplars.** Every bucket remembers the last trace id that
  landed in it, and every budget breach remembers its trace — ``/debug/slo``
  answers "show me a solve that blew the objective" with an id that greps
  straight into ``/debug/traces`` and the flight dir.
- **Multi-window burn rates.** Each objective keeps a fast (default 5 m)
  and slow (12x fast, so 1 h) sliding window over one shared slice ring;
  *burning* means BOTH windows consume error budget faster than allowed —
  the standard multiwindow page condition (a blip trips neither; a real
  regression trips both).
- **Fake-clock testable.** All windowing runs off an injected ``clock``;
  tests drive burn-rate transitions deterministically.

The engine is installed with ``obs.configure_slo`` (a tracer finish-hook
+ a registered flight-recorder state panel, so every slow-solve record
snapshots which objectives were burning at the time). Never import this
module from jit/vmap/pallas-reachable solver code — it is host-side span
machinery like the rest of ``obs`` (karplint ``span-closed``).
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.obs.trace import Span

# -- log-linear bucket scheme -------------------------------------------------

BASE_S = 1e-4  # 0.1ms: everything faster lands in bucket 0
GROWTH = 1.05  # per-bucket width ratio; quantile error ~ sqrt(1.05)-1 ≈ 2.5%
_LOG_GROWTH = math.log(GROWTH)


def bucket_index(value: float) -> int:
    if value <= BASE_S:
        return 0
    return int(math.log(value / BASE_S) / _LOG_GROWTH) + 1


def bucket_value(index: int) -> float:
    """Representative value of a bucket: the geometric midpoint of its
    bounds, so quantization error is symmetric in log space."""
    if index <= 0:
        return BASE_S
    return BASE_S * GROWTH ** (index - 0.5)


# -- objective grammar --------------------------------------------------------

# span sources: grammar prefix -> (span name, value extraction)
# "duration" = span.duration_s; "duration+admission" additionally counts the
# batcher window the round span carries as an attribute (work that predates
# the span, which is exactly what a pod waiting to bind experienced)
SPAN_SOURCES: Dict[str, Tuple[str, str]] = {
    "solve": ("solver.solve", "duration"),
    "provision": ("provision.round", "duration"),
    "time_to_bind": ("provision.round", "duration+admission"),
    "sidecar.pack": ("sidecar.pack", "duration"),
    # the kube transport choke point (kube/transport.py): one span per
    # logical apiserver request, so `kube.p99 < 1s` pages on a browning-out
    # control plane before the breaker has to open
    "kube": ("kube.request", "duration"),
}

# ratio sources fed by explicit events (not spans): full grammar lhs
RATIO_SOURCES = ("session.catalog_hit_rate",)

_UNITS = {"us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0}
_OPS: Dict[str, Callable[[float, float], bool]] = {
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}
_EXPR_RE = re.compile(
    r"^\s*(?P<lhs>[a-z0-9_.]+)\s*(?P<op>[<>]=?)\s*"
    r"(?P<value>[0-9]*\.?[0-9]+)\s*(?P<unit>us|ms|s|m)?\s*$"
)
_PCTL_RE = re.compile(r"^p(\d{1,2})$")

DEFAULT_OBJECTIVES = (
    "solve.p99 < 100ms",
    "provision.success_rate >= 0.999",
    "time_to_bind.p99 < 5s",
    "session.catalog_hit_rate >= 0.9",
    # apiserver health as seen from THIS client (per kube.request span) —
    # a browning-out control plane burns this first, before binds fail
    "kube.p99 < 1s",
)
# the sidecar's own view: its end-to-end unit is the pack span, and the
# session store it owns is the hit-rate source of truth
SIDECAR_OBJECTIVES = (
    "sidecar.pack.p99 < 100ms",
    "session.catalog_hit_rate >= 0.9",
)


class Objective:
    """One parsed objective. ``kind`` is ``latency`` (histogram quantile
    judged against the threshold), ``span_ratio`` (error-free span
    fraction), or ``ratio`` (explicit good/bad events)."""

    __slots__ = (
        "name", "expr", "kind", "span_name", "value_kind", "stat",
        "quantile", "op_name", "op", "threshold", "budget",
    )

    def __init__(self, expr: str):
        m = _EXPR_RE.match(expr)
        if m is None:
            raise ValueError(
                f"unparseable objective {expr!r} "
                "(grammar: <source>.<stat> <op> <value>[us|ms|s|m])"
            )
        lhs, self.op_name = m.group("lhs"), m.group("op")
        self.expr = expr.strip()
        self.op = _OPS[self.op_name]
        self.threshold = float(m.group("value")) * _UNITS.get(m.group("unit") or "", 1.0)

        if lhs in RATIO_SOURCES:
            self.kind = "ratio"
            self.span_name = None
            self.value_kind = None
            self.stat = lhs
            self.quantile = None
            self.name = lhs.replace(".", "_")
            self.budget = self._ratio_budget()
            return
        source, _, stat = lhs.rpartition(".")
        if source not in SPAN_SOURCES:
            raise ValueError(
                f"unknown objective source {source!r} in {expr!r} "
                f"(known: {', '.join((*SPAN_SOURCES, *RATIO_SOURCES))})"
            )
        self.span_name, self.value_kind = SPAN_SOURCES[source]
        self.stat = stat
        self.name = f"{source.replace('.', '_')}_{stat}"
        pm = _PCTL_RE.match(stat)
        if pm is not None:
            self.kind = "latency"
            self.quantile = int(pm.group(1)) / 100.0
            # the error budget of `p99 < X` is the 1% of events allowed
            # over X; burn rate = (observed over-threshold fraction)/budget
            self.budget = max(1.0 - self.quantile, 1e-6)
        elif stat == "mean":
            self.kind = "latency"
            self.quantile = None
            self.budget = 0.01  # treat like a p99: 1% may breach
        elif stat == "success_rate":
            self.kind = "span_ratio"
            self.quantile = None
            self.budget = self._ratio_budget()
        else:
            raise ValueError(
                f"unknown stat {stat!r} in {expr!r} "
                "(pNN, mean, or success_rate)"
            )

    def _ratio_budget(self) -> float:
        # `success_rate >= 0.999` allows 0.1% bad events; a `<=`-style
        # ratio objective would allow `threshold` itself
        if self.op_name in (">", ">="):
            return max(1.0 - self.threshold, 1e-6)
        return max(self.threshold, 1e-6)

    def evaluate(self, value: Optional[float]) -> Optional[bool]:
        if value is None:
            return None
        return bool(self.op(value, self.threshold))


def parse_objectives(exprs: Sequence[str]) -> List[Objective]:
    objs = [Objective(e) for e in exprs]
    seen: Dict[str, str] = {}
    for o in objs:
        if o.name in seen:
            raise ValueError(
                f"objective {o.expr!r} collides with {seen[o.name]!r} "
                f"(both evaluate as {o.name})"
            )
        seen[o.name] = o.expr
    return objs


def load_objectives(path: str) -> List[str]:
    """Read an ``--slo-config`` file: one objective per line, ``#`` starts
    a comment, blank lines ignored. Parse errors raise at load time —
    a typo'd objective must fail startup, not silently never evaluate."""
    out: List[str] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                out.append(line)
    parse_objectives(out)  # validate eagerly
    return out


# -- sliding-window state -----------------------------------------------------


# Raw values retained per slice for SMALL-sample exactness: the sketch's
# ~2.5% bucket quantization is fine at volume but dominates a 12-event
# bench window, where a p99 IS the max and a bucket midpoint can miss it
# by a whole bucket (BENCH_r07: 8% online-vs-offline delta on the device
# leg). While a window's raw list is COMPLETE (no slice hit the cap) the
# quantile is answered exactly; past the cap the sketch takes over and
# its error bound is back to the bucket scheme's.
RAW_SAMPLE_CAP = 64


class _Slice:
    __slots__ = ("index", "counts", "exemplars", "good", "bad", "breach", "raw")

    def __init__(self, index: int):
        self.index = index
        self.counts: Dict[int, int] = {}
        self.exemplars: Dict[int, str] = {}  # bucket -> last trace id
        self.good = 0
        self.bad = 0
        self.breach: Optional[str] = None  # last budget-breaching trace id
        self.raw: List[float] = []  # first RAW_SAMPLE_CAP values, exact


class SlidingWindow:
    """A ring of time slices shared by the fast and slow windows: the fast
    window reads the newest ``fast_slices`` slices, the slow window reads
    them all. One lock, O(1) record."""

    def __init__(
        self,
        slice_s: float,
        fast_slices: int,
        total_slices: int,
        clock: Callable[[], float],
    ):
        self.slice_s = slice_s
        self.fast_slices = fast_slices
        self.total_slices = total_slices
        self._clock = clock
        self._slices: "deque[_Slice]" = deque()  # guarded-by: self._lock
        self._lock = threading.Lock()

    def _current_locked(self) -> Tuple[_Slice, bool]:
        idx = int(self._clock() / self.slice_s)
        rotated = False
        if not self._slices or self._slices[-1].index != idx:
            # a quiet period leaves index gaps; expired slices drop by
            # INDEX, not by count, so silence ages the window correctly
            self._slices.append(_Slice(idx))
            floor = idx - self.total_slices + 1
            while self._slices and self._slices[0].index < floor:
                self._slices.popleft()
            rotated = True
        return self._slices[-1], rotated

    def record(
        self,
        value: Optional[float],
        trace_id: Optional[str],
        bad: bool,
    ) -> bool:
        """One event; returns True when the slice ring rotated (the
        caller's cue to republish derived gauges)."""
        with self._lock:
            sl, rotated = self._current_locked()
            if value is not None:
                b = bucket_index(value)
                sl.counts[b] = sl.counts.get(b, 0) + 1
                if trace_id:
                    sl.exemplars[b] = trace_id
                if len(sl.raw) < RAW_SAMPLE_CAP:
                    sl.raw.append(value)
            if bad:
                sl.bad += 1
                if trace_id:
                    sl.breach = trace_id
            else:
                sl.good += 1
        return rotated

    def merged(self, fast: bool) -> Dict[str, Any]:
        """Counts/exemplars/good/bad merged over the fast or slow window.
        Slices are selected by INDEX AGE against the clock, so a window
        with no recent events still expires its old slices."""
        now_idx = int(self._clock() / self.slice_s)
        span = self.fast_slices if fast else self.total_slices
        floor = now_idx - span + 1
        counts: Dict[int, int] = {}
        exemplars: Dict[int, str] = {}
        good = bad = 0
        breach: Optional[str] = None
        raw: List[float] = []
        with self._lock:
            # merge under the lock: the newest slice's dicts are live —
            # a concurrent record() growing them mid-iteration would raise
            for s in self._slices:
                if s.index < floor:
                    continue
                for b, n in s.counts.items():
                    counts[b] = counts.get(b, 0) + n
                exemplars.update(s.exemplars)
                good += s.good
                bad += s.bad
                if s.breach is not None:
                    breach = s.breach
                raw.extend(s.raw)
        return {
            "counts": counts, "exemplars": exemplars,
            "good": good, "bad": bad, "breach": breach,
            # complete iff len(raw) == sum(counts.values()): no slice in
            # the window overflowed its cap, so exact stats are available
            "raw": raw,
        }


class Histogram:
    """A mergeable log-linear histogram over the FIXED bucket geometry
    above. Because every window in every process shares ``BASE_S``/
    ``GROWTH``, merging two snapshots is plain per-bucket addition — the
    property the fleet telemetry plane (obs/collector.py) leans on: member
    snapshots merge into fleet-wide quantiles with exactly the same ~2.5%
    error bar as a single process's sketch, no re-binning, no loss."""

    __slots__ = ("counts", "good", "bad")

    def __init__(self):
        self.counts: Dict[int, int] = {}
        self.good = 0
        self.bad = 0

    def observe(self, value: Optional[float], bad: bool = False) -> None:
        if value is not None:
            b = bucket_index(value)
            self.counts[b] = self.counts.get(b, 0) + 1
        if bad:
            self.bad += 1
        else:
            self.good += 1

    def merge(self, snapshot) -> "Histogram":
        """Fold another histogram (or its JSON ``snapshot()`` dict — bucket
        keys may arrive as strings after a round trip) into this one."""
        if isinstance(snapshot, Histogram):
            counts, good, bad = snapshot.counts, snapshot.good, snapshot.bad
        else:
            counts = snapshot.get("counts") or {}
            good = int(snapshot.get("good") or 0)
            bad = int(snapshot.get("bad") or 0)
        for b, n in counts.items():
            b = int(b)
            self.counts[b] = self.counts.get(b, 0) + int(n)
        self.good += good
        self.bad += bad
        return self

    def total(self) -> int:
        return sum(self.counts.values())

    def events(self) -> int:
        return self.good + self.bad

    def quantile(self, q: float) -> Optional[float]:
        return _quantile(self.counts, q)

    def mean(self) -> Optional[float]:
        return _mean(self.counts)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready form; ``merge`` accepts it back verbatim."""
        return {
            "counts": {str(b): n for b, n in self.counts.items()},
            "good": self.good,
            "bad": self.bad,
        }

    @classmethod
    def from_window(cls, merged: Dict[str, Any]) -> "Histogram":
        """Wrap a :meth:`SlidingWindow.merged` result (already bucketed in
        the shared geometry)."""
        h = cls()
        h.counts = dict(merged.get("counts") or {})
        h.good = int(merged.get("good") or 0)
        h.bad = int(merged.get("bad") or 0)
        return h


def _quantile_exact(values: Sequence[float], q: float) -> float:
    """Nearest-rank quantile over raw values — the SAME rank formula as
    the sketch walk below (and the bench's offline ``_p99``), so a small
    complete window agrees with the offline cross-check to the float."""
    vs = sorted(values)
    rank = min(max(math.ceil(q * len(vs)), 1), len(vs))
    return vs[rank - 1]


def _quantile(counts: Dict[int, int], q: float) -> Optional[float]:
    total = sum(counts.values())
    if not total:
        return None
    rank = max(math.ceil(q * total), 1)
    seen = 0
    for b in sorted(counts):
        seen += counts[b]
        if seen >= rank:
            return bucket_value(b)
    return bucket_value(max(counts))


def _mean(counts: Dict[int, int]) -> Optional[float]:
    total = sum(counts.values())
    if not total:
        return None
    return sum(bucket_value(b) * n for b, n in counts.items()) / total


# -- the engine ---------------------------------------------------------------

FAST_SLICES = 5  # fast window = 5 slices; slow = SLOW_FACTOR x fast
SLOW_FACTOR = 12  # 5m fast -> 1h slow, the classic multiwindow pairing
# Low-traffic guard: a window holding fewer events than this never burns.
# Burn rate divides by OBSERVED volume, so after an idle hour a 5-solve
# blip would otherwise be 100% of both windows and page instantly — the
# exact transient the multiwindow condition exists to filter.
MIN_WINDOW_EVENTS = 10


class _ObjectiveState:
    """One objective's window plus its pre-resolved metric children (label
    lookup once at construction, not per event)."""

    __slots__ = (
        "objective", "window", "_m", "_g_ok", "_g_burning", "_g_fast",
        "_g_slow", "_c_good", "_c_bad",
    )

    def __init__(self, objective: Objective, window: SlidingWindow):
        self.objective = objective
        self.window = window
        self._m = None
        self._g_ok = self._g_burning = None
        self._g_fast = self._g_slow = self._c_good = self._c_bad = None
        try:
            from karpenter_tpu import metrics

            self._m = metrics
            name = objective.name
            # objective_ok stays UNRESOLVED here: instantiating the child
            # would publish 0.0 ("failing") for an objective that has seen
            # no data — it materializes on the first real verdict
            self._g_burning = metrics.SLO_BURNING.labels(objective=name)
            self._g_fast = metrics.SLO_BURN_RATE.labels(objective=name, window="fast")
            self._g_slow = metrics.SLO_BURN_RATE.labels(objective=name, window="slow")
            self._c_good = metrics.SLO_EVENTS.labels(objective=name, verdict="good")
            self._c_bad = metrics.SLO_EVENTS.labels(objective=name, verdict="bad")
        except Exception:
            pass  # the sidecar's trimmed images may lack the registry

    # -- event intake -------------------------------------------------------

    def observe(self, value: Optional[float], trace_id: Optional[str], bad: bool) -> None:
        rotated = self.window.record(value, trace_id, bad)
        c = self._c_bad if bad else self._c_good
        if c is not None:
            c.inc()
        if rotated:
            # derived gauges refresh on slice boundaries (and on every
            # snapshot) — the hot path stays one bucket increment
            self.publish()

    def observe_span(self, span: Span) -> None:
        obj = self.objective
        value = span.duration_s
        if obj.value_kind == "duration+admission":
            try:
                value += float(span.attrs.get("admission_window_s") or 0.0)
            except (TypeError, ValueError):
                pass
        if obj.kind == "span_ratio":
            bad = span.error is not None
        else:
            # a latency objective's budget-consuming event is a breach of
            # the threshold itself (`p99 < 100ms` allows 1% over 100ms)
            bad = obj.evaluate(value) is False
        self.observe(value, span.trace_id or None, bad)

    # -- evaluation ---------------------------------------------------------

    def _value(self, merged: Dict[str, Any]) -> Optional[float]:
        obj = self.objective
        if obj.kind == "latency":
            raw = merged.get("raw") or []
            total = sum(merged["counts"].values())
            if raw and len(raw) == total:
                # small complete window: answer exactly instead of off the
                # sketch (the sketch's bucket quantization dominates at
                # bench-scale sample counts — see RAW_SAMPLE_CAP)
                if obj.quantile is not None:
                    return _quantile_exact(raw, obj.quantile)
                return sum(raw) / len(raw)
            if obj.quantile is not None:
                return _quantile(merged["counts"], obj.quantile)
            return _mean(merged["counts"])
        total = merged["good"] + merged["bad"]
        if not total:
            return None
        return merged["good"] / total

    def _burn(self, merged: Dict[str, Any]) -> float:
        total = merged["good"] + merged["bad"]
        if total < MIN_WINDOW_EVENTS:
            return 0.0  # below the volume guard: no verdict, no page
        return (merged["bad"] / total) / self.objective.budget

    def evaluate(self) -> Dict[str, Any]:
        obj = self.objective
        fast = self.window.merged(fast=True)
        slow = self.window.merged(fast=False)
        value = self._value(fast)
        ok = obj.evaluate(value)
        burn_fast, burn_slow = self._burn(fast), self._burn(slow)
        burning = burn_fast >= 1.0 and burn_slow >= 1.0
        worst = None
        if fast["counts"]:
            top = max(b for b in fast["counts"] if fast["counts"][b])
            worst = {
                "trace_id": fast["exemplars"].get(top),
                "value_s": round(bucket_value(top), 6),
            }
        return {
            "expr": obj.expr,
            "kind": obj.kind,
            "threshold": obj.threshold,
            "value": value,
            "ok": ok,
            "burn_rate": {
                "fast": round(burn_fast, 4), "slow": round(burn_slow, 4),
            },
            "burning": burning,
            "events": {
                "fast": fast["good"] + fast["bad"],
                "slow": slow["good"] + slow["bad"],
            },
            "exemplars": {"worst": worst, "breach": fast["breach"]},
        }

    def publish(self) -> Dict[str, Any]:
        out = self.evaluate()
        if self._g_burning is not None:
            if out["ok"] is not None:
                if self._g_ok is None:
                    self._g_ok = self._m.SLO_OBJECTIVE_OK.labels(
                        objective=self.objective.name
                    )
                self._g_ok.set(1.0 if out["ok"] else 0.0)
            self._g_burning.set(1.0 if out["burning"] else 0.0)
            self._g_fast.set(out["burn_rate"]["fast"])
            self._g_slow.set(out["burn_rate"]["slow"])
        return out


class SloEngine:
    """The tracer finish-hook: streams watched spans into per-objective
    sliding windows. Register with ``tracer.add_hook`` (``obs.configure_slo``
    does this); feed non-span ratio events through :meth:`record_ratio`."""

    def __init__(
        self,
        objectives: Optional[Sequence[str]] = None,
        window_s: float = 300.0,
        slow_factor: int = SLOW_FACTOR,
        clock: Callable[[], float] = time.monotonic,
    ):
        if window_s <= 0:
            raise ValueError("SLO window must be positive seconds")
        self.window_s = float(window_s)
        self.slow_window_s = self.window_s * slow_factor
        self._clock = clock
        slice_s = self.window_s / FAST_SLICES
        total = FAST_SLICES * slow_factor
        self._states: Dict[str, _ObjectiveState] = {}
        self._by_span: Dict[str, List[_ObjectiveState]] = {}
        self._by_ratio: Dict[str, _ObjectiveState] = {}
        for obj in parse_objectives(list(objectives or DEFAULT_OBJECTIVES)):
            st = _ObjectiveState(
                obj, SlidingWindow(slice_s, FAST_SLICES, total, clock)
            )
            self._states[obj.name] = st
            if obj.kind == "ratio":
                self._by_ratio[obj.stat] = st
            else:
                self._by_span.setdefault(obj.span_name, []).append(st)

    @property
    def watched_spans(self) -> Tuple[str, ...]:
        return tuple(self._by_span)

    # -- intake -------------------------------------------------------------

    def __call__(self, span: Span) -> None:
        """Tracer finish-hook. Must stay fast and never raise (the tracer
        contains hook exceptions, but a slow hook taxes every span)."""
        states = self._by_span.get(span.name)
        if not states:
            return
        for st in states:
            st.observe_span(span)

    def record_ratio(
        self, key: str, good: bool, trace_id: Optional[str] = None
    ) -> None:
        """An explicit good/bad event for a ratio source (session_stats
        feeds ``session.catalog_hit_rate`` through this)."""
        st = self._by_ratio.get(key)
        if st is not None:
            st.observe(None, trace_id, not good)

    # -- readout ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``/debug/slo`` payload; also republishes every gauge so a
        scrape following a snapshot is never staler than the snapshot."""
        return {
            "window_s": self.window_s,
            "slow_window_s": self.slow_window_s,
            "objectives": {
                name: st.publish() for name, st in self._states.items()
            },
        }

    def histogram_snapshot(self) -> Dict[str, Any]:
        """The MERGEABLE form of the engine's state: per objective, the
        fast and slow windows as raw :class:`Histogram` snapshots (fixed
        bucket geometry) plus the expression to re-judge them with. This is
        what the telemetry flusher ships — the collector merges member
        windows bucket-by-bucket into fleet-wide quantiles and burn rates
        (obs/collector.py), which verdict-only snapshots cannot support."""
        out: Dict[str, Any] = {}
        for name, st in self._states.items():
            fast = st.window.merged(fast=True)
            slow = st.window.merged(fast=False)
            out[name] = {
                "expr": st.objective.expr,
                "kind": st.objective.kind,
                "fast": Histogram.from_window(fast).snapshot(),
                "slow": Histogram.from_window(slow).snapshot(),
                "breach": fast.get("breach"),
            }
        return {"window_s": self.window_s, "objectives": out}

    def burning_panel(self) -> Dict[str, Any]:
        """The flight-recorder state panel: which objectives were burning
        when the slow solve happened — compact, no exemplars (the record
        already IS the exemplar)."""
        out: Dict[str, Any] = {}
        for name, st in self._states.items():
            e = st.evaluate()
            out[name] = {
                "ok": e["ok"],
                "burning": e["burning"],
                "burn_fast": e["burn_rate"]["fast"],
                "burn_slow": e["burn_rate"]["slow"],
            }
        return out
