"""The slow-solve flight recorder.

When a watched span (default: ``solver.solve``, the end-to-end accelerated
solve) exceeds its latency budget (default: the 100ms BASELINE p99), the
COMPLETED span tree plus a snapshot of the routing/breaker/session state
that shaped the solve is written to a capped on-disk ring under
``--flight-dir``. The point is post-hoc forensics: by the time a p99 alert
fires, the interesting solve is long gone from the in-memory trace ring —
the flight dir holds exactly the slow ones, each with the context a human
would have asked for ("what did the router believe? was a breaker open?
was the session cache thrashing?").

State providers are registered module-globally (``register_state``):
the scheduler registers its router/breaker/session views at construction,
and the recorder snapshots whatever is registered AT RECORD TIME — a
provider that raises contributes its error string instead of aborting the
record (a flight record with one missing panel beats no record).

``GET /debug/flight`` on both health servers lists :meth:`recent`.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from karpenter_tpu.obs.trace import Span

logger = logging.getLogger("karpenter.obs")

DEFAULT_BUDGET_S = 0.100  # the BASELINE <100ms p99 target
DEFAULT_CAP = 64
DEFAULT_WATCH = ("solver.solve",)

# name -> zero-arg callable returning a JSON-serializable snapshot
_state_lock = threading.Lock()
_state_providers: Dict[str, Callable[[], Any]] = {}  # guarded-by: _state_lock


def register_state(name: str, provider: Callable[[], Any]) -> None:
    """Expose one panel of process state to future flight records (router
    EMAs, breaker states, session-cache disposition...). Re-registering a
    name replaces the provider — schedulers hot-swap."""
    with _state_lock:
        _state_providers[name] = provider


def unregister_state(name: str) -> None:
    """Drop one panel (tests, and engine teardown in reset_for_tests)."""
    with _state_lock:
        _state_providers.pop(name, None)


def state_snapshot() -> Dict[str, Any]:
    """Best-effort snapshot of every registered panel: a raising provider
    contributes its error string (and counts on
    ``karpenter_flight_panel_errors_total``) instead of aborting the
    record — the span tree a flight record exists for must never be lost
    to one broken panel callback."""
    with _state_lock:
        providers = dict(_state_providers)
    out: Dict[str, Any] = {}
    for name, fn in providers.items():
        try:
            out[name] = fn()
        except Exception as e:
            out[name] = f"<state provider failed: {e}>"
            try:
                from karpenter_tpu import metrics

                metrics.FLIGHT_PANEL_ERRORS.labels(panel=name).inc()
            except Exception:
                pass  # trimmed registries
    return out


class FlightRecorder:
    """Span-completion hook (``tracer.add_hook``) + the on-disk ring."""

    def __init__(
        self,
        directory: str,
        budget_s: float = DEFAULT_BUDGET_S,
        cap: int = DEFAULT_CAP,
        watch=DEFAULT_WATCH,
    ):
        self.directory = directory
        self.budget_s = budget_s
        self.cap = cap
        self.watch = frozenset(watch)
        self.records_written = 0
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)

    # -- the hook -----------------------------------------------------------
    def __call__(self, span: Span) -> None:
        if span.name in self.watch and span.duration_s > self.budget_s:
            self.record(span)

    def record(self, span: Span) -> Optional[str]:
        """Write one incident; returns the file path (None on failure —
        recording must never fail the traced action)."""
        try:
            payload = {
                "name": span.name,
                "trace_id": span.trace_id,
                "duration_s": round(span.duration_s, 6),
                "budget_s": self.budget_s,
                "recorded_at": time.time(),
                "trace": span.to_dict(),
                "state": state_snapshot(),
            }
            with self._lock:
                # millisecond wall stamp + write sequence in the name:
                # lexicographic order IS recency order (prune and recent()
                # rely on it), and the sequence breaks same-millisecond
                # ties deterministically — two back-to-back records used
                # to tie-break on the random trace-id suffix
                fname = (
                    f"flight-{int(time.time() * 1e3):013d}"
                    f"-{self.records_written % 1_000_000:06d}"
                    f"-{span.trace_id[:8]}.json"
                )
                path = os.path.join(self.directory, fname)
                with open(path, "w", encoding="utf-8") as f:
                    json.dump(payload, f)
                self.records_written += 1
                self._prune_locked()
            try:
                from karpenter_tpu import metrics

                metrics.FLIGHT_RECORDS.inc()
            except Exception:
                pass
            logger.info(
                "flight record: %s took %.1fms (budget %.1fms) -> %s",
                span.name, span.duration_s * 1e3, self.budget_s * 1e3, path,
            )
            return path
        except Exception:
            logger.debug("flight record write failed", exc_info=True)
            return None

    def _prune_locked(self) -> None:
        names = sorted(
            n for n in os.listdir(self.directory)
            if n.startswith("flight-") and n.endswith(".json")
        )
        for victim in names[: max(len(names) - self.cap, 0)]:
            try:
                os.remove(os.path.join(self.directory, victim))
            except OSError:
                pass

    # -- the /debug/flight surface ------------------------------------------
    def recent(self, limit: int = 20) -> List[Dict[str, Any]]:
        try:
            names = sorted(
                (
                    n for n in os.listdir(self.directory)
                    if n.startswith("flight-") and n.endswith(".json")
                ),
                reverse=True,
            )[:limit]
        except OSError:
            return []
        out = []
        for n in names:
            try:
                with open(os.path.join(self.directory, n), encoding="utf-8") as f:
                    out.append(json.load(f))
            except Exception:
                continue  # a half-written or pruned-under-us file
        return out
