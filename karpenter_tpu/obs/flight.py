"""The slow-solve flight recorder.

When a watched span (default: ``solver.solve``, the end-to-end accelerated
solve) exceeds its latency budget (default: the 100ms BASELINE p99), the
COMPLETED span tree plus a snapshot of the routing/breaker/session state
that shaped the solve is written to a capped on-disk ring under
``--flight-dir``. The point is post-hoc forensics: by the time a p99 alert
fires, the interesting solve is long gone from the in-memory trace ring —
the flight dir holds exactly the slow ones, each with the context a human
would have asked for ("what did the router believe? was a breaker open?
was the session cache thrashing?").

State providers are registered module-globally (``register_state``):
the scheduler registers its router/breaker/session views at construction,
and the recorder snapshots whatever is registered AT RECORD TIME — a
provider that raises contributes its error string instead of aborting the
record (a flight record with one missing panel beats no record).

``GET /debug/flight`` on both health servers lists :meth:`recent`.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from karpenter_tpu.obs.trace import Span

logger = logging.getLogger("karpenter.obs")

DEFAULT_BUDGET_S = 0.100  # the BASELINE <100ms p99 target
DEFAULT_CAP = 64
DEFAULT_WATCH = ("solver.solve",)

# wire-dominance watch rule (ROADMAP item 2): a solve whose TRANSPORT
# self-time exceeds its device/solve share is a transport regression the
# latency budget alone can hide (a fast solve over a slow wire can still
# land under 100ms) — it self-reports as a flight record tagged
# ``wire_dominated=true`` even when under budget. The floor keeps
# microsecond-scale loopback noise from spamming the ring, and the
# cooldown keeps a STEADY wire-dominated regime (every solve matching)
# from turning the hot solve path into per-solve disk writes — one
# record per window names the regression; the rest add nothing.
MIN_WIRE_DOMINANCE_S = 0.005
WIRE_DOMINANCE_COOLDOWN_S = 30.0
# the transport leg and the sidecar stages it grafts/stitches beneath it
WIRE_SPAN = "solver.wire"
SOLVE_SHARE_SPANS = frozenset({"sidecar.solve", "sidecar.fetch"})


def _walk(span: Span):
    yield span
    for child in span.children:
        yield from _walk(child)


def wire_dominance(span: Span) -> Optional[Dict[str, float]]:
    """For a ``solver.solve`` tree: the wire's SELF time (the
    ``solver.wire`` spans minus their grafted/stitched sidecar children)
    vs the device/solve share (``sidecar.solve`` + ``sidecar.fetch``).
    None when the solve never crossed a wire (in-process backends)."""
    wire_self = 0.0
    solve_share = 0.0
    crossed = False
    for s in _walk(span):
        if s.name == WIRE_SPAN:
            crossed = True
            wire_self += max(
                s.duration_s - sum(c.duration_s for c in s.children), 0.0
            )
        elif s.name in SOLVE_SHARE_SPANS:
            solve_share += s.duration_s
    if not crossed:
        return None
    return {
        "wire_self_s": round(wire_self, 6),
        "solve_share_s": round(solve_share, 6),
    }

# name -> zero-arg callable returning a JSON-serializable snapshot
_state_lock = threading.Lock()
_state_providers: Dict[str, Callable[[], Any]] = {}  # guarded-by: _state_lock


def register_state(name: str, provider: Callable[[], Any]) -> None:
    """Expose one panel of process state to future flight records (router
    EMAs, breaker states, session-cache disposition...). Re-registering a
    name replaces the provider — schedulers hot-swap."""
    with _state_lock:
        _state_providers[name] = provider


def unregister_state(name: str) -> None:
    """Drop one panel (tests, and engine teardown in reset_for_tests)."""
    with _state_lock:
        _state_providers.pop(name, None)


def state_snapshot(only=None) -> Dict[str, Any]:
    """Best-effort snapshot of every registered panel: a raising provider
    contributes its error string (and counts on
    ``karpenter_flight_panel_errors_total``) instead of aborting the
    record — the span tree a flight record exists for must never be lost
    to one broken panel callback. ``only`` restricts to a subset of panel
    names (the decision audit log snapshots just the brownout panel, not
    the full router/breaker/session spread a flight record wants)."""
    with _state_lock:
        providers = dict(_state_providers)
    if only is not None:
        providers = {k: v for k, v in providers.items() if k in only}
    out: Dict[str, Any] = {}
    for name, fn in providers.items():
        try:
            out[name] = fn()
        except Exception as e:
            out[name] = f"<state provider failed: {e}>"
            try:
                from karpenter_tpu import metrics

                metrics.FLIGHT_PANEL_ERRORS.labels(panel=name).inc()
            except Exception:
                pass  # trimmed registries
    return out


class FlightRecorder:
    """Span-completion hook (``tracer.add_hook``) + the on-disk ring."""

    def __init__(
        self,
        directory: str,
        budget_s: float = DEFAULT_BUDGET_S,
        cap: int = DEFAULT_CAP,
        watch=DEFAULT_WATCH,
    ):
        self.directory = directory
        self.budget_s = budget_s
        self.cap = cap
        self.watch = frozenset(watch)
        self.records_written = 0
        self._lock = threading.Lock()
        self._last_rule_record = 0.0  # guarded-by: self._lock
        # filename -> incident id: records pinned against pruning because
        # an incident record references them (obs/incidents.py); bounded —
        # the oldest pins release once PIN_CAP incidents have come and gone
        self._pinned: Dict[str, str] = {}  # guarded-by: self._lock
        os.makedirs(directory, exist_ok=True)

    def _rule_cooled_down(self) -> bool:
        now = time.monotonic()
        with self._lock:
            if now - self._last_rule_record < WIRE_DOMINANCE_COOLDOWN_S:
                return False
            self._last_rule_record = now
            return True

    # -- the hook -----------------------------------------------------------
    def __call__(self, span: Span) -> None:
        if span.name not in self.watch:
            return
        extra = None
        if span.name == "solver.solve":
            shares = wire_dominance(span)
            if shares is not None and shares["wire_self_s"] > max(
                shares["solve_share_s"], MIN_WIRE_DOMINANCE_S
            ):
                extra = {"wire_dominated": True, **shares}
        over_budget = span.duration_s > self.budget_s
        if not over_budget and extra is not None and not self._rule_cooled_down():
            return  # steady wire-dominance: one record per cooldown window
        if over_budget or extra is not None:
            self.record(span, extra=extra)

    def record(self, span: Span, extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write one incident; returns the file path (None on failure —
        recording must never fail the traced action). ``extra`` merges
        watch-rule verdicts (e.g. ``wire_dominated``) into the payload."""
        try:
            payload = {
                "name": span.name,
                "trace_id": span.trace_id,
                "duration_s": round(span.duration_s, 6),
                "budget_s": self.budget_s,
                "recorded_at": time.time(),
                "trace": span.to_dict(),
                "state": state_snapshot(),
            }
            if extra:
                payload.update(extra)
            with self._lock:
                # millisecond wall stamp + write sequence in the name:
                # lexicographic order IS recency order (prune and recent()
                # rely on it), and the sequence breaks same-millisecond
                # ties deterministically — two back-to-back records used
                # to tie-break on the random trace-id suffix
                fname = (
                    f"flight-{int(time.time() * 1e3):013d}"
                    f"-{self.records_written % 1_000_000:06d}"
                    f"-{span.trace_id[:8]}.json"
                )
                path = os.path.join(self.directory, fname)
                with open(path, "w", encoding="utf-8") as f:
                    json.dump(payload, f)
                self.records_written += 1
                self._prune_locked()
            try:
                from karpenter_tpu import metrics

                metrics.FLIGHT_RECORDS.inc()
            except Exception:
                pass
            logger.info(
                "flight record: %s took %.1fms (budget %.1fms) -> %s",
                span.name, span.duration_s * 1e3, self.budget_s * 1e3, path,
            )
            return path
        except Exception:
            logger.debug("flight record write failed", exc_info=True)
            return None

    def _prune_locked(self) -> None:
        names = sorted(
            n for n in os.listdir(self.directory)
            if n.startswith("flight-") and n.endswith(".json")
        )
        excess = max(len(names) - self.cap, 0)
        removed = 0
        for victim in names:
            if removed >= excess:
                break
            if victim in self._pinned:
                continue  # incident evidence outlives the ring's age-out
            try:
                os.remove(os.path.join(self.directory, victim))
            except OSError:
                pass
            removed += 1

    # -- the incident plane's evidence hook ----------------------------------
    PIN_CAP = 16

    def pin_for_incident(
        self, incident_id: str, limit: int = 3
    ) -> List[Dict[str, Any]]:
        """Pin the newest ``limit`` records against pruning and return
        their payloads tagged with ``incident_id`` — the incident record
        (obs/incidents.py) references these files, and an unreferenced
        prune would sever the evidence an operator follows from
        ``/debug/incidents`` into ``/debug/flight``. Pins are bounded:
        past ``PIN_CAP`` the oldest-pinned files release back to the
        normal ring age-out."""
        try:
            names = sorted(
                (
                    n for n in os.listdir(self.directory)
                    if n.startswith("flight-") and n.endswith(".json")
                ),
                reverse=True,
            )[:limit]
        except OSError:
            return []
        out: List[Dict[str, Any]] = []
        with self._lock:
            for n in names:
                self._pinned[n] = incident_id
            while len(self._pinned) > self.PIN_CAP:
                self._pinned.pop(next(iter(self._pinned)))
        for n in names:
            try:
                with open(os.path.join(self.directory, n), encoding="utf-8") as f:
                    payload = json.load(f)
            except Exception:
                continue  # half-written or pruned-under-us
            out.append({
                "file": n,
                "incident_id": incident_id,
                "name": payload.get("name"),
                "trace_id": payload.get("trace_id"),
                "duration_s": payload.get("duration_s"),
                "recorded_at": payload.get("recorded_at"),
            })
        return out

    def pinned(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._pinned)

    # -- the /debug/flight surface ------------------------------------------
    def recent(self, limit: int = 20) -> List[Dict[str, Any]]:
        try:
            names = sorted(
                (
                    n for n in os.listdir(self.directory)
                    if n.startswith("flight-") and n.endswith(".json")
                ),
                reverse=True,
            )[:limit]
        except OSError:
            return []
        out = []
        for n in names:
            try:
                with open(os.path.join(self.directory, n), encoding="utf-8") as f:
                    out.append(json.load(f))
            except Exception:
                continue  # a half-written or pruned-under-us file
        return out
