"""Causally-linked spans for the provisioning pipeline (Dapper-style).

One batch of pending pods crosses many layers — reconcile, batcher,
scheduler stages, the v3 solver wire, the sidecar's device work, cloud
create, bind — and the aggregate histograms can say THAT a p99 regressed
but never WHERE. A span is one timed region with a parent, so a whole
solve becomes a tree whose self-times attribute the latency leg by leg
(docs/observability.md has the span model).

Design constraints, in order:

- **Context-manager only.** ``with tracer.span("name") as sp`` is the sole
  sanctioned way to open a span; karplint's ``span-closed`` rule flags any
  bare ``start_span`` call outside this package. An un-closed span is a
  tree that never exports and a contextvar that never resets — the API
  shape makes that unrepresentable.
- **Monotonic clocks.** Durations come from ``time.perf_counter``; a wall
  timestamp is captured once per span for display only. NTP steps can
  never produce a negative stage.
- **Contextvar propagation.** The active span rides
  ``contextvars.ContextVar``, so nesting works across the reconcile call
  tree without threading a span argument through every signature. Threads
  do NOT inherit it (executor pools run launches) — pass ``parent=``
  explicitly there.
- **Cheap when off.** ``Tracer.span`` short-circuits to a shared no-op
  context manager when disabled; the hot path pays two attribute reads.

Cross-process propagation uses W3C-traceparent-style ids
(``00-<32 hex trace>-<16 hex span>-01``): the HTTP cloud wire carries the
header, the v3 solver frames carry the same 24 bytes as an optional i32
trailer (solver/service.py), and Node objects carry it as an annotation so
the much-later ready transition still joins the launch trace.
"""

from __future__ import annotations

import contextvars
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, NamedTuple, Optional

TRACEPARENT_VERSION = "00"

# the annotation provisioning stamps on launched Nodes so the node-ready
# transition (minutes later, another reconcile) joins the launch trace
TRACE_ANNOTATION = "karpenter.sh/trace-context"


class SpanContext(NamedTuple):
    """The portable identity of a span: what crosses a process boundary."""

    trace_id: str  # 32 lowercase hex chars
    span_id: str  # 16 lowercase hex chars


def _new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One timed region. Created only by :meth:`Tracer.span`'s context
    manager (karplint: ``span-closed``); ``end`` is written exactly once,
    at ``with``-exit."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "start", "end",
        "wall_start", "attrs", "children", "error", "parent",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        parent: Optional["Span"],
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.parent = parent
        self.start = time.perf_counter()
        self.end: Optional[float] = None
        self.wall_start = time.time()
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.children: List["Span"] = []
        self.error: Optional[str] = None

    # -- while open ---------------------------------------------------------
    def set_attribute(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def add_child_record(
        self,
        name: str,
        duration_s: float,
        attrs: Optional[Dict[str, Any]] = None,
        start: Optional[float] = None,
    ) -> "Span":
        """Attach an already-COMPLETED child (a remote peer's reported
        stage, the batcher's admission window): a record, not a live span —
        it never touches the contextvar, so the span-closed contract
        holds. ``start`` is a perf_counter timestamp; defaults to "ends
        now"."""
        child = Span(
            name, self.trace_id, _new_span_id(), self.span_id, self, attrs
        )
        now = time.perf_counter()
        child.start = now - duration_s if start is None else start
        child.end = child.start + duration_s
        child.wall_start = time.time() - duration_s if start is None else (
            self.wall_start + (child.start - self.start)
        )
        self.children.append(child)
        return child

    # -- introspection ------------------------------------------------------
    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    @property
    def duration_s(self) -> float:
        end = self.end if self.end is not None else time.perf_counter()
        return max(end - self.start, 0.0)

    def to_dict(self) -> Dict[str, Any]:
        """JSON-ready tree. ``t0``/``t1`` are raw perf_counter stamps (for
        same-process overlap analysis — bench's pipelined invariant);
        ``wall_start`` anchors the tree in calendar time for humans."""
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t0": self.start,
            "t1": self.end if self.end is not None else self.start,
            "duration_ms": round(self.duration_s * 1e3, 3),
            "wall_start": self.wall_start,
            "attrs": self.attrs,
            "error": self.error,
            "children": [c.to_dict() for c in self.children],
        }

    def __repr__(self) -> str:  # debugging aid, never on a hot path
        return (
            f"Span({self.name!r} {self.trace_id[:8]}/{self.span_id} "
            f"{self.duration_s * 1e3:.2f}ms)"
        )


class _NoopSpan:
    """What disabled tracing hands out: absorbs the Span surface."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent = None
    attrs: Dict[str, Any] = {}

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def add_child_record(self, name, duration_s, attrs=None, start=None):
        return self

    @property
    def context(self) -> Optional[SpanContext]:
        return None

    @property
    def duration_s(self) -> float:
        return 0.0


_NOOP_SPAN = _NoopSpan()


class _NoopCm:
    __slots__ = ()

    def __enter__(self) -> _NoopSpan:
        return _NOOP_SPAN

    def __exit__(self, *exc) -> bool:
        return False


_NOOP_CM = _NoopCm()

_UNSET = object()


class _SpanCm:
    """The context manager ``Tracer.span`` returns; all lifecycle writes
    (contextvar set/reset, parent attach, export) live in enter/exit so a
    span cannot leak half-open."""

    __slots__ = (
        "_tracer", "_name", "_attrs", "_parent", "_span", "_token",
        "_tid", "_prev_active",
    )

    def __init__(self, tracer: "Tracer", name: str, attrs, parent):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs
        self._parent = parent
        self._span: Optional[Span] = None
        self._token = None
        self._tid = 0
        self._prev_active: Optional[Span] = None

    def __enter__(self) -> Span:
        tracer = self._tracer
        parent = self._parent
        if parent is _UNSET:
            parent = tracer._current.get()
        if isinstance(parent, Span):
            span = Span(
                self._name, parent.trace_id, _new_span_id(), parent.span_id,
                parent, self._attrs,
            )
        elif isinstance(parent, SpanContext):
            # remote parent: a local ROOT carrying the caller's trace id —
            # exported as its own tree, joined to the caller's by the ids
            span = Span(
                self._name, parent.trace_id, _new_span_id(), parent.span_id,
                None, self._attrs,
            )
        else:
            span = Span(
                self._name, _new_trace_id(), _new_span_id(), None, None,
                self._attrs,
            )
        self._span = span
        self._token = tracer._current.set(span)
        # thread registry for out-of-context readers (the sampling
        # profiler attributes a sampled thread's stack to its ACTIVE span;
        # a contextvar is unreadable from another thread, this dict isn't).
        # Plain dict ops: atomic under the GIL, no lock on the hot path.
        self._tid = threading.get_ident()
        self._prev_active = tracer._active_by_thread.get(self._tid)
        tracer._active_by_thread[self._tid] = span
        return span

    def __exit__(self, exc_type, exc, tb) -> bool:
        span = self._span
        span.end = time.perf_counter()
        if exc is not None:
            span.error = f"{exc_type.__name__}: {exc}"
        self._tracer._current.reset(self._token)
        if self._prev_active is None:
            self._tracer._active_by_thread.pop(self._tid, None)
        else:
            self._tracer._active_by_thread[self._tid] = self._prev_active
        if span.parent is not None:
            # list.append is atomic under the GIL; launches from several
            # executor threads attach to one round span concurrently
            span.parent.children.append(span)
        self._tracer._finish(span)
        return False


class Tracer:
    def __init__(self, exporter=None, enabled: bool = True):
        self.exporter = exporter
        self.enabled = enabled
        self._current: contextvars.ContextVar[Optional[Span]] = (
            contextvars.ContextVar("karpenter_active_span", default=None)
        )
        self._hooks: List[Callable[[Span], None]] = []  # guarded-by: self._hooks_lock
        self._hooks_lock = threading.Lock()
        # thread id -> innermost open span on that thread; written by
        # _SpanCm enter/exit (GIL-atomic dict ops), read by the sampling
        # profiler from ITS thread — the cross-thread twin of _current
        self._active_by_thread: Dict[int, Span] = {}

    # -- the one sanctioned way to open a span ------------------------------
    def span(self, name: str, attrs: Optional[Dict[str, Any]] = None, parent=_UNSET):
        """``with tracer.span("solve.encode") as sp:`` — context-manager
        only (karplint ``span-closed``). ``parent``: omitted = the active
        contextvar span; a :class:`Span` = explicit (executor threads); a
        :class:`SpanContext` = remote parent from the wire; ``None`` =
        force a fresh root."""
        if not self.enabled:
            return _NOOP_CM
        return _SpanCm(self, name, attrs, parent)

    def start_span(self, name: str, attrs=None, parent=_UNSET) -> Span:
        """Low-level span construction WITHOUT lifecycle management — the
        context manager's internals, exposed for this package's own tests.
        Anywhere else, karplint's ``span-closed`` rule flags a call to this
        name: a span opened here never resets the contextvar and never
        exports unless the caller reimplements ``_SpanCm`` exactly."""
        cm = _SpanCm(self, name, attrs, parent)
        return cm.__enter__()

    # -- ambient context ----------------------------------------------------
    def current(self) -> Optional[Span]:
        """The calling context's active span (None when outside any)."""
        return self._current.get() if self.enabled else None

    def active_spans(self) -> Dict[int, Span]:
        """Snapshot of thread id -> that thread's innermost OPEN span —
        the profiler's attribution surface. A copy: the registry mutates
        under the caller's feet otherwise."""
        return dict(self._active_by_thread)

    # -- completion fan-out -------------------------------------------------
    def add_hook(self, fn: Callable[[Span], None]) -> None:
        """``fn(span)`` runs on every span completion (the flight recorder
        rides this). Hooks must be fast and never raise — a raising hook
        is contained but logged at debug only."""
        with self._hooks_lock:
            self._hooks.append(fn)

    def remove_hook(self, fn: Callable[[Span], None]) -> None:
        with self._hooks_lock:
            if fn in self._hooks:
                self._hooks.remove(fn)

    def _finish(self, span: Span) -> None:
        with self._hooks_lock:
            hooks = list(self._hooks)
        for fn in hooks:
            try:
                fn(span)
            except Exception:
                import logging

                logging.getLogger("karpenter.obs").debug(
                    "span hook failed", exc_info=True
                )
        if span.parent is None and self.exporter is not None:
            self.exporter.export(span)


# -- traceparent-style wire form ---------------------------------------------


def to_traceparent(span_or_ctx) -> str:
    """``00-<trace_id>-<span_id>-01`` for the HTTP header / annotation."""
    ctx = span_or_ctx.context if isinstance(span_or_ctx, Span) else span_or_ctx
    return f"{TRACEPARENT_VERSION}-{ctx.trace_id}-{ctx.span_id}-01"


def from_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a traceparent-style header; None on anything malformed — a
    corrupt header degrades to an unlinked trace, never an error."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _version, trace_id, span_id, _flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return SpanContext(trace_id.lower(), span_id.lower())
