"""Bounded in-memory trace storage + the analysis helpers bench uses.

The exporter is a ring: the newest ``capacity`` root span TREES are held,
older ones are dropped (counted — ``karpenter_trace_dropped_total`` —
because a silently-shrinking window reads as "nothing slow happened").
``GET /debug/traces`` on either health server serves :meth:`snapshot`;
:meth:`dump_jsonl` writes the same trees as JSON lines for offline tools.

The two pure functions at the bottom are the bench's measurement surface:
``critical_path`` walks the slowest chain of a tree attributing SELF time
per leg, and ``overlapping_pairs`` counts cross-trace interval overlaps —
the PR-4 "encode(i+1) overlaps solve(i)" pipeline claim as a checked
invariant instead of a smoke test.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from karpenter_tpu.obs.trace import Span


def _count_spans(span: Span) -> int:
    return 1 + sum(_count_spans(c) for c in span.children)


class RingExporter:
    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._trees: "deque[Span]" = deque()  # guarded-by: self._lock
        self._lock = threading.Lock()
        self.exported_spans = 0  # guarded-by: self._lock
        self.dropped_spans = 0  # guarded-by: self._lock
        self.held_spans = 0  # guarded-by: self._lock

    def export(self, root: Span) -> None:
        n = _count_spans(root)
        dropped = 0
        with self._lock:
            self.exported_spans += n
            while len(self._trees) >= self.capacity:
                dropped += _count_spans(self._trees.popleft())
            self.dropped_spans += dropped
            self._trees.append(root)
            self.held_spans += n - dropped
            held_trees, held_spans = len(self._trees), self.held_spans
        try:
            from karpenter_tpu import metrics

            metrics.TRACE_SPANS.inc(n)
            if dropped:
                metrics.TRACE_DROPPED.inc(dropped)
            metrics.TRACE_RING_TREES.set(held_trees)
            metrics.TRACE_RING_SPANS.set(held_spans)
        except Exception:
            pass  # the sidecar's trimmed images may lack the registry

    def stats(self) -> Dict[str, Any]:
        """Per-process exporter residency — the /debug/traces sidebar and
        the source of the `karpenter_trace_ring_*` gauges."""
        with self._lock:
            return {
                "capacity": self.capacity,
                "trees": len(self._trees),
                "spans": self.held_spans,
                "exported_spans": self.exported_spans,
                "dropped_spans": self.dropped_spans,
            }

    def snapshot(
        self,
        limit: Optional[int] = 50,
        newest_first: bool = True,
        name: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        """JSON-ready trees; newest first by default (the /debug surface).
        ``name`` keeps only trees CONTAINING a span so named (the
        ``?name=`` query filter — one trace family, not the whole ring);
        ``trace_id`` is the exact lookup (every span in a tree shares its
        root's trace id, so this is a root-field test, not a walk);
        ``limit`` applies after the filters, so it bounds what the
        operator asked for."""
        with self._lock:
            trees = list(self._trees)
        if newest_first:
            trees.reverse()
        if trace_id is not None:
            trees = [t for t in trees if t.trace_id == trace_id]
        if name is None:
            # no filter: slice BEFORE serializing — a full 256-tree ring
            # must not pay 256 deep to_dict()s to answer a limit-50 request
            if limit is not None:
                trees = trees[:limit]
            return [t.to_dict() for t in trees]
        dicts: List[Dict[str, Any]] = []
        for t in trees:
            d = t.to_dict()
            if spans_named(d, name):
                dicts.append(d)
                if limit is not None and len(dicts) >= limit:
                    break
        return dicts

    def trees(self) -> List[Dict[str, Any]]:
        """All held trees, oldest first — bench correlates tree index to
        iteration index (single-threaded legs export in call order)."""
        return self.snapshot(limit=None, newest_first=False)

    def clear(self) -> None:
        with self._lock:
            self._trees.clear()
            self.held_spans = 0
        try:
            from karpenter_tpu import metrics

            metrics.TRACE_RING_TREES.set(0)
            metrics.TRACE_RING_SPANS.set(0)
        except Exception:
            pass

    def dump_jsonl(self, path: str) -> int:
        """Write every held tree as one JSON line each; returns the count."""
        trees = self.snapshot(limit=None, newest_first=False)
        with open(path, "w", encoding="utf-8") as f:
            for t in trees:
                f.write(json.dumps(t) + "\n")
        return len(trees)


# -- analysis ----------------------------------------------------------------


def critical_path(tree: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Self-time attribution down the slowest-child chain of a span tree
    (already in dict form). Each step reports the leg's total duration and
    its SELF time (duration minus its children's) — where the milliseconds
    actually live, not just which subtree contains them."""
    out: List[Dict[str, Any]] = []
    node = tree
    while node is not None:
        children = node.get("children") or []
        child_total = sum(c.get("duration_ms", 0.0) for c in children)
        out.append({
            "name": node.get("name"),
            "duration_ms": round(node.get("duration_ms", 0.0), 3),
            "self_ms": round(max(node.get("duration_ms", 0.0) - child_total, 0.0), 3),
        })
        node = max(children, key=lambda c: c.get("duration_ms", 0.0)) if children else None
    return out


def spans_named(tree: Dict[str, Any], name: str) -> List[Dict[str, Any]]:
    """Every span dict named ``name`` anywhere under ``tree`` (inclusive) —
    the one tree walk, shared by the overlap counter and bench's
    fetch-duration gating."""
    out = []
    stack = [tree]
    while stack:
        node = stack.pop()
        if node.get("name") == name:
            out.append(node)
        stack.extend(node.get("children") or [])
    return out


def overlapping_pairs(
    trees: List[Dict[str, Any]],
    a_name: str = "solve.encode",
    b_name: str = "solve.pack_fetch",
) -> int:
    """Count (a, b) span pairs from DIFFERENT traces whose perf_counter
    intervals overlap — only meaningful for trees captured in one process
    (t0/t1 share a clock). The pipelined bench asserts this is nonzero:
    some batch's encode really did run while another solve's fetch was in
    flight."""
    a_spans = []
    b_spans = []
    for t in trees:
        tid = t.get("trace_id")
        a_spans.extend((tid, s["t0"], s["t1"]) for s in spans_named(t, a_name))
        b_spans.extend((tid, s["t0"], s["t1"]) for s in spans_named(t, b_name))
    pairs = 0
    for a_tid, a0, a1 in a_spans:
        for b_tid, b0, b1 in b_spans:
            if a_tid != b_tid and a0 < b1 and b0 < a1:
                pairs += 1
    return pairs
