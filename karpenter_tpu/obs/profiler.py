"""Always-on sampling profiler: stdlib-only, span-attributed.

The span trees say which STAGE ate a slow solve's budget; nothing says
which PYTHON FRAMES did. This module closes that gap with the classic
low-overhead answer — a daemon thread wakes ``hz`` times a second, grabs
``sys._current_frames()``, and folds every other thread's stack into
collapsed-flamegraph counts (``a.py:f;b.py:g 12``). Because it samples
wall-clock state rather than instrumenting calls, the steady-state cost is
one frame walk per thread per tick: the bench acceptance bar holds it
under 1% of headline throughput, self-accounted as
``karpenter_telemetry_profile_overhead_ratio`` so the claim is scrapeable,
not folklore.

Attribution: each sampled thread's stack is ALSO charged to that thread's
innermost open span via the tracer's thread registry
(:meth:`Tracer.active_spans` — a contextvar is unreadable from another
thread, the registry isn't), so ``/debug/profile`` can say "38% of samples
landed under ``solve.encode``" next to the frame-level folds.

Safety notes (docs/telemetry.md):

- ``sys._current_frames()`` returns real frame objects; walking
  ``f_back``/``f_code`` only READS them — the sampled thread keeps
  running, nothing is suspended.
- The sampler never takes locks the sampled code could hold: fold storage
  is guarded by its own lock, touched only by the sampler thread and
  readers.
- The default rate (19 Hz) is deliberately off-aligned from common 10/20/
  100 Hz periodic work so the sampler does not phase-lock with it and
  systematically over- or under-count.
- Fold storage is bounded (``max_folds``): a pathological stack churn
  degrades to an ``<other>`` bucket, never unbounded memory.

``GET /debug/profile`` on BOTH health servers serves
:func:`karpenter_tpu.obs.debug_profile_payload` (top-N self-time JSON, or
the raw collapsed corpus with ``?format=collapsed`` — feed it straight to
a flamegraph renderer). The in-window top folds additionally ride every
flight record via the registered ``profile`` state panel, so a slow-solve
incident file finally names the frames.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

DEFAULT_HZ = 19.0  # off-aligned: never phase-locks with 10/20/100Hz work
DEFAULT_WINDOW_S = 60.0  # the flight-panel "recent" window
MAX_STACK_DEPTH = 64
MAX_FOLDS = 4096  # past this, new stacks fold into "<other>"
OVERFLOW_KEY = "<other>"


def _frame_label(frame) -> str:
    """``path/tail.py:qualname`` — short enough to read, unique enough to
    grep. Two path components keep ``service.py`` in the controller apart
    from any other ``service.py``."""
    code = frame.f_code
    fname = code.co_filename.replace("\\", "/")
    parts = fname.rsplit("/", 2)
    tail = "/".join(parts[-2:]) if len(parts) > 1 else fname
    name = getattr(code, "co_qualname", None) or code.co_name
    return f"{tail}:{name}"


def fold_stack(frame, max_depth: int = MAX_STACK_DEPTH) -> str:
    """Collapse one thread's live stack, outermost frame first — the
    flamegraph 'collapsed' convention."""
    labels: List[str] = []
    depth = 0
    while frame is not None and depth < max_depth:
        labels.append(_frame_label(frame))
        frame = frame.f_back
        depth += 1
    labels.reverse()
    return ";".join(labels)


class SamplingProfiler:
    """The daemon sampler. ``obs.configure_profiler`` installs the
    process-wide one; tests drive :meth:`sample_once` directly with no
    thread at all."""

    def __init__(
        self,
        hz: float = DEFAULT_HZ,
        tracer=None,
        window_s: float = DEFAULT_WINDOW_S,
        max_depth: int = MAX_STACK_DEPTH,
        max_folds: int = MAX_FOLDS,
        clock: Callable[[], float] = time.perf_counter,
    ):
        if hz <= 0:
            raise ValueError("profiler rate must be positive Hz")
        self.hz = float(hz)
        self.interval = 1.0 / self.hz
        self.window_s = float(window_s)
        self.max_depth = max_depth
        self.max_folds = max_folds
        self._tracer = tracer
        self._clock = clock
        self._lock = threading.Lock()
        # cumulative since start (the /debug/profile corpus)
        self._folds: Dict[str, int] = {}  # guarded-by: self._lock
        self._leaf: Dict[str, int] = {}  # guarded-by: self._lock
        self._span_samples: Dict[str, int] = {}  # guarded-by: self._lock
        # two half-window slices rotated in place: cur+prev always cover
        # the last [window_s/2, window_s] of samples — the flight panel's
        # "what was hot JUST NOW", without a deque of per-tick dicts
        self._win_cur: Dict[str, int] = {}  # guarded-by: self._lock
        self._win_prev: Dict[str, int] = {}  # guarded-by: self._lock
        self._win_rotated_at = self._clock()  # guarded-by: self._lock
        self.samples = 0  # guarded-by: self._lock
        self.ticks = 0  # guarded-by: self._lock
        self._busy_s = 0.0  # guarded-by: self._lock
        # per-thread fold memo keyed by FRAME IDENTITY: a frame's ancestor
        # chain is fixed for its lifetime, so an unchanged current frame
        # means an unchanged fold — parked threads (most of a controller's
        # worker pool, blocked in wait()) cost one dict probe per tick
        # instead of a stack walk + string build. Entries pin their frame
        # (one stack per live thread, replaced the tick the thread moves)
        # and are pruned to the currently-live thread set every sweep.
        # Only the sampler thread touches it.
        self._fold_memo: Dict[int, tuple] = {}
        self._started_at: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._started_at = self._clock()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="obs-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _loop(self) -> None:
        # drift-free schedule: aim at absolute deadlines; if a tick falls
        # behind (GIL starvation under load), skip the lost ticks rather
        # than bursting to catch up — a burst IS overhead
        next_t = self._clock() + self.interval
        while not self._stop.wait(max(next_t - self._clock(), 0.0)):
            t0 = self._clock()
            try:
                self.sample_once()
            except Exception:
                pass  # a torn frame walk must never kill the sampler
            busy = self._clock() - t0
            with self._lock:
                self._busy_s += busy
            next_t += self.interval
            now = self._clock()
            if next_t < now:
                next_t = now + self.interval

    # -- sampling -----------------------------------------------------------

    def sample_once(self) -> int:
        """One sweep over every other thread's live stack; returns the
        number of threads sampled. Public so tests drive it deterministically
        without the daemon thread."""
        frames = sys._current_frames()
        own = threading.get_ident()
        active = self._tracer.active_spans() if self._tracer is not None else {}
        sampled = 0
        folds: List[str] = []
        span_names: List[str] = []
        leaves: List[str] = []
        for tid, frame in frames.items():
            if tid == own:
                continue
            memo = self._fold_memo.get(tid)
            if memo is not None and memo[0] is frame:
                stack, leaf = memo[1], memo[2]
            else:
                stack = fold_stack(frame, self.max_depth)
                leaf = _frame_label(frame)
                self._fold_memo[tid] = (frame, stack, leaf)
            folds.append(stack)
            leaves.append(leaf)
            span = active.get(tid)
            if span is not None and getattr(span, "name", None):
                span_names.append(span.name)
            sampled += 1
        for tid in list(self._fold_memo):
            if tid not in frames:
                del self._fold_memo[tid]  # dead thread: drop its pinned stack
        now = self._clock()
        with self._lock:
            if now - self._win_rotated_at > self.window_s / 2:
                self._win_prev = self._win_cur
                self._win_cur = {}
                self._win_rotated_at = now
            for stack in folds:
                self._bump_locked(self._folds, stack)
                self._bump_locked(self._win_cur, stack)
            for leaf in leaves:
                self._bump_locked(self._leaf, leaf)
            for name in span_names:
                self._span_samples[name] = self._span_samples.get(name, 0) + 1
            self.samples += sampled
            self.ticks += 1
        try:
            from karpenter_tpu import metrics

            metrics.TELEMETRY_PROFILE_SAMPLES.inc(sampled)
            metrics.TELEMETRY_PROFILE_OVERHEAD.set(self.overhead_ratio())
        except Exception:
            pass  # trimmed registries
        return sampled

    def _bump_locked(self, d: Dict[str, int], key: str) -> None:
        if key not in d and len(d) >= self.max_folds:
            key = OVERFLOW_KEY
        d[key] = d.get(key, 0) + 1

    # -- readout ------------------------------------------------------------

    def overhead_ratio(self) -> float:
        """Sampler busy-time over wall-time since start — the self-accounted
        cost the <1% bench bar judges (0.0 before the first tick)."""
        if self._started_at is None:
            return 0.0
        elapsed = self._clock() - self._started_at
        if elapsed <= 0:
            return 0.0
        with self._lock:
            busy = self._busy_s
        return busy / elapsed

    def collapsed(self) -> str:
        """The cumulative corpus in collapsed-flamegraph format, one
        ``stack count`` line per distinct stack."""
        with self._lock:
            items = sorted(self._folds.items())
        return "".join(f"{stack} {n}\n" for stack, n in items)

    def top(self, n: int = 20) -> List[Dict[str, Any]]:
        """Top-N frames by SELF time (leaf-sample counts): where the
        interpreter actually was, not which caller contains it."""
        with self._lock:
            total = max(self.samples, 1)
            items = sorted(self._leaf.items(), key=lambda kv: -kv[1])[:n]
        return [
            {
                "frame": frame,
                "self_samples": count,
                "self_pct": round(count / total * 100, 2),
            }
            for frame, count in items
        ]

    def snapshot(self, top_n: int = 20) -> Dict[str, Any]:
        """The JSON /debug/profile body + what the telemetry flusher ships."""
        with self._lock:
            samples, ticks = self.samples, self.ticks
            spans = dict(self._span_samples)
        return {
            "hz": self.hz,
            "samples": samples,
            "ticks": ticks,
            "overhead_ratio": round(self.overhead_ratio(), 6),
            "top": self.top(top_n),
            "span_samples": spans,
        }

    def flight_panel(self) -> Dict[str, Any]:
        """The registered flight-recorder panel: the RECENT window's top
        folds, so a slow-solve incident names the frames hot at the time,
        not the frames hot since boot."""
        with self._lock:
            merged: Dict[str, int] = dict(self._win_prev)
            for stack, n in self._win_cur.items():
                merged[stack] = merged.get(stack, 0) + n
        top = sorted(merged.items(), key=lambda kv: -kv[1])[:10]
        return {
            "window_s": self.window_s,
            "window_samples": sum(merged.values()),
            "top_folds": [{"stack": s, "samples": n} for s, n in top],
        }
