"""The regression sentinel: online performance baselines + change-point
detection over the tracer finish-hook stream.

The paper's north star is a 10k-pod solve under 100ms p99, but a
regression today is only visible when a human runs ``tools/bench_compare``
against checked-in snapshots. Production already emits everything needed
to notice sooner — spans, SLO verdicts, profiles, flight records,
decision records — it just lacks the layer that cross-examines them.
This module is that layer's sensor half:

- **Online baselines.** For every (watched span stage, route/transport,
  shape-class) key the engine learns an EW mean/variance of the span's
  duration (the ``forecast/model.py`` Ewma discipline: residual against
  the pre-update level) plus a short window of recent durations.
- **Change-point detection.** Each finished span's window median is
  compared against the learned level; a median past
  ``level + max(sigma·std, rel_floor·level, abs_floor)`` is a deviation.
  Medians over a small window make the detector a *step* detector — one
  slow outlier cannot trip it, a sustained shift must.
- **Sustained deviation → incident.** ``sustain`` consecutive deviating
  windows hand the triggering span to :class:`~karpenter_tpu.obs.
  incidents.IncidentLog`, which correlates the evidence already lying
  around (flight records, decision ids, profiler folds, state panels)
  under one incident id. After minting, the key re-baselines to the new
  regime and cools down — a persisting regression is ONE incident, not a
  siren.
- **Persistence.** Baselines survive restarts (``--sentinel-dir``,
  flock'd + tmp/rename in the launch-journal discipline) so a restarted
  replica resumes with its learned normals instead of re-learning — and
  never mints a warm-up false incident. A corrupt or unwritable baseline
  file degrades to memory-only with a counted reason
  (``karpenter_sentinel_baselines_total{event=...}``), the decision-ring
  containment contract: observability failures never fail the observed.

Hot path: one frozenset probe + one dict get + one deque append per
finished span, under a short lock; the detector arithmetic runs only on
watched spans. All sentinel work is self-accounted (``overhead_ratio``,
the profiler's discipline) and gated <1% by ``bench.py
--sentinel-overhead-check``.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from karpenter_tpu.obs.trace import Span

logger = logging.getLogger("karpenter.obs")

# the stages whose latency regressions this plane exists to catch: the
# round, the end-to-end solve, the transport leg, and the sidecar's
# device half — enough to tell encode-bound from wire-bound from
# device-bound without hooking every span in the process
DEFAULT_WATCH = (
    "provision.round",
    "solver.solve",
    "solver.wire",
    "sidecar.pack",
    "solve.encode",
    "solve.pack_fetch",
)

# baseline learning / detection knobs (struct-of-defaults so bench and
# tests can tighten them on a live engine without a config plumbing tax)
DEFAULT_ALPHA = 0.3          # EW level/variance smoothing (forecaster's)
DEFAULT_WINDOW = 8           # change-point median window (deque maxlen)
DEFAULT_MIN_EVENTS = 24      # warm-up: no verdicts before this many events
DEFAULT_SIGMA = 4.0          # deviation needs median > level + sigma*std...
DEFAULT_REL_FLOOR = 0.5      # ...and > level * (1 + rel_floor)...
DEFAULT_ABS_FLOOR_S = 0.002  # ...and > level + 2ms (loopback noise floor)
DEFAULT_SUSTAIN = 3          # consecutive deviating windows -> incident
DEFAULT_COOLDOWN_S = 60.0    # per-key quiet period after an incident
DEFAULT_SAVE_INTERVAL_S = 30.0
DEFAULT_KEY_CAP = 256        # baseline table bound (route/shape churn)

BASELINE_FILE = "baselines.json"
BASELINE_VERSION = 1


def _count(event: str) -> None:
    try:
        from karpenter_tpu import metrics

        metrics.SENTINEL_BASELINES.labels(event=event).inc()
    except Exception:
        pass  # trimmed registries


def shape_class(value: Any) -> str:
    """Power-of-two bucket of a batch size: 4000 pods and 4100 pods are
    the same workload shape, 400 and 4000 are not. Non-numeric -> "-"."""
    try:
        n = int(value)
    except (TypeError, ValueError):
        return "-"
    if n <= 0:
        return "0"
    return str(1 << (n - 1).bit_length())


def route_of(span: Span) -> str:
    """The span's route/transport identity: the wire leg keys on its
    transport (stream_shm/stream/unary), the solve on its backend, the
    sidecar on its session — a unary fallback must not pollute the
    streamed path's baseline."""
    attrs = span.attrs
    for k in ("transport", "solver", "backend", "route"):
        v = attrs.get(k)
        if v:
            return str(v)
    if attrs.get("address"):
        return "remote"
    return "-"


class _Baseline:
    """One (stage, route, shape) key's learned normal + recent window."""

    __slots__ = (
        "level", "variance", "observations", "window",
        "deviating", "cooldown_until", "restored",
    )

    def __init__(self, window: int):
        self.level: Optional[float] = None
        self.variance = 0.0
        self.observations = 0
        self.window: deque = deque(maxlen=window)
        self.deviating = 0           # consecutive deviating window checks
        self.cooldown_until = 0.0    # monotonic: no incidents before this
        self.restored = False        # loaded from disk (skips warm-up)

    def update(self, value: float, alpha: float) -> None:
        # the forecaster's Ewma: residual against the PRE-update level so
        # the variance tracks prediction error, not post-hoc fit
        if self.level is None:
            self.level = value
        else:
            residual = value - self.level
            self.variance = (1 - alpha) * self.variance + alpha * residual * residual
            self.level += alpha * residual
        self.observations += 1

    def std(self) -> float:
        return math.sqrt(max(self.variance, 0.0))


class SentinelEngine:
    """Tracer finish-hook (``tracer.add_hook``) + the baseline store.

    ``incidents`` is the :class:`IncidentLog` deviations escalate into;
    ``directory`` ('' = memory-only) persists baselines across restarts.
    """

    def __init__(
        self,
        incidents=None,
        directory: str = "",
        watch=DEFAULT_WATCH,
        alpha: float = DEFAULT_ALPHA,
        window: int = DEFAULT_WINDOW,
        min_events: int = DEFAULT_MIN_EVENTS,
        sigma: float = DEFAULT_SIGMA,
        rel_floor: float = DEFAULT_REL_FLOOR,
        abs_floor_s: float = DEFAULT_ABS_FLOOR_S,
        sustain: int = DEFAULT_SUSTAIN,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        save_interval_s: float = DEFAULT_SAVE_INTERVAL_S,
        key_cap: int = DEFAULT_KEY_CAP,
    ):
        from karpenter_tpu.obs.incidents import IncidentLog

        self.incidents = incidents if incidents is not None else IncidentLog()
        self.directory = directory
        self.watch = frozenset(watch)
        self.alpha = alpha
        self.window = window
        self.min_events = min_events
        self.sigma = sigma
        self.rel_floor = rel_floor
        self.abs_floor_s = abs_floor_s
        self.sustain = sustain
        self.cooldown_s = cooldown_s
        self.save_interval_s = save_interval_s
        self.key_cap = key_cap
        self._lock = threading.Lock()
        # key -> _Baseline; insertion-ordered, oldest key evicted past cap
        self._baselines: Dict[Tuple[str, str, str], _Baseline] = {}
        self._busy_s = 0.0           # guarded-by: self._lock
        self._started_at = time.monotonic()
        self._last_save = time.monotonic()  # guarded-by: self._lock
        # pre-warm the lazy metrics import OUTSIDE the hook: the first
        # span must not get charged ~100ms of prometheus import time in
        # the self-accounted busy window (the <1% gate reads it)
        try:
            from karpenter_tpu import metrics  # noqa: F401
        except Exception:
            pass
        if directory:
            self._load()

    # -- the hook (every finished span lands here) ---------------------------
    def __call__(self, span: Span) -> None:
        if span.name not in self.watch:
            return
        t0 = time.perf_counter()
        try:
            self._observe(span)
        except Exception:
            # the containment contract: the sentinel must never fail the
            # span's owner (trace.py already swallows, but stay honest)
            logger.debug("sentinel observe failed", exc_info=True)
        finally:
            save_due = False
            dt = time.perf_counter() - t0
            with self._lock:
                self._busy_s += dt
                if self.directory and (
                    time.monotonic() - self._last_save >= self.save_interval_s
                ):
                    self._last_save = time.monotonic()
                    save_due = True
            if save_due:
                t1 = time.perf_counter()
                self.save()
                with self._lock:
                    self._busy_s += time.perf_counter() - t1

    def _observe(self, span: Span) -> None:
        key = (span.name, route_of(span), shape_class(
            span.attrs.get("pods", span.attrs.get("batch"))
        ))
        duration = span.duration_s
        trip = None
        with self._lock:
            b = self._baselines.get(key)
            if b is None:
                if len(self._baselines) >= self.key_cap:
                    # churn bound: evict the oldest-inserted key; a live
                    # key re-learns in min_events, a dead one stays gone
                    self._baselines.pop(next(iter(self._baselines)))
                b = self._baselines[key] = _Baseline(self.window)
                _count("learned")
            b.window.append(duration)
            warm = b.observations >= self.min_events
            if not warm:
                b.update(duration, self.alpha)
                return
            level = b.level or 0.0
            threshold = level + max(
                self.sigma * b.std(),
                self.rel_floor * level,
                self.abs_floor_s,
            )
            # gated update: a value past the threshold never feeds the
            # baseline — an un-gated EW level CHASES a step fast enough
            # (alpha 0.3) that the median can never clear the moving
            # threshold and the regression self-absorbs undetected
            if duration <= threshold:
                b.update(duration, self.alpha)
            full = len(b.window) == b.window.maxlen
            med = sorted(b.window)[len(b.window) // 2] if full else 0.0
            if full and med > threshold:
                b.deviating += 1
                now = time.monotonic()
                if b.deviating >= self.sustain and now >= b.cooldown_until:
                    b.cooldown_until = now + self.cooldown_s
                    b.deviating = 0
                    trip = {
                        "observed_s": round(med, 6),
                        "baseline_s": round(level, 6),
                        "baseline_std_s": round(b.std(), 6),
                        "threshold_s": round(threshold, 6),
                        "observations": b.observations,
                    }
                    # re-baseline to the new regime: the incident NAMES
                    # the step; tracking it afterwards is the new normal
                    # (a fix shows up as a fast step back under threshold)
                    b.level = med
                    b.variance = 0.0
                    b.window.clear()
            else:
                b.deviating = 0
        if trip is None:
            return
        stage, route, shape = key
        try:
            from karpenter_tpu import metrics

            metrics.SENTINEL_DEVIATIONS.labels(stage=stage).inc()
        except Exception:
            pass
        self.incidents.deviation(
            stage=stage, route=route, shape=shape, span=span, baseline=trip,
        )

    # -- persistence (launch-journal discipline) -----------------------------
    def _baseline_path(self) -> str:
        return os.path.join(self.directory, BASELINE_FILE)

    def _load(self) -> None:
        path = self._baseline_path()
        try:
            os.makedirs(self.directory, exist_ok=True)
        except OSError as e:
            logger.warning(
                "sentinel dir %s uncreatable (%s); baselines memory-only",
                self.directory, e,
            )
            self.directory = ""
            _count("persist_failed")
            return
        if not os.path.exists(path):
            return
        try:
            with open(path, encoding="utf-8") as f:
                payload = json.load(f)
            if payload.get("version") != BASELINE_VERSION:
                raise ValueError(f"baseline version {payload.get('version')}")
            loaded = 0
            with self._lock:
                for row in payload.get("baselines", [])[: self.key_cap]:
                    key = tuple(row["key"])
                    if len(key) != 3:
                        continue
                    b = _Baseline(self.window)
                    b.level = float(row["level"])
                    b.variance = max(float(row.get("variance", 0.0)), 0.0)
                    b.observations = int(row.get("observations", 0))
                    b.restored = True
                    self._baselines[key] = b
                    loaded += 1
            if loaded:
                _count("loaded")
            logger.info(
                "sentinel restored %d baselines from %s", loaded, path
            )
        except Exception as e:
            # corrupt file: keep running memory-only on a FRESH table —
            # half-loaded baselines would be worse than none — and leave
            # the file for forensics (the next save overwrites it)
            logger.warning(
                "sentinel baseline file %s unreadable (%s); re-learning",
                path, e,
            )
            with self._lock:
                self._baselines.clear()
            _count("corrupt")

    def save(self) -> bool:
        """Persist current baselines (flock + tmp/rename — a concurrent
        replica or a crash mid-write can never leave a torn file). Returns
        False (and degrades to memory-only, counted) on failure."""
        if not self.directory:
            return False
        with self._lock:
            rows = [
                {
                    "key": list(key),
                    "level": b.level,
                    "variance": b.variance,
                    "observations": b.observations,
                }
                for key, b in self._baselines.items()
                if b.level is not None
            ]
        payload = {
            "version": BASELINE_VERSION,
            "saved_at": time.time(),
            "baselines": rows,
        }
        path = self._baseline_path()
        # pid-unique tmp + atomic rename is the torn-file contract; the
        # dir-level flock (telemetry-backend discipline) serializes
        # concurrent savers — replicas sharing the dir AND this process's
        # own hook-vs-shutdown race — with NO threading lock held across
        # the file-lock wait (karplint lock-blocking)
        tmp = f"{path}.{os.getpid()}.tmp"
        lock_fd = -1
        try:
            try:
                import fcntl

                lock_fd = os.open(
                    os.path.join(self.directory, ".sentinel.flock"),
                    os.O_CREAT | os.O_RDWR,
                )
                fcntl.flock(lock_fd, fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass  # flock is advisory belt, not the contract
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(payload, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            finally:
                if lock_fd >= 0:
                    try:
                        import fcntl

                        fcntl.flock(lock_fd, fcntl.LOCK_UN)
                    finally:
                        os.close(lock_fd)
            _count("persisted")
            return True
        except OSError as e:
            # ENOSPC / read-only volume: degrade to memory-only with a
            # counted reason; detection keeps running on what it has
            logger.warning(
                "sentinel baseline write to %s failed (%s); memory-only",
                path, e,
            )
            self.directory = ""
            _count("persist_failed")
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False

    def close(self) -> None:
        """Final persist (runtime stop / sidecar shutdown)."""
        self.save()

    # -- readouts ------------------------------------------------------------
    def overhead_ratio(self) -> float:
        """Self-accounted busy/wall since start (the profiler's measure;
        the ``--sentinel-overhead-check`` <1% gate reads this)."""
        elapsed = time.monotonic() - self._started_at
        if elapsed <= 0:
            return 0.0
        with self._lock:
            return self._busy_s / elapsed

    def baseline_count(self) -> int:
        with self._lock:
            return len(self._baselines)

    def snapshot(self, limit: int = 64) -> Dict[str, Any]:
        """The baseline table (bounded) + engine disposition — the
        ``/debug/incidents`` payload's ``sentinel`` half."""
        with self._lock:
            rows: List[Dict[str, Any]] = []
            for key, b in list(self._baselines.items())[:limit]:
                rows.append({
                    "stage": key[0],
                    "route": key[1],
                    "shape": key[2],
                    "level_s": round(b.level, 6) if b.level is not None else None,
                    "std_s": round(b.std(), 6),
                    "observations": b.observations,
                    "deviating": b.deviating,
                    "restored": b.restored,
                })
        return {
            "baselines": rows,
            "baseline_count": self.baseline_count(),
            "persist_dir": self.directory,
            "overhead_ratio": round(self.overhead_ratio(), 6),
            "watch": sorted(self.watch),
        }

    def panel(self) -> Dict[str, Any]:
        """The ``sentinel`` flight-recorder/state panel: small enough to
        ride every flight record, rich enough to say what the sentinel
        believed when some OTHER plane's incident landed."""
        open_inc = self.incidents.open_summary()
        return {
            "baselines": self.baseline_count(),
            "incidents": self.incidents.count(),
            "open_incident": open_inc,
            "overhead_ratio": round(self.overhead_ratio(), 6),
        }
