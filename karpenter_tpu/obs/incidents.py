"""The correlated incident plane: one id over all the evidence.

When the sentinel (obs/sentinel.py) sees a sustained latency step it does
NOT page with a bare number — it mints a bounded **Incident record** that
correlates everything the process already knows under one incident id:

- the triggering span tree (who was slow, with its children),
- in-window flight records (flight.py's pin-by-incident hook keeps them
  from being pruned out from under the incident, and the triggering span
  is force-recorded so an incident always carries at least one),
- the decision ids whose provisioning rounds fell inside the window
  (the PR-15 audit ring — ``tools/replay_decision.py`` re-solves them),
- the profiler's in-window top folds,
- the full state-panel snapshot (brownout rung, fence, breaker/pool
  disposition, delta-encoder full-re-encode reasons, stream credit
  stalls — whatever panels are registered at mint time).

A regression that keeps deviating ATTACHES to the open incident (one
incident per regime change, not one per window); a later deviation in a
different stage inside the correlation window attaches as an additional
stage — a slow sidecar shows up once, as wire+device, not as a siren of
near-duplicate incidents.

``GET /debug/incidents`` (both health servers, via
``obs.debug_incidents_payload``) lists summaries; ``?id=`` returns the
full record. Bounded summaries ride the member telemetry payload so
``/debug/fleet`` carries a fleet-merged incident index.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

from karpenter_tpu.obs.trace import Span

logger = logging.getLogger("karpenter.obs")

DEFAULT_CAP = 32          # incident records retained (memory ring)
CORRELATE_WINDOW_S = 30.0  # deviations inside this window share one id
DECISION_WINDOW_S = 120.0  # decisions this recent count as in-window
MAX_STAGES = 8             # stages attached to one incident
MAX_DECISIONS = 8
MAX_FLIGHTS = 3
MAX_PROFILE_FOLDS = 10


def _new_id() -> str:
    return "i-" + uuid.uuid4().hex[:16]


class IncidentLog:
    """Bounded incident ring + the evidence-correlation assembly.

    ``recorder`` (a ``kube.events.EventRecorder``) is optional: when set,
    every minted incident also lands as an ``IncidentDetected`` Warning
    event carrying the newest in-window decision id — the operator's path
    from ``kubectl describe`` into ``/debug/incidents``."""

    def __init__(self, cap: int = DEFAULT_CAP, recorder=None, clock=time.time):
        self.cap = cap
        self.recorder = recorder
        self.clock = clock
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=cap)  # guarded-by: self._lock
        self._opened = 0  # guarded-by: self._lock

    # -- the sentinel's escalation entrypoint --------------------------------
    def deviation(
        self,
        stage: str,
        route: str,
        shape: str,
        span: Span,
        baseline: Dict[str, Any],
    ) -> Optional[Dict[str, Any]]:
        """A sustained deviation: attach to the open incident when one is
        inside the correlation window, mint a new record otherwise.
        Never raises — evidence assembly is best-effort by contract."""
        try:
            return self._deviation(stage, route, shape, span, baseline)
        except Exception:
            logger.debug("incident assembly failed", exc_info=True)
            return None

    def _deviation(self, stage, route, shape, span, baseline):
        now = self.clock()
        stage_row = {
            "stage": stage,
            "route": route,
            "shape": shape,
            "trace_id": span.trace_id,
            "at": now,
            **baseline,
        }
        with self._lock:
            open_rec = self._open_locked(now)
            if open_rec is not None:
                if len(open_rec["stages"]) < MAX_STAGES:
                    open_rec["stages"].append(stage_row)
                open_rec["last_deviation_at"] = now
                return open_rec
        return self._mint(stage, span, stage_row, now)

    def _open_locked(self, now: float) -> Optional[Dict[str, Any]]:
        if not self._records:
            return None
        rec = self._records[-1]
        if now - rec.get("last_deviation_at", 0.0) <= CORRELATE_WINDOW_S:
            return rec
        return None

    def _mint(self, stage, span, stage_row, now) -> Dict[str, Any]:
        from karpenter_tpu import obs
        from karpenter_tpu.obs.flight import state_snapshot

        incident_id = _new_id()
        # decision ids whose rounds fell in the window: the replay path
        decisions: List[Dict[str, Any]] = []
        try:
            for s in obs.decision_log().summaries(limit=MAX_DECISIONS):
                if now - s.get("recorded_at", 0.0) <= DECISION_WINDOW_S:
                    decisions.append({
                        "id": s["id"],
                        "recorded_at": s["recorded_at"],
                        "provisioner": s.get("provisioner"),
                        "trace_id": s.get("trace_id"),
                    })
        except Exception:
            pass
        # flight evidence: pin what's already on disk against pruning,
        # and force-record the triggering span so the incident always
        # carries the tree that tripped it even when it was under the
        # flight budget (a 2x step on a 10ms stage is)
        flights: List[Dict[str, Any]] = []
        rec = obs.flight_recorder()
        if rec is not None:
            try:
                path = rec.record(span, extra={"incident_id": incident_id})
                flights = rec.pin_for_incident(incident_id, limit=MAX_FLIGHTS)
                if path and not flights:
                    flights = [{"file": path, "trace_id": span.trace_id}]
            except Exception:
                pass
        profile_top: List[Dict[str, Any]] = []
        prof = obs.profiler()
        if prof is not None:
            try:
                profile_top = prof.flight_panel().get(
                    "top_folds", []
                )[:MAX_PROFILE_FOLDS]
            except Exception:
                pass
        record = {
            "id": incident_id,
            "opened_at": now,
            "last_deviation_at": now,
            "stage": stage,
            "stages": [stage_row],
            "trace_id": span.trace_id,
            "trace": span.to_dict(),
            "decisions": decisions,
            "flights": flights,
            "profile_top": profile_top,
            # the full panel spread: brownout rung, fence, breakers/pool,
            # delta re-encode reasons, stream credit stalls, slo burn...
            "state": state_snapshot(),
        }
        with self._lock:
            self._records.append(record)
            self._opened += 1
        try:
            from karpenter_tpu import metrics

            metrics.SENTINEL_INCIDENTS.labels(stage=stage).inc()
        except Exception:
            pass
        self._emit_event(record)
        logger.warning(
            "sentinel incident %s: %s regressed to %.1fms (baseline %.1fms)",
            incident_id, stage,
            stage_row.get("observed_s", 0.0) * 1e3,
            stage_row.get("baseline_s", 0.0) * 1e3,
        )
        return record

    def _emit_event(self, record: Dict[str, Any]) -> None:
        if self.recorder is None:
            return
        decision_id = (
            record["decisions"][0]["id"] if record["decisions"] else ""
        )
        stage_row = record["stages"][0]
        try:
            self.recorder.event(
                "Provisioner",
                str(stage_row.get("route") or "default"),
                reason="IncidentDetected",
                message=(
                    f"performance incident {record['id']}: stage "
                    f"{record['stage']} regressed to "
                    f"{stage_row.get('observed_s', 0.0) * 1e3:.1f}ms "
                    f"(baseline {stage_row.get('baseline_s', 0.0) * 1e3:.1f}ms)"
                    " — see GET /debug/incidents"
                ),
                type="Warning",
                decision_id=decision_id,
            )
        except Exception:
            logger.debug("incident event emit failed", exc_info=True)

    # -- readouts ------------------------------------------------------------
    def count(self) -> int:
        with self._lock:
            return self._opened

    def open_summary(self) -> Optional[Dict[str, Any]]:
        """Id + stage of the incident still inside its correlation window
        (None when quiet) — the ``sentinel`` state panel's headline."""
        with self._lock:
            rec = self._open_locked(self.clock())
            if rec is None:
                return None
            return {"id": rec["id"], "stage": rec["stage"]}

    def get(self, incident_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            for rec in self._records:
                if rec["id"] == incident_id:
                    return dict(rec)
        return None

    def recent(self, limit: int = 20) -> List[Dict[str, Any]]:
        """Newest-first full records (the ``?id=`` detail is one of
        these; the default listing serves :meth:`summaries`)."""
        with self._lock:
            records = list(self._records)
        records.reverse()
        return [dict(r) for r in records[:limit]]

    def summaries(self, limit: int = 8) -> List[Dict[str, Any]]:
        """The bounded per-member index the telemetry plane flushes —
        ``/debug/fleet`` merges these across members, and a dead
        replica's incidents survive through them."""
        out = []
        for r in self.recent(limit=limit):
            out.append({
                "id": r["id"],
                "opened_at": r["opened_at"],
                "stage": r["stage"],
                "stages": [
                    {k: s.get(k) for k in (
                        "stage", "route", "shape", "observed_s", "baseline_s"
                    )}
                    for s in r["stages"]
                ],
                "trace_id": r["trace_id"],
                "decision_ids": [d["id"] for d in r["decisions"]],
                "flight_count": len(r["flights"]),
            })
        return out

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
