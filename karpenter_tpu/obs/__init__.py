"""karpenter_tpu.obs — end-to-end provisioning traces.

Public surface:

- ``tracer()`` — the process-default :class:`Tracer` (ring exporter
  attached); ``with obs.tracer().span("name") as sp:`` is the ONE way to
  open a span (karplint ``span-closed``).
- ``set_enabled(bool)`` — kill switch (bench ``--no-trace``).
- ``configure_flight(dir, budget_s)`` — install the slow-solve flight
  recorder on the default tracer; ``flight_recorder()`` reads it back.
- ``register_state(name, fn)`` — contribute a state panel to future
  flight records.
- ``to_traceparent`` / ``from_traceparent`` — the cross-process id form
  (HTTP header, node annotation, v3 wire trailer).
- ``configure_profiler(hz)`` — the always-on sampling profiler
  (obs/profiler.py); ``profiler()`` reads it back.
- ``configure_telemetry(...)`` — the fleet telemetry plane
  (obs/collector.py): periodic flush + cross-process collection/stitch;
  ``telemetry()`` reads it back.
- ``configure_sentinel(...)`` — the regression sentinel
  (obs/sentinel.py): online latency baselines + change-point detection
  escalating into the correlated incident plane (obs/incidents.py);
  ``sentinel()`` reads it back, ``GET /debug/incidents`` serves it.
- ``debug_*_payload`` helpers — the ONE body builder per ``/debug/*``
  endpoint, shared by the controller and sidecar health servers (karplint
  ``debug-endpoint`` enforces that handlers route through these).

Never import this package from jit/vmap/pallas-reachable solver code —
karplint's ``span-closed`` tracer-safety check enforces it (a host-side
span call inside traced code would serialize the device pipeline).
"""

from __future__ import annotations

import threading
from typing import Optional

from karpenter_tpu.obs.export import (  # noqa: F401
    RingExporter,
    critical_path,
    overlapping_pairs,
    spans_named,
)
from karpenter_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    register_state,
    state_snapshot,
    unregister_state,
)
from karpenter_tpu.obs.collector import (  # noqa: F401
    TelemetryPlane,
    stitch,
    wire_attribution,
)
from karpenter_tpu.obs.decisions import DecisionLog  # noqa: F401
from karpenter_tpu.obs.incidents import IncidentLog  # noqa: F401
from karpenter_tpu.obs.profiler import SamplingProfiler  # noqa: F401
from karpenter_tpu.obs.sentinel import SentinelEngine  # noqa: F401
from karpenter_tpu.obs.slo import (  # noqa: F401
    DEFAULT_OBJECTIVES,
    SIDECAR_OBJECTIVES,
    Histogram,
    SloEngine,
    load_objectives,
)
from karpenter_tpu.obs.trace import (  # noqa: F401
    TRACE_ANNOTATION,
    Span,
    SpanContext,
    Tracer,
    from_traceparent,
    to_traceparent,
)

_lock = threading.Lock()
_tracer = Tracer(exporter=RingExporter())
_flight: Optional[FlightRecorder] = None  # guarded-by: _lock


def tracer() -> Tracer:
    return _tracer


def exporter() -> RingExporter:
    return _tracer.exporter


def set_enabled(enabled: bool) -> None:
    _tracer.enabled = bool(enabled)


def enabled() -> bool:
    return _tracer.enabled


def configure_flight(
    directory: str,
    budget_s: Optional[float] = None,
    cap: Optional[int] = None,
    watch=None,
) -> FlightRecorder:
    """Install (or replace) the flight recorder on the default tracer."""
    global _flight
    kwargs = {}
    if budget_s is not None:
        kwargs["budget_s"] = budget_s
    if cap is not None:
        kwargs["cap"] = cap
    if watch is not None:
        kwargs["watch"] = watch
    rec = FlightRecorder(directory, **kwargs)
    with _lock:
        if _flight is not None:
            _tracer.remove_hook(_flight)
        _flight = rec
    _tracer.add_hook(rec)
    return rec


def flight_recorder() -> Optional[FlightRecorder]:
    with _lock:
        return _flight


_slo: Optional[SloEngine] = None  # guarded-by: _lock


def configure_slo(
    objectives=None,
    window_s: float = 300.0,
    clock=None,
    slow_factor: Optional[int] = None,
) -> SloEngine:
    """Install (or replace) the online SLO engine on the default tracer:
    a span finish-hook plus the ``slo`` flight-recorder state panel, so
    every slow-solve record snapshots which objectives were burning."""
    global _slo
    kwargs = {}
    if clock is not None:
        kwargs["clock"] = clock
    if slow_factor is not None:
        kwargs["slow_factor"] = slow_factor
    eng = SloEngine(objectives=objectives, window_s=window_s, **kwargs)
    with _lock:
        if _slo is not None:
            _tracer.remove_hook(_slo)
        _slo = eng
    _tracer.add_hook(eng)
    register_state("slo", eng.burning_panel)
    return eng


def slo_engine() -> Optional[SloEngine]:
    with _lock:
        return _slo


def shutdown_slo(engine: Optional[SloEngine] = None) -> None:
    """Detach the engine (hook + flight panel). Pass the engine you
    installed to make teardown ownership-checked: a stopped replica must
    not tear down an engine a LATER configure_slo installed for a runtime
    still running in this process. ``None`` detaches unconditionally
    (reset_for_tests)."""
    global _slo
    with _lock:
        if engine is not None and _slo is not engine:
            return  # someone else's engine is current — not ours to kill
        if _slo is not None:
            _tracer.remove_hook(_slo)
        _slo = None
    unregister_state("slo")


def slo_snapshot() -> dict:
    """The ``/debug/slo`` payload ({} while no engine is configured)."""
    eng = slo_engine()
    return eng.snapshot() if eng is not None else {}


def debug_traces_payload(query: str = "") -> dict:
    """The ``GET /debug/traces`` body, shared by both health servers.
    ``query`` is the raw URL query string; ``?limit=`` bounds the tree
    count (default 50), ``?name=`` keeps only trees containing a span of
    that name — one trace family instead of a 256-tree payload — and
    ``?trace_id=`` is the exact lookup: a flight record's or SLO
    exemplar's trace id is one request away from its full tree."""
    from urllib.parse import parse_qs

    q = parse_qs(query or "")
    limit = 50
    try:
        limit = max(int(q["limit"][0]), 0)
    except (KeyError, ValueError, IndexError):
        pass
    name = (q.get("name") or [None])[0] or None
    trace_id = (q.get("trace_id") or [None])[0] or None
    exp = exporter()
    return {
        "traces": exp.snapshot(limit=limit, name=name, trace_id=trace_id),
        "stats": exp.stats(),
    }


# -- the sampling profiler (obs/profiler.py) ---------------------------------

_profiler: Optional[SamplingProfiler] = None  # guarded-by: _lock


def configure_profiler(hz: Optional[float] = None) -> SamplingProfiler:
    """Install (and start) the process-wide sampling profiler; replaces a
    previous one. Also registers the ``profile`` flight-recorder panel so
    every over-budget incident names the in-window hot frames."""
    global _profiler
    kwargs = {}
    if hz is not None:
        kwargs["hz"] = hz
    prof = SamplingProfiler(tracer=_tracer, **kwargs)
    with _lock:
        old, _profiler = _profiler, prof
    if old is not None:
        old.stop()
    prof.start()
    register_state("profile", prof.flight_panel)
    return prof


def profiler() -> Optional[SamplingProfiler]:
    with _lock:
        return _profiler


def shutdown_profiler(prof: Optional[SamplingProfiler] = None) -> None:
    """Stop and detach (ownership-checked like ``shutdown_slo``; ``None``
    detaches unconditionally — reset_for_tests)."""
    global _profiler
    with _lock:
        if prof is not None and _profiler is not prof:
            return
        old, _profiler = _profiler, None
    if old is not None:
        old.stop()
    unregister_state("profile")


# -- the arrival-rate forecaster (forecast/model.py) -------------------------

_forecaster = None  # guarded-by: _lock


def configure_forecast(
    bucket_s: Optional[float] = None,
    model: Optional[str] = None,
    alpha: Optional[float] = None,
    season_len: Optional[int] = None,
    band_sigma: Optional[float] = None,
    default_horizon_s: Optional[float] = None,
    clock=None,
):
    """Install (or replace) the arrival-rate forecaster on the default
    tracer: a span finish-hook over ``provision.round`` (admission
    counts) and ``node.ready`` (the launch-to-ready horizon), plus the
    ``forecast`` flight-recorder state panel so every slow-solve record
    snapshots what the forecaster believed at the time."""
    from karpenter_tpu.forecast.model import ArrivalForecaster

    global _forecaster
    kwargs = {}
    if bucket_s is not None:
        kwargs["bucket_s"] = bucket_s
    if model is not None:
        kwargs["model"] = model
    if alpha is not None:
        kwargs["alpha"] = alpha
    if season_len is not None:
        kwargs["season_len"] = season_len
    if band_sigma is not None:
        kwargs["band_sigma"] = band_sigma
    if default_horizon_s is not None:
        kwargs["default_horizon_s"] = default_horizon_s
    if clock is not None:
        kwargs["clock"] = clock
    eng = ArrivalForecaster(**kwargs)
    with _lock:
        if _forecaster is not None:
            _tracer.remove_hook(_forecaster)
        _forecaster = eng
    _tracer.add_hook(eng)
    register_state("forecast", eng.panel)
    return eng


def forecaster():
    with _lock:
        return _forecaster


def shutdown_forecast(engine=None) -> None:
    """Detach the forecaster (hook + flight panel). Ownership-checked like
    ``shutdown_slo``: pass the engine you installed so a stopped replica
    cannot tear down a LATER configure's engine; ``None`` detaches
    unconditionally (reset_for_tests)."""
    global _forecaster
    with _lock:
        if engine is not None and _forecaster is not engine:
            return  # someone else's engine is current — not ours to kill
        if _forecaster is not None:
            _tracer.remove_hook(_forecaster)
        _forecaster = None
    unregister_state("forecast")


# -- the regression sentinel (obs/sentinel.py + obs/incidents.py) ------------

_sentinel: Optional[SentinelEngine] = None  # guarded-by: _lock


def configure_sentinel(
    directory: str = "",
    recorder=None,
    incident_cap: Optional[int] = None,
    **tuning,
) -> SentinelEngine:
    """Install (or replace) the regression sentinel on the default tracer:
    a span finish-hook learning per-(stage, route, shape) latency
    baselines (persisted under ``directory`` when set), a change-point
    detector, and the correlated incident plane behind
    ``GET /debug/incidents``. ``recorder`` (an EventRecorder) makes every
    minted incident land as an ``IncidentDetected`` Warning event.
    ``tuning`` passes through SentinelEngine knobs (window, min_events,
    sustain, ...) — bench and tests tighten warm-up there."""
    inc_kwargs = {"recorder": recorder}
    if incident_cap is not None:
        inc_kwargs["cap"] = incident_cap
    eng = SentinelEngine(
        incidents=IncidentLog(**inc_kwargs), directory=directory, **tuning
    )
    global _sentinel
    with _lock:
        if _sentinel is not None:
            _tracer.remove_hook(_sentinel)
        _sentinel = eng
    _tracer.add_hook(eng)
    register_state("sentinel", eng.panel)
    return eng


def sentinel() -> Optional[SentinelEngine]:
    with _lock:
        return _sentinel


def shutdown_sentinel(engine: Optional[SentinelEngine] = None) -> None:
    """Detach (hook + state panel) and final-persist the baselines.
    Ownership-checked like ``shutdown_slo``: pass the engine you installed
    so a stopped replica cannot tear down a LATER configure's engine;
    ``None`` detaches unconditionally (reset_for_tests)."""
    global _sentinel
    with _lock:
        if engine is not None and _sentinel is not engine:
            return  # someone else's engine is current — not ours to kill
        if _sentinel is not None:
            _tracer.remove_hook(_sentinel)
        old, _sentinel = _sentinel, None
    if old is not None:
        old.close()
    unregister_state("sentinel")


# -- the decision audit log (obs/decisions.py) -------------------------------

# memory-only default: /debug/decisions and /debug/explain answer from the
# first round onward even when no --decision-dir is configured
_decisions = DecisionLog()  # guarded-by: _lock (replacement only)


def decision_log() -> DecisionLog:
    with _lock:
        return _decisions


def configure_decisions(
    directory: str = "",
    cap: Optional[int] = None,
    write_interval: Optional[float] = None,
) -> DecisionLog:
    """Install (or replace) the process decision log — an on-disk capped
    ring under ``directory`` ('' keeps memory-only), the flight-recorder
    discipline (best-effort async writes, evictions counted,
    interval-thinned persistence)."""
    global _decisions
    kwargs = {}
    if cap is not None:
        kwargs["cap"] = cap
    if write_interval is not None:
        kwargs["write_interval"] = write_interval
    log = DecisionLog(directory=directory, **kwargs)
    with _lock:
        old, _decisions = _decisions, log
    # stop the replaced log's writer thread (it drains, then exits) — a
    # reconfigure must not strand an immortal thread pinning the old ring
    old.close()
    return log


# -- the fleet telemetry plane (obs/collector.py) ----------------------------

_telemetry: Optional[TelemetryPlane] = None  # guarded-by: _lock


def configure_telemetry(
    identity: Optional[str] = None,
    role: str = "controller",
    directory: str = "",
    peers=(),
    flush_interval: Optional[float] = None,
) -> TelemetryPlane:
    """Install (and start) this process's telemetry plane: periodic member
    flushes to the shared ``directory`` (when set) plus a collector over
    the directory and/or HTTP ``peers`` — ``GET /debug/fleet`` serves its
    aggregate. Replaces a previous plane."""
    import os as _os

    global _telemetry
    kwargs = {}
    if flush_interval is not None:
        kwargs["flush_interval"] = flush_interval
    plane = TelemetryPlane(
        identity=identity or f"{_os.uname().nodename}-{_os.getpid()}",
        role=role,
        directory=directory,
        peers=peers,
        **kwargs,
    )
    with _lock:
        old, _telemetry = _telemetry, plane
    if old is not None:
        old.stop()
    plane.start()
    return plane


def telemetry() -> Optional[TelemetryPlane]:
    with _lock:
        return _telemetry


def shutdown_telemetry(plane: Optional[TelemetryPlane] = None) -> None:
    """Stop and detach (ownership-checked; ``None`` detaches
    unconditionally — reset_for_tests)."""
    global _telemetry
    with _lock:
        if plane is not None and _telemetry is not plane:
            return
        old, _telemetry = _telemetry, None
    if old is not None:
        old.stop()


# -- shared /debug payload builders ------------------------------------------
# One builder per endpoint, used by BOTH health servers (main.py and
# solver/service.py) — karplint's `debug-endpoint` rule keeps any new
# handler from re-growing a private copy (the controller/sidecar parity
# drift the PR-8 filtering fix had to hand-patch).


def debug_slo_payload(query: str = "") -> dict:
    """``GET /debug/slo``: live verdicts plus the mergeable histogram form
    (the ``histograms`` key is what HTTP-pull telemetry scrapes)."""
    eng = slo_engine()
    return {
        "slo": eng.snapshot() if eng is not None else {},
        "histograms": eng.histogram_snapshot() if eng is not None else {},
    }


def debug_flight_payload(query: str = "") -> dict:
    """``GET /debug/flight``: recent slow-span incident records."""
    rec = flight_recorder()
    return {"records": rec.recent() if rec is not None else []}


def debug_fleet_payload(query: str = "") -> dict:
    """``GET /debug/fleet``: member inventory with staleness, fleet-merged
    SLO verdicts, stitched-trace index ({} until telemetry is configured)."""
    plane = telemetry()
    return {"fleet": plane.fleet_payload() if plane is not None else {}}


def debug_decisions_payload(query: str = "") -> dict:
    """``GET /debug/decisions``: the newest decision records (the audit
    log behind every provisioning round). ``?limit=`` bounds the count
    (default 20), ``?provisioner=`` filters to one provisioner."""
    from urllib.parse import parse_qs

    q = parse_qs(query or "")
    limit = 20
    try:
        limit = max(int(q["limit"][0]), 0)
    except (KeyError, ValueError, IndexError):
        pass
    provisioner = (q.get("provisioner") or [None])[0] or None
    return {
        "decisions": decision_log().recent(limit=limit, provisioner=provisioner)
    }


def debug_explain_payload(query: str = "") -> dict:
    """``GET /debug/explain?pod=<name>``: the newest decision's verdict
    for that pod — the per-candidate elimination breakdown when it failed
    placement, the chosen instance type when it placed, null when no
    recorded decision mentions it."""
    from urllib.parse import parse_qs

    q = parse_qs(query or "")
    pod = (q.get("pod") or [None])[0] or ""
    return {
        "pod": pod,
        "explain": decision_log().explain(pod) if pod else None,
    }


def debug_incidents_payload(query: str = "") -> dict:
    """``GET /debug/incidents``: the sentinel's correlated incident
    records plus its baseline disposition. ``?id=`` returns one FULL
    record (span tree, pinned flight records, decision ids, profiler
    folds, state panels); the default listing serves bounded summaries
    (``?limit=`` bounds the count, default 20). ({} halves while no
    sentinel is configured.)"""
    from urllib.parse import parse_qs

    q = parse_qs(query or "")
    eng = sentinel()
    if eng is None:
        return {"incidents": [], "sentinel": {}}
    incident_id = (q.get("id") or [None])[0] or None
    if incident_id:
        return {
            "incident": eng.incidents.get(incident_id),
            "sentinel": eng.snapshot(),
        }
    limit = 20
    try:
        limit = max(int(q["limit"][0]), 0)
    except (KeyError, ValueError, IndexError):
        pass
    return {
        "incidents": eng.incidents.summaries(limit=limit),
        "sentinel": eng.snapshot(),
    }


def debug_forecast_payload(query: str = "") -> dict:
    """``GET /debug/forecast``: per-provisioner arrival predictions, the
    measured launch-to-ready horizon, and the model parameters ({} while
    no forecaster is configured)."""
    eng = forecaster()
    return {"forecast": eng.snapshot() if eng is not None else {}}


def debug_profile_payload(query: str = ""):
    """``GET /debug/profile`` → ``(content_type, body_bytes)``. Default is
    the top-N self-time JSON; ``?format=collapsed`` returns the raw
    collapsed-flamegraph corpus as text (pipe it into any renderer)."""
    import json as _json
    from urllib.parse import parse_qs

    q = parse_qs(query or "")
    prof = profiler()
    if (q.get("format") or [""])[0] == "collapsed":
        body = prof.collapsed() if prof is not None else ""
        return "text/plain", body.encode()
    payload = {
        "profile": ({"enabled": False} if prof is None
                    else {"enabled": True, **prof.snapshot()})
    }
    return "application/json", _json.dumps(payload).encode()


def reset_for_tests() -> None:
    """Drop collected traces and detach any flight recorder / SLO engine /
    profiler / telemetry plane / decision log."""
    global _flight, _decisions
    with _lock:
        if _flight is not None:
            _tracer.remove_hook(_flight)
        _flight = None
        old_decisions, _decisions = _decisions, DecisionLog()
    old_decisions.close()
    shutdown_forecast()
    shutdown_slo()
    shutdown_sentinel()
    shutdown_profiler()
    shutdown_telemetry()
    from karpenter_tpu.obs import decisions as _dec

    _dec.set_enabled(None)
    _tracer.exporter.clear()
    _tracer.enabled = True
