"""karpenter_tpu.obs — end-to-end provisioning traces.

Public surface:

- ``tracer()`` — the process-default :class:`Tracer` (ring exporter
  attached); ``with obs.tracer().span("name") as sp:`` is the ONE way to
  open a span (karplint ``span-closed``).
- ``set_enabled(bool)`` — kill switch (bench ``--no-trace``).
- ``configure_flight(dir, budget_s)`` — install the slow-solve flight
  recorder on the default tracer; ``flight_recorder()`` reads it back.
- ``register_state(name, fn)`` — contribute a state panel to future
  flight records.
- ``to_traceparent`` / ``from_traceparent`` — the cross-process id form
  (HTTP header, node annotation, v3 wire trailer).

Never import this package from jit/vmap/pallas-reachable solver code —
karplint's ``span-closed`` tracer-safety check enforces it (a host-side
span call inside traced code would serialize the device pipeline).
"""

from __future__ import annotations

import threading
from typing import Optional

from karpenter_tpu.obs.export import (  # noqa: F401
    RingExporter,
    critical_path,
    overlapping_pairs,
    spans_named,
)
from karpenter_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    register_state,
    state_snapshot,
    unregister_state,
)
from karpenter_tpu.obs.slo import (  # noqa: F401
    DEFAULT_OBJECTIVES,
    SIDECAR_OBJECTIVES,
    SloEngine,
    load_objectives,
)
from karpenter_tpu.obs.trace import (  # noqa: F401
    TRACE_ANNOTATION,
    Span,
    SpanContext,
    Tracer,
    from_traceparent,
    to_traceparent,
)

_lock = threading.Lock()
_tracer = Tracer(exporter=RingExporter())
_flight: Optional[FlightRecorder] = None  # guarded-by: _lock


def tracer() -> Tracer:
    return _tracer


def exporter() -> RingExporter:
    return _tracer.exporter


def set_enabled(enabled: bool) -> None:
    _tracer.enabled = bool(enabled)


def enabled() -> bool:
    return _tracer.enabled


def configure_flight(
    directory: str,
    budget_s: Optional[float] = None,
    cap: Optional[int] = None,
    watch=None,
) -> FlightRecorder:
    """Install (or replace) the flight recorder on the default tracer."""
    global _flight
    kwargs = {}
    if budget_s is not None:
        kwargs["budget_s"] = budget_s
    if cap is not None:
        kwargs["cap"] = cap
    if watch is not None:
        kwargs["watch"] = watch
    rec = FlightRecorder(directory, **kwargs)
    with _lock:
        if _flight is not None:
            _tracer.remove_hook(_flight)
        _flight = rec
    _tracer.add_hook(rec)
    return rec


def flight_recorder() -> Optional[FlightRecorder]:
    with _lock:
        return _flight


_slo: Optional[SloEngine] = None  # guarded-by: _lock


def configure_slo(
    objectives=None,
    window_s: float = 300.0,
    clock=None,
    slow_factor: Optional[int] = None,
) -> SloEngine:
    """Install (or replace) the online SLO engine on the default tracer:
    a span finish-hook plus the ``slo`` flight-recorder state panel, so
    every slow-solve record snapshots which objectives were burning."""
    global _slo
    kwargs = {}
    if clock is not None:
        kwargs["clock"] = clock
    if slow_factor is not None:
        kwargs["slow_factor"] = slow_factor
    eng = SloEngine(objectives=objectives, window_s=window_s, **kwargs)
    with _lock:
        if _slo is not None:
            _tracer.remove_hook(_slo)
        _slo = eng
    _tracer.add_hook(eng)
    register_state("slo", eng.burning_panel)
    return eng


def slo_engine() -> Optional[SloEngine]:
    with _lock:
        return _slo


def shutdown_slo(engine: Optional[SloEngine] = None) -> None:
    """Detach the engine (hook + flight panel). Pass the engine you
    installed to make teardown ownership-checked: a stopped replica must
    not tear down an engine a LATER configure_slo installed for a runtime
    still running in this process. ``None`` detaches unconditionally
    (reset_for_tests)."""
    global _slo
    with _lock:
        if engine is not None and _slo is not engine:
            return  # someone else's engine is current — not ours to kill
        if _slo is not None:
            _tracer.remove_hook(_slo)
        _slo = None
    unregister_state("slo")


def slo_snapshot() -> dict:
    """The ``/debug/slo`` payload ({} while no engine is configured)."""
    eng = slo_engine()
    return eng.snapshot() if eng is not None else {}


def debug_traces_payload(query: str = "") -> dict:
    """The ``GET /debug/traces`` body, shared by both health servers.
    ``query`` is the raw URL query string; ``?limit=`` bounds the tree
    count (default 50) and ``?name=`` keeps only trees containing a span
    of that name — one trace family instead of a 256-tree payload."""
    from urllib.parse import parse_qs

    q = parse_qs(query or "")
    limit = 50
    try:
        limit = max(int(q["limit"][0]), 0)
    except (KeyError, ValueError, IndexError):
        pass
    name = (q.get("name") or [None])[0] or None
    exp = exporter()
    return {
        "traces": exp.snapshot(limit=limit, name=name),
        "stats": exp.stats(),
    }


def reset_for_tests() -> None:
    """Drop collected traces and detach any flight recorder / SLO engine."""
    global _flight
    with _lock:
        if _flight is not None:
            _tracer.remove_hook(_flight)
        _flight = None
    shutdown_slo()
    _tracer.exporter.clear()
    _tracer.enabled = True
