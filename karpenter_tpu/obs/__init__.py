"""karpenter_tpu.obs — end-to-end provisioning traces.

Public surface:

- ``tracer()`` — the process-default :class:`Tracer` (ring exporter
  attached); ``with obs.tracer().span("name") as sp:`` is the ONE way to
  open a span (karplint ``span-closed``).
- ``set_enabled(bool)`` — kill switch (bench ``--no-trace``).
- ``configure_flight(dir, budget_s)`` — install the slow-solve flight
  recorder on the default tracer; ``flight_recorder()`` reads it back.
- ``register_state(name, fn)`` — contribute a state panel to future
  flight records.
- ``to_traceparent`` / ``from_traceparent`` — the cross-process id form
  (HTTP header, node annotation, v3 wire trailer).

Never import this package from jit/vmap/pallas-reachable solver code —
karplint's ``span-closed`` tracer-safety check enforces it (a host-side
span call inside traced code would serialize the device pipeline).
"""

from __future__ import annotations

import threading
from typing import Optional

from karpenter_tpu.obs.export import (  # noqa: F401
    RingExporter,
    critical_path,
    overlapping_pairs,
    spans_named,
)
from karpenter_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    register_state,
    state_snapshot,
)
from karpenter_tpu.obs.trace import (  # noqa: F401
    TRACE_ANNOTATION,
    Span,
    SpanContext,
    Tracer,
    from_traceparent,
    to_traceparent,
)

_lock = threading.Lock()
_tracer = Tracer(exporter=RingExporter())
_flight: Optional[FlightRecorder] = None  # guarded-by: _lock


def tracer() -> Tracer:
    return _tracer


def exporter() -> RingExporter:
    return _tracer.exporter


def set_enabled(enabled: bool) -> None:
    _tracer.enabled = bool(enabled)


def enabled() -> bool:
    return _tracer.enabled


def configure_flight(
    directory: str,
    budget_s: Optional[float] = None,
    cap: Optional[int] = None,
    watch=None,
) -> FlightRecorder:
    """Install (or replace) the flight recorder on the default tracer."""
    global _flight
    kwargs = {}
    if budget_s is not None:
        kwargs["budget_s"] = budget_s
    if cap is not None:
        kwargs["cap"] = cap
    if watch is not None:
        kwargs["watch"] = watch
    rec = FlightRecorder(directory, **kwargs)
    with _lock:
        if _flight is not None:
            _tracer.remove_hook(_flight)
        _flight = rec
    _tracer.add_hook(rec)
    return rec


def flight_recorder() -> Optional[FlightRecorder]:
    with _lock:
        return _flight


def reset_for_tests() -> None:
    """Drop collected traces and detach any flight recorder."""
    global _flight
    with _lock:
        if _flight is not None:
            _tracer.remove_hook(_flight)
        _flight = None
    _tracer.exporter.clear()
    _tracer.enabled = True
