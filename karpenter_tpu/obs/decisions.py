"""The decision audit log: every provisioning round, recorded and replayable.

PRs 5/8/12 explain how long things took; nothing explained WHAT was decided
and why. This module is the flight-recorder twin for decisions:

- :meth:`DecisionLog.record_round` turns one provisioning round (pods
  considered, the chosen packing, the solver context the scheduler
  captured, brownout/fence state at decision time) into a bounded
  ``DecisionRecord``: per-pod elimination attribution for the pods the
  solve left unplaced (``solver/explain.py`` — cheap mask reductions, OFF
  the hot path), the route/transport/session provenance, and — when an
  on-disk ring is configured — a compressed replay blob carrying the exact
  kernel tensors so ``tools/replay_decision.py`` can re-solve the decision
  offline on the native packer and diff it (the PR-10 canary's forensic
  twin).

- the ring is flight-recorder-shaped (``--decision-dir``, capped,
  lexicographic filename = recency): record writes are BEST-EFFORT — a
  full or read-only directory never fails a reconcile round (drops count
  on ``karpenter_decisions_dropped_total{reason="write_failed"}``), and
  pruning counts evictions (``reason="evicted"``). An in-memory deque
  (bounded) always backs ``GET /debug/decisions`` and
  ``GET /debug/explain?pod=`` even with no directory configured.

- the unschedulable tracker closes the loop to Kubernetes: a pod that
  fails selection/admission or solver placement for N CONSECUTIVE rounds
  gets a ``PodUnschedulable`` Warning event carrying the top elimination
  reason, with the decision id in the ``karpenter.sh/decision-id``
  annotation (karplint ``event-decision-id``). A round that places the pod
  resets its streak.

Member payloads (obs/collector.py) ship recent decision summaries, so a
dead replica's decisions survive it in ``GET /debug/fleet``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger("karpenter.obs")

DEFAULT_CAP = 64  # on-disk ring size (the flight-recorder default)
DEFAULT_MEMORY_CAP = 256  # in-memory records backing the debug endpoints
DEFAULT_EVENT_ROUNDS = 3  # consecutive failures before PodUnschedulable
# per-record bounds: counts stay complete, listings are capped
MAX_POD_KEYS = 200
MAX_PACKING_NODES = 100
MAX_NODE_POD_KEYS = 50
MAX_UNSCHEDULABLE = 50

# tracker cap: a pathological churn of never-again-seen pods must not grow
# the failure table without bound (oldest-updated evicts first)
MAX_TRACKED_PODS = 4096

# a pod mid-failure-streak reuses its cached verdict; every this-many
# records the round re-attributes everything fresh (catalog/constraint
# drift can change WHY a pod is stuck even while it stays stuck)
VERDICT_REFRESH_ROUNDS = 32

# failure-streak entries not bumped within this window expire: a stuck
# pod that gets DELETED never re-appears in a batch to reset its streak,
# and without expiry it would pin the unschedulable gauge (and its event
# emission) forever
STREAK_TTL_S = 600.0

# async write queue depth: disk persistence (incl. the replay-tensor
# serialization) runs on ONE daemon writer thread so the hot provisioning
# round pays only the record build + an enqueue — the <1% explain bar.
# A full queue DROPS the newest write (counted), never blocks the round.
MAX_WRITE_QUEUE = 8

# disk-persistence thinning: back-to-back rounds would churn the capped
# ring (64 records at 100 rounds/sec = a sub-second window) and keep the
# writer thread competing for the GIL against live solves, so at most one
# record per interval lands on disk. Every record ALWAYS lands in the
# in-memory ring; thinning trades disk history density, not audit truth.
DEFAULT_WRITE_INTERVAL_S = 1.0

_enabled_lock = threading.Lock()
_enabled: Optional[bool] = None  # guarded-by: _enabled_lock


def enabled() -> bool:
    """Is decision recording + attribution on? Defaults to the
    ``KARPENTER_EXPLAIN`` env twin (true unless explicitly disabled) —
    bench's ``--no-explain`` leg and the overhead gate flip it."""
    global _enabled
    with _enabled_lock:
        if _enabled is None:
            from karpenter_tpu.options import env_bool

            _enabled = env_bool("KARPENTER_EXPLAIN", default=True)
        return _enabled


def set_enabled(value: Optional[bool]) -> None:
    """Override (``None`` = re-read the env twin on next check)."""
    global _enabled
    with _enabled_lock:
        _enabled = value if value is None else bool(value)


PACK_ARG_NAMES = (
    "pod_valid", "pod_open_sig", "pod_core", "pod_host",
    "pod_host_in_base", "pod_open_host", "pod_req", "join_table",
    "frontiers", "daemon",
)


def _replay_arrays(batch, assignment, n_max: int) -> Dict[str, np.ndarray]:
    """The exact kernel inputs (``EncodedBatch.pack_args`` order) plus the
    served assignment and node-table size — everything the native packer
    needs to re-solve this decision offline. Written as an ``.npz``
    sidecar (C-speed serialization — the writer thread shares the GIL
    with live solves). The dense ``pod_req`` matrix ships in its compact
    transfer form (unique request vectors + per-pod ids — a 10k batch has
    dozens of distinct shapes, not 10k rows); replay re-gathers the
    identical matrix."""
    arrays = {
        n: np.asarray(a) for n, a in zip(PACK_ARG_NAMES, batch.pack_args())
    }
    if batch.uniq_req is not None and batch.pod_req_id is not None:
        del arrays["pod_req"]
        arrays["uniq_req"] = np.asarray(batch.uniq_req)
        arrays["pod_req_id"] = np.asarray(batch.pod_req_id)
    arrays["n_pods"] = np.asarray(int(batch.n_pods))
    arrays["n_max"] = np.asarray(int(n_max))
    if assignment is not None:
        arrays["assignment"] = np.asarray(assignment)
    return arrays


class DecisionLog:
    """Capped decision ring: bounded in-memory deque always, an on-disk
    flight-recorder-style ring when ``directory`` is set."""

    def __init__(
        self,
        directory: str = "",
        cap: int = DEFAULT_CAP,
        memory_cap: int = DEFAULT_MEMORY_CAP,
        clock=time.time,
        write_interval: float = DEFAULT_WRITE_INTERVAL_S,
    ):
        self.directory = directory
        self.cap = cap
        self.clock = clock
        self.write_interval = write_interval
        self._last_enqueue_mono = -float("inf")  # guarded-by: self._lock
        self.records_written = 0
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=memory_cap)  # guarded-by: self._lock
        self._seq = 0  # guarded-by: self._lock
        # consecutive-failure tracker: pod key -> {count, reason, message,
        # decision_id, namespace, name}  # guarded-by: self._lock
        self._failing: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._last_id_by_provisioner: Dict[str, str] = {}  # guarded-by: self._lock
        # what the unschedulable gauge currently shows, by reason: only
        # CHANGED series re-publish (a steady state pays zero prometheus
        # child lookups per round)
        self._gauge_shown: Dict[str, int] = {}  # guarded-by: self._lock
        # async persistence: the writer thread owns every disk touch
        # (serialize replay blob, tmp+rename, prune) so record_round's
        # hot-path cost is build + enqueue
        self._write_cond = threading.Condition(self._lock)
        self._write_queue: deque = deque()  # guarded-by: self._lock
        self._writes_inflight = 0  # guarded-by: self._lock
        self._writer: Optional[threading.Thread] = None  # guarded-by: self._lock
        # set by close(): the writer drains the queue and EXITS — a
        # replaced log (configure_decisions, tests) must not strand an
        # immortal once-a-second thread pinning its memory ring
        self._closed = False  # guarded-by: self._lock
        if directory:
            # best-effort, like every write below: an uncreatable dir
            # degrades to memory-only, never a boot failure — and the
            # degradation is REAL (directory cleared), so no writer
            # thread spins failing one write per interval forever
            try:
                os.makedirs(directory, exist_ok=True)
            except OSError:
                logger.warning(
                    "decision dir %s not writable; memory-only ring", directory
                )
                self.directory = ""

    # -- recording -----------------------------------------------------------

    def record_round(
        self,
        provisioner: str,
        pods,
        nodes,
        context: Optional[Dict[str, Any]] = None,
        trace_id: str = "",
        state: Optional[Dict[str, Any]] = None,
        admission_failures: Optional[List[Dict[str, str]]] = None,
    ) -> Optional[Dict[str, Any]]:
        """Record one provisioning round. NEVER raises and never fails the
        round — a broken disk loses audit detail, not scheduling. Returns
        the record (or None when disabled / the builder itself broke)."""
        if not enabled():
            return None
        try:
            return self._record_round(
                provisioner, pods, nodes, context or {}, trace_id,
                state or {}, admission_failures or [],
            )
        except Exception:
            logger.debug("decision record build failed", exc_info=True)
            self._count_drop("error")
            return None

    def _record_round(
        self, provisioner, pods, nodes, context, trace_id, state,
        admission_failures,
    ) -> Dict[str, Any]:
        t0 = time.perf_counter()
        with self._lock:
            self._seq += 1
            seq = self._seq
        batch = context.get("batch")
        assignment = context.get("assignment")
        unschedulable: List[Dict[str, Any]] = []
        if batch is not None and assignment is not None:
            # the assignment names the unplaced pods directly — no
            # whole-batch key scan on the hot path (10k f-string key
            # derivations per round would alone blow the <1% bar)
            a = np.asarray(assignment).reshape(-1)[: batch.n_pods]
            unplaced_idx = [int(i) for i in np.flatnonzero(a < 0)]
            unplaced_keys = [batch.pods[i].key for i in unplaced_idx]
            from karpenter_tpu.solver import explain as expl

            # streak-aware reuse: a pod mid-failure-streak keeps its
            # verdict from the round that started the streak (it resets on
            # placement, and a periodic refresh re-derives it in case the
            # catalog/constraints moved underneath) — re-attributing 50
            # stuck pods every round would alone approach the <1% budget
            with self._lock:
                known = {
                    k: f["verdict"] for k, f in self._failing.items()
                    if f.get("verdict") is not None
                }
            refresh = (seq % VERDICT_REFRESH_ROUNDS) == 0
            # template grouping for the fresh ones: unplaced pods sharing
            # (signature, request, hostname state) share one verdict —
            # attribute the group once, stamp each pod's key on a copy
            sig_arr = np.asarray(batch.pod_open_sig)
            rid_arr = (
                np.asarray(batch.pod_req_id)
                if batch.pod_req_id is not None else None
            )
            oh_arr = np.asarray(batch.pod_open_host)
            host_arr = np.asarray(batch.pod_host)
            group_cache: Dict[Any, Dict[str, Any]] = {}
            for i, key in zip(unplaced_idx, unplaced_keys):
                if len(unschedulable) >= MAX_UNSCHEDULABLE:
                    break
                if not refresh:
                    cached = known.get(key)
                    if cached is not None:
                        unschedulable.append(cached)
                        continue
                gk = (
                    int(sig_arr[i]),
                    int(rid_arr[i]) if rid_arr is not None else i,
                    int(oh_arr[i]),
                    # the hostname id: two pods pinning DIFFERENT
                    # hostnames must not share one verdict — the
                    # hostname_poisoned annotation is per-pin
                    int(host_arr[i]),
                )
                core = group_cache.get(gk)
                if core is None:
                    core = group_cache[gk] = expl.explain_pod(batch, i)
                    unschedulable.append(core)
                else:
                    unschedulable.append({**core, "pod": key})
        else:
            # no tensor context (FFD route / solver: ffd): fall back to
            # the key-set difference — these rounds have no attribution
            placed_keys = {p.key for node in nodes for p in node.pods}
            unplaced_keys = [
                p.key for p in pods if p.key not in placed_keys
            ]
        for af in admission_failures:
            if len(unschedulable) < MAX_UNSCHEDULABLE:
                unschedulable.append(af)

        rec_id = f"d-{os.urandom(8).hex()}"
        record: Dict[str, Any] = {
            "id": rec_id,
            "recorded_at": self.clock(),
            "provisioner": provisioner,
            "trace_id": trace_id,
            "route": context.get("route"),
            "transport": context.get("transport"),
            "solver_address": context.get("address"),
            "session_key": context.get("session_key"),
            "state": state,
            "pods_considered": len(pods),
            "nodes": len(nodes),
            "unschedulable_count": len(unplaced_keys) + len(admission_failures),
            "unschedulable": unschedulable,
            # packing/pod-key listings materialize LAZILY (first read or
            # the async writer): deriving hundreds of pod keys per round
            # on the hot path would alone blow the <1% explain budget.
            # The refs are to post-solve objects nothing mutates.
            "_pods": list(pods[:MAX_POD_KEYS]),
            "_nodes": list(nodes[:MAX_PACKING_NODES]),
        }
        explain_s = time.perf_counter() - t0
        record["explain_s"] = round(explain_s, 6)

        self._enqueue_write(record, batch, assignment, context.get("n_max"), seq)
        with self._lock:
            self._records.append(record)
            self._last_id_by_provisioner[provisioner] = rec_id
            # streak bookkeeping: an unplaced pod extends its consecutive-
            # failure run; a TRACKED pod that was in this batch but not
            # unplaced must have placed — reset it. The reset scan runs
            # only while such candidates exist (the failing table is tiny
            # and usually all still failing), so a healthy steady state
            # never pays a whole-batch key walk.
            by_key = {v["pod"]: v for v in unschedulable if "pod" in v}
            unplaced_set = set(unplaced_keys)
            hits = {
                k for k in self._failing
                if k not in unplaced_set
            }
            if hits:
                for p in pods:
                    k = p.key
                    if k in hits:
                        self._failing.pop(k, None)
                        hits.discard(k)
                        if not hits:
                            break
            for k in unplaced_keys:
                self._bump_failure_locked(k, by_key.get(k), rec_id)
            for af in admission_failures:
                k = af.get("pod")
                if k:
                    self._bump_failure_locked(k, af, rec_id)
        try:
            from karpenter_tpu import metrics

            metrics.DECISIONS_RECORDED.inc()
            metrics.DECISION_EXPLAIN_DURATION.observe(explain_s)
            self._publish_unschedulable_gauge()
        except Exception:
            pass  # trimmed registries
        return record

    def _materialize(self, record: Dict[str, Any], copy: bool = False) -> Dict[str, Any]:
        """Turn the deferred node/pod refs into the serializable
        ``packing`` / ``pod_keys`` listings. Idempotent; runs under the
        log lock so concurrent readers and the writer agree.
        ``copy=True`` returns a shallow copy taken UNDER the lock — what
        readers must serialize, because the async writer later inserts
        ``path`` into the live dict and a json.dumps iterating it at that
        moment would see the dict change size."""
        with self._lock:
            nodes = record.pop("_nodes", None)
            pods = record.pop("_pods", None)
            if nodes is not None:
                record["packing"] = [
                    {
                        "instance_type": (
                            node.instance_type_options[0].name
                            if node.instance_type_options else None
                        ),
                        "surviving_types": len(node.instance_type_options),
                        "pods": [
                            p.key for p in node.pods[:MAX_NODE_POD_KEYS]
                        ],
                        "pod_count": len(node.pods),
                    }
                    for node in nodes
                ]
            if pods is not None:
                record["pod_keys"] = [p.key for p in pods]
            return dict(record) if copy else record

    def _bump_failure_locked(self, key, verdict, rec_id) -> None:
        cur = self._failing.get(key)
        count = (cur["count"] if cur else 0) + 1
        reason = (verdict or {}).get("top_reason") or (cur or {}).get(
            "reason"
        ) or "unknown"
        message = (verdict or {}).get("message") or (cur or {}).get(
            "message"
        ) or "no placement found"
        self._failing[key] = {
            "count": count, "reason": reason, "message": message,
            "decision_id": rec_id,
            # monotonic freshness stamp: entries that stop being bumped
            # (the pod was deleted while stuck) expire after STREAK_TTL_S
            "bumped_mono": time.monotonic(),
            # the full verdict rides the streak so later rounds (and the
            # explain endpoint) reuse it instead of re-attributing
            "verdict": (
                verdict if verdict is not None
                else (cur or {}).get("verdict")
            ),
        }
        self._failing.move_to_end(key)
        while len(self._failing) > MAX_TRACKED_PODS:
            self._failing.popitem(last=False)

    def _expire_stale_locked(self) -> None:
        """Drop streak entries whose pod stopped appearing in batches
        long ago (deleted/evicted while stuck) — without this the gauge
        and the event loop would track ghosts forever."""
        horizon = time.monotonic() - STREAK_TTL_S
        stale = [
            k for k, v in self._failing.items()
            if v.get("bumped_mono", horizon) < horizon
        ]
        for k in stale:
            self._failing.pop(k, None)

    def _publish_unschedulable_gauge(self) -> None:
        from karpenter_tpu import metrics

        with self._lock:
            self._expire_stale_locked()
            counts: Dict[str, int] = {}
            for v in self._failing.values():
                counts[v["reason"]] = counts.get(v["reason"], 0) + 1
            # delta publication: only series whose value moved (incl. a
            # drained reason dropping to 0) touch the registry
            changed = {
                reason: counts.get(reason, 0)
                for reason in set(counts) | set(self._gauge_shown)
                if counts.get(reason, 0) != self._gauge_shown.get(reason)
            }
            self._gauge_shown = counts
        for reason, value in changed.items():
            metrics.PODS_UNSCHEDULABLE.labels(reason=reason).set(value)

    def _count_drop(self, reason: str) -> None:
        try:
            from karpenter_tpu import metrics

            metrics.DECISIONS_DROPPED.labels(reason=reason).inc()
        except Exception:
            pass

    def record_consolidation(
        self,
        provisioner: str,
        victims: List[str],
        keep: int,
        moves: int,
        savings: float,
        context: Optional[Dict[str, Any]] = None,
        trace_id: str = "",
    ) -> Optional[Dict[str, Any]]:
        """Record one consolidation wave decision: which nodes the re-pack
        retires, how many it left untouched (the minimal-move objective's
        receipt), how many pod moves the wave costs, and the hourly
        savings that justify it. The record id is what the wave's journal
        entry and every wave/move event carry — `/decisions/<id>` answers
        "why is consolidation draining my node". Same contract as
        ``record_round``: NEVER raises, never fails the wave."""
        if not enabled():
            return None
        try:
            with self._lock:
                self._seq += 1
                seq = self._seq
            rec_id = f"d-{os.urandom(8).hex()}"
            record: Dict[str, Any] = {
                "id": rec_id,
                "recorded_at": self.clock(),
                "provisioner": provisioner,
                "trace_id": trace_id,
                "kind": "consolidation",
                "route": (context or {}).get("route"),
                "state": {
                    "victims": list(victims),
                    "kept_nodes": int(keep),
                    "moves": int(moves),
                    "savings_per_hour": float(savings),
                    **{
                        k: v for k, v in (context or {}).items()
                        if k not in ("batch", "assignment", "n_max")
                    },
                },
                "pods_considered": int(moves),
                "nodes": len(victims) + int(keep),
                "unschedulable_count": 0,
                "unschedulable": [],
                "_pods": [],
                "_nodes": [],
            }
            self._enqueue_write(record, None, None, None, seq)
            with self._lock:
                self._records.append(record)
                self._last_id_by_provisioner[provisioner] = rec_id
            return record
        except Exception:
            logger.debug("consolidation decision record failed", exc_info=True)
            self._count_drop("error")
            return None

    def _enqueue_write(self, record, batch, assignment, n_max, seq) -> None:
        """Hand the record to the writer thread. The hot path pays only
        this enqueue; a full queue drops the write (counted), never blocks
        or fails the round. Disk persistence is interval-thinned (the
        in-memory ring keeps every record)."""
        if not self.directory:
            return
        with self._lock:
            if self._closed:
                return
            now = time.monotonic()
            if now - self._last_enqueue_mono < self.write_interval:
                return  # thinning, not loss: the memory ring has it
            self._last_enqueue_mono = now
            if len(self._write_queue) >= MAX_WRITE_QUEUE:
                dropped = True
            else:
                dropped = False
                self._write_queue.append((record, batch, assignment, n_max, seq))
                if self._writer is None or not self._writer.is_alive():
                    self._writer = threading.Thread(
                        target=self._writer_loop,
                        name="karpenter-decision-writer", daemon=True,
                    )
                    # started under the lock (the probe/canary discipline:
                    # is_alive() is False for an assigned-but-unstarted
                    # thread, so a concurrent enqueue could double-spawn)
                    self._writer.start()
                self._write_cond.notify_all()
        if dropped:
            self._count_drop("queue_full")

    def _writer_loop(self) -> None:
        while True:
            with self._lock:
                while not self._write_queue:
                    if self._closed:
                        return  # drained + closed: the thread ends
                    self._write_cond.wait(timeout=1.0)
                entry = self._write_queue.popleft()
                self._writes_inflight += 1
            try:
                self._write_now(*entry)
            finally:
                with self._lock:
                    self._writes_inflight -= 1
                    self._write_cond.notify_all()

    def close(self) -> None:
        """Stop the writer thread after it drains the queue. A closed log
        still serves its memory ring; new disk writes are refused."""
        with self._lock:
            self._closed = True
            self._write_cond.notify_all()

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait for queued disk writes to land (tests, clean shutdown).
        True when the queue drained in time."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._write_queue or self._writes_inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._write_cond.wait(timeout=min(left, 0.5))
        return True

    def _write_now(self, record, batch, assignment, n_max, seq) -> Optional[str]:
        """Best-effort on-disk persistence; a failed write (full/read-only
        disk) drops THIS record's files, never the round or the in-memory
        copy. Runs on the writer thread. The replay tensors land as an
        ``.npz`` sidecar next to the record json (numpy's C serializer —
        the writer shares the GIL with live solves, so json-encoding
        megabytes of base64 here would tax them)."""
        try:
            payload = dict(self._materialize(record))
            stem = (
                f"decision-{int(self.clock() * 1e3):013d}"
                f"-{seq % 1_000_000:06d}-{record['id'][2:10]}"
            )
            path = os.path.join(self.directory, f"{stem}.json")
            if batch is not None and n_max:
                npz_tmp = os.path.join(
                    self.directory, f"{stem}.npz.{os.getpid()}.tmp"
                )
                npz_path = os.path.join(self.directory, f"{stem}.npz")
                with open(npz_tmp, "wb") as f:
                    np.savez(f, **_replay_arrays(batch, assignment, n_max))
                os.replace(npz_tmp, npz_path)
                payload["replay_file"] = f"{stem}.npz"
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
            with self._lock:
                self.records_written += 1
                record["path"] = path
            # prune OUTSIDE the lock: listdir + unlinks on a slow disk
            # must not stall record_round's seq/enqueue/streak bookkeeping
            # (only this writer thread ever prunes, so no racing sweeps)
            self._prune()
            return path
        except Exception:
            logger.debug("decision record write failed", exc_info=True)
            self._count_drop("write_failed")
            return None

    def _prune(self) -> None:
        names = sorted(
            n for n in os.listdir(self.directory)
            if n.startswith("decision-") and n.endswith(".json")
        )
        evicted = 0
        for victim in names[: max(len(names) - self.cap, 0)]:
            try:
                os.remove(os.path.join(self.directory, victim))
                evicted += 1
            except OSError:
                pass
            try:
                os.remove(os.path.join(
                    self.directory, victim[: -len(".json")] + ".npz"
                ))
            except OSError:
                pass  # record had no replay sidecar
        if evicted:
            self._count_drop_n("evicted", evicted)

    def _count_drop_n(self, reason: str, n: int) -> None:
        try:
            from karpenter_tpu import metrics

            metrics.DECISIONS_DROPPED.labels(reason=reason).inc(n)
        except Exception:
            pass

    # -- the admission/selection feed ---------------------------------------

    def note_admission_failure(
        self, pod, errors: List[str], provisioner: str = ""
    ) -> Dict[str, str]:
        """Selection-level rejection (no provisioner admitted the pod):
        classify the dimension — taint intolerance vs requirement — and
        extend the pod's consecutive-failure streak outside any solve."""
        from karpenter_tpu.solver.explain import REASON_REQUIREMENT, REASON_TAINT

        msg = "; ".join(errors)[:400] if errors else "no provisioner admitted"
        reason = REASON_TAINT if "tolerate" in msg else REASON_REQUIREMENT
        verdict = {"pod": pod.key, "top_reason": reason, "message": msg}
        with self._lock:
            rec_id = self._last_id_by_provisioner.get(provisioner, "")
            self._bump_failure_locked(pod.key, verdict, rec_id)
        try:
            self._publish_unschedulable_gauge()
        except Exception:
            pass
        return verdict

    # -- the Kubernetes loop -------------------------------------------------

    def _emit_one(self, recorder, key: str, v: Dict[str, Any], threshold: int):
        """One PodUnschedulable Warning. The message is deliberately
        STABLE across rounds (no streak count in it): EventRecorder
        aggregates on the message, so repeats bump the existing Event's
        count instead of minting a fresh apiserver object per round —
        embedding the incrementing count would turn one stuck pod into an
        event storm."""
        namespace, _, name = key.partition("/")
        return recorder.event(
            "Pod", name or key,
            "PodUnschedulable",
            f"pod unschedulable for {threshold}+ consecutive round(s): "
            f"{v['message']} (top reason: {v['reason']}; "
            "GET /debug/explain?pod=<name> has the per-candidate "
            "breakdown)",
            type="Warning",
            namespace=namespace if name else "",
            decision_id=v["decision_id"],
        )

    def emit_unschedulable_events(
        self, cluster, threshold: int = DEFAULT_EVENT_ROUNDS
    ) -> int:
        """Emit a ``PodUnschedulable`` Warning event for every pod whose
        consecutive-failure streak reached ``threshold``, carrying the top
        elimination reason in the message and the decision id in the
        ``karpenter.sh/decision-id`` annotation. Runs ONCE PER ROUND (the
        provisioning worker's seam); per-pod feeds use
        :meth:`maybe_emit_for`. Never raises."""
        try:
            with self._lock:
                self._expire_stale_locked()
                due = [
                    (k, dict(v)) for k, v in self._failing.items()
                    if v["count"] >= threshold
                ]
            if not due:
                return 0
            from karpenter_tpu.kube.events import recorder_for

            recorder = recorder_for(cluster)
            emitted = 0
            for key, v in due:
                # authoritative existence check: a pod deleted while stuck
                # never re-enters a batch to reset its streak — drop the
                # ghost instead of eventing a nonexistent object per round
                namespace, _, name = key.partition("/")
                if name and cluster.try_get("pods", name, namespace) is None:
                    with self._lock:
                        self._failing.pop(key, None)
                    continue
                if self._emit_one(recorder, key, v, threshold) is not None:
                    emitted += 1
            return emitted
        except Exception:
            logger.debug("unschedulable event emission failed", exc_info=True)
            return 0

    def maybe_emit_for(
        self, cluster, pod_key: str, threshold: int = DEFAULT_EVENT_ROUNDS
    ) -> bool:
        """The per-pod twin: emit for THIS pod only when its streak is
        due. Selection's admission feed runs once per rejected pod, and a
        whole-table sweep there would be O(rejected x failing) apiserver
        writes per selection pass. Never raises."""
        try:
            with self._lock:
                v = self._failing.get(pod_key)
                if v is None or v["count"] < threshold:
                    return False
                v = dict(v)
            from karpenter_tpu.kube.events import recorder_for

            return self._emit_one(
                recorder_for(cluster), pod_key, v, threshold
            ) is not None
        except Exception:
            logger.debug("unschedulable event emission failed", exc_info=True)
            return False

    # -- read surface --------------------------------------------------------

    def recent(
        self, limit: int = 20, provisioner: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        with self._lock:
            records = list(self._records)
        records.reverse()  # newest first
        if provisioner:
            records = [r for r in records if r["provisioner"] == provisioner]
        return [self._materialize(r, copy=True) for r in records[:limit]]

    def summaries(self, limit: int = 16) -> List[Dict[str, Any]]:
        """The bounded per-member index the telemetry plane flushes — a
        dead replica's decisions survive in /debug/fleet through these."""
        out = []
        for r in self.recent(limit=limit):
            out.append({
                "id": r["id"],
                "recorded_at": r["recorded_at"],
                "provisioner": r["provisioner"],
                "trace_id": r["trace_id"],
                "route": r.get("route"),
                "pods_considered": r["pods_considered"],
                "nodes": r["nodes"],
                "unschedulable_count": r["unschedulable_count"],
                "top_reasons": sorted({
                    v.get("top_reason") for v in r.get("unschedulable", [])
                    if v.get("top_reason")
                }),
            })
        return out

    def explain(self, pod: str) -> Optional[Dict[str, Any]]:
        """The ``/debug/explain?pod=`` body: the newest record mentioning
        the pod (by key or bare name), with its verdict — per-candidate
        breakdown for an unplaced pod, the chosen placement otherwise."""
        with self._lock:
            records = list(self._records)
        for r in reversed(records):
            self._materialize(r)
            verdict = next(
                (
                    v for v in r.get("unschedulable", [])
                    if v.get("pod") == pod
                    or v.get("pod", "").rpartition("/")[2] == pod
                ),
                None,
            )
            if verdict is not None:
                out = {
                    "decision_id": r["id"],
                    "recorded_at": r["recorded_at"],
                    "provisioner": r["provisioner"],
                    "trace_id": r["trace_id"],
                    "route": r.get("route"),
                    "placed": False,
                    **verdict,
                }
                with self._lock:
                    streak = self._failing.get(verdict.get("pod", pod))
                if streak:
                    out["consecutive_failures"] = streak["count"]
                return out
            for node in r.get("packing", []):
                for k in node["pods"]:
                    if k == pod or k.rpartition("/")[2] == pod:
                        return {
                            "decision_id": r["id"],
                            "recorded_at": r["recorded_at"],
                            "provisioner": r["provisioner"],
                            "trace_id": r["trace_id"],
                            "route": r.get("route"),
                            "placed": True,
                            "pod": k,
                            "instance_type": node["instance_type"],
                            "surviving_types": node["surviving_types"],
                        }
        return None

    def failure_streak(self, pod_key: str) -> int:
        with self._lock:
            v = self._failing.get(pod_key)
            return v["count"] if v else 0

    def last_decision_id(self, provisioner: str) -> str:
        with self._lock:
            return self._last_id_by_provisioner.get(provisioner, "")

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._failing.clear()
            self._last_id_by_provisioner.clear()
